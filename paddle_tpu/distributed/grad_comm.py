"""Explicit gradient-communication layer (ref: fleet sharding stage 1/2 +
DGC comm knobs; papers: "Automatic Cross-Replica Sharding of Weight Update
in Data-Parallel Training" arXiv:2004.13336, "EQuARX: Efficient Quantized
AllReduce in XLA" arXiv:2506.17615).

The default TrainStep hands gradient communication to GSPMD: full-precision
all-reduce of every gradient plus a replicated weight update. This module
makes the schedule explicit so it can be (a) halved — reduce-scatter the
grads, update each replica's 1/n shard, all-gather the params (weight-update
sharding, i.e. ZeRO-1 done as the paper does it), and (b) compressed —
bf16/int8 wire dtypes with fp32 accumulation on the receive side.

Layout: every parameter's flat gradient is zero-padded to a multiple of the
axis size n and viewed as (n, cols); same-dtype params concatenate along the
column axis into buckets of ~FLAGS_grad_bucket_bytes, so collectives are few
and large. `psum_scatter` over the leading axis hands replica r exactly row
r — the concatenation of its flat shard of every member param — which maps
back to per-param shards by column offset. The fused optimizer rule (any
elementwise `Optimizer._update`) applies unchanged to the shards: slicing a
flat view commutes with an elementwise update, so shard-then-update is
bitwise shard-of-update.

Quantized reduce (bf16/int8) cannot use `psum_scatter` directly — XLA would
accumulate in the wire dtype. Instead the (n, cols) bucket is quantized
row-wise (per 2048-element chunk scales for int8), exchanged with one
`all_to_all` (wire bytes at the compressed dtype), then dequantized and
summed locally in fp32 — the accumulation-precision trick EQuARX applies
inside its fused stages.

Everything here is trace-time Python + lax collectives; the byte counters
are computed statically from the bucket plan (the schedule is static per
compiled step) and recorded per executed step for
`paddle_tpu.profiler.comm_counters()`.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

logger = logging.getLogger(__name__)

# per-chunk scale granularity for the int8 wire format (EQuARX quantizes in
# chunks so one outlier only flattens its own chunk's resolution)
INT8_CHUNK = 2048


def _int8_chunking(cols):
    """(chunk, n_chunks, padded_cols) for an int8 row of `cols` elements.
    The chunk shrinks to the row for small buckets so chunk padding never
    exceeds the payload."""
    chunk = max(1, min(INT8_CHUNK, cols))
    nch = -(-cols // chunk)
    return chunk, nch, nch * chunk

_WIRE_DTYPES = {
    "float32": None, "fp32": None, None: None,
    "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
    "int8": jnp.int8,
}


# ---------------------------------------------------------------------------
# bucket plan


@dataclass(frozen=True)
class _Entry:
    name: str
    shape: tuple
    dtype: object
    size: int          # true element count
    cols: int          # padded size // n
    bucket: int        # bucket index
    offset: int        # column offset inside the bucket


@dataclass(frozen=True)
class _Bucket:
    index: int
    dtype: object
    names: tuple
    cols: int          # total columns


class BucketPlan:
    """Static flat-buffer layout of a parameter dict over an axis of size n."""

    def __init__(self, n, entries, buckets):
        self.n = n
        self.entries = entries      # dict name -> _Entry
        self.buckets = buckets      # list[_Bucket]

    @staticmethod
    def build(params, n, bucket_bytes):
        """params: dict name -> array (order defines packing order)."""
        by_dtype = {}
        for name, a in params.items():
            by_dtype.setdefault(jnp.dtype(a.dtype), []).append((name, a))
        entries, buckets = {}, []
        for dtype, items in by_dtype.items():
            itemsize = dtype.itemsize
            cur_names, cur_cols = [], 0
            for name, a in items:
                size = int(np.prod(a.shape)) if a.shape else 1
                cols = -(-size // n)  # ceil
                if cur_names and (cur_cols + cols) * n * itemsize > bucket_bytes:
                    buckets.append(_Bucket(len(buckets), dtype,
                                           tuple(cur_names), cur_cols))
                    cur_names, cur_cols = [], 0
                entries[name] = _Entry(name, tuple(int(s) for s in a.shape),
                                       dtype, size, cols, len(buckets),
                                       cur_cols)
                cur_names.append(name)
                cur_cols += cols
            if cur_names:
                buckets.append(_Bucket(len(buckets), dtype, tuple(cur_names),
                                       cur_cols))
        return BucketPlan(n, entries, buckets)

    def fingerprint(self):
        """Stable short digest of the packed layout: axis size plus every
        entry's (name, shape, dtype, size, cols, bucket, offset) and every
        bucket's boundary. Two plans with equal fingerprints pack every
        grad/slot byte identically — the topology metadata checkpoints
        carry (TrainStep.topology()) so a mismatched load can be named
        instead of failing in a reshape."""
        import hashlib
        ent = sorted((e.name, e.shape, str(jnp.dtype(e.dtype)), e.size,
                      e.cols, e.bucket, e.offset)
                     for e in self.entries.values())
        bks = [(b.index, str(jnp.dtype(b.dtype)), b.names, b.cols)
               for b in self.buckets]
        return hashlib.sha1(repr((self.n, ent, bks)).encode()).hexdigest()[:16]

    # -- static byte accounting (per-device wire traffic) --------------------
    def payload_bytes(self):
        return sum(e.size * e.dtype.itemsize for e in self.entries.values())

    def padded_bytes(self, wire_dtype=None):
        return sum(b.cols * self.n *
                   jnp.dtype(wire_dtype or b.dtype).itemsize
                   for b in self.buckets)

    def reduce_record(self, wire_dtype, two_sided=False, fixed16=False):
        """Wire bytes + collective count of one reduce pass. A ring
        reduce-scatter moves (n-1)/n of the buffer per device; the explicit
        all-reduce schedule (two_sided=True) is RS + grad all-gather and
        moves twice that — which is exactly ring all-reduce's cost.
        `fixed16` (the composed fused backend's bf16-width wire) counts
        int16 scatter rows plus the fp32 scale all-reduce."""
        n = self.n
        frac = (n - 1) / n
        by_dtype, coll = {}, 0
        for b in self.buckets:
            wd = wire_dtype if (wire_dtype is not None and
                                jnp.issubdtype(b.dtype, jnp.floating)) else None
            eff = jnp.dtype(wd or b.dtype)
            cols = b.cols
            key = str(eff)
            if fixed16 and wd is jnp.bfloat16:
                _, nch, cols = _int8_chunking(b.cols)
                key, eff = "int16", jnp.dtype(jnp.int16)
                # the shared-scale bound is a psum (ring AR: 2(n-1)/n) of
                # the (n, nch) fp32 absmaxes
                by_dtype["float32"] = by_dtype.get("float32", 0) + int(
                    2 * frac * n * nch * 4)
                coll += 1
            elif wd is jnp.int8:
                _, nch, cols = _int8_chunking(b.cols)  # chunk-padded rows
                by_dtype["float32"] = by_dtype.get("float32", 0) + int(
                    n * nch * 4 * frac)          # per-chunk scales
                coll += 1                        # extra scale all_to_all
            by_dtype[key] = by_dtype.get(key, 0) + int(
                cols * n * eff.itemsize * frac)
            coll += 1
            if two_sided:  # the gather half of the explicit all-reduce
                gb = int(b.cols * n * jnp.dtype(b.dtype).itemsize * frac)
                by_dtype[str(jnp.dtype(b.dtype))] = by_dtype.get(
                    str(jnp.dtype(b.dtype)), 0) + gb
                coll += 1
        return by_dtype, coll

    def gather_record(self, emulated=False):
        """Ring all-gather moves (n-1)/n of the buffer; the psum-emulated
        gather of the mp-composed schedule (see all_gather_shards) is a
        ring all-reduce of the full buffer — exactly twice that."""
        n = self.n
        frac = (n - 1) / n * (2 if emulated else 1)
        total = sum(int(b.cols * n * jnp.dtype(b.dtype).itemsize * frac)
                    for b in self.buckets)
        return total, len(self.buckets)


# ---------------------------------------------------------------------------
# traced packing / collectives (called inside shard_map)


def _pack_bucket(plan, bucket, tree):
    parts = []
    for name in bucket.names:
        e = plan.entries[name]
        flat = tree[name].reshape(-1)
        pad = e.cols * plan.n - e.size
        if pad:
            flat = jnp.pad(flat, (0, pad))
        parts.append(flat.reshape(plan.n, e.cols))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)


def _split_row(plan, bucket, row):
    out = {}
    for name in bucket.names:
        e = plan.entries[name]
        out[name] = row[e.offset:e.offset + e.cols]
    return out


def _fixed16_reduce_row(x, axis, idx):
    """(n, cols) local bucket -> this replica's reduced row (cols,) fp32
    over an int16 fixed-point wire — the partial-manual-safe realization
    of the fused backend's compressed (bf16-width, 0.5x fp32 bytes) wire
    for the dp x mp composed step, where jax 0.4.x can partition neither
    `all_to_all` nor an in-kernel remote DMA.

    Per INT8_CHUNK-element chunk the psum of per-replica absmaxes bounds
    the accumulated magnitude, so with scale = bound / (32767 - n) the
    int16 `psum_scatter` accumulation cannot overflow even with per-value
    rounding of up to 0.5 — integer accumulation is EXACT, and the single
    fp32 dequantization at the destination is the only lossy step
    (>= 12-bit effective mantissa at n <= 8 vs bf16's 8). `idx` is the
    replica index operand (lax.axis_index aborts the partitioner here)."""
    n, cols = x.shape
    chunk, nch, padded = _int8_chunking(cols)
    xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (0, padded - cols)))
    xc = xp.reshape(n, nch, chunk)
    absmax = jnp.max(jnp.abs(xc), axis=-1)           # (n, nch)
    bound = lax.psum(absmax, axis)                   # identical on replicas
    scale = bound / float(32767 - n)
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.round(xc * inv[..., None]).astype(jnp.int16)
    qs = lax.psum_scatter(q.reshape(n, padded), axis,
                          scatter_dimension=0, tiled=True).reshape(-1)
    srow = lax.dynamic_index_in_dim(scale, idx, keepdims=False)   # (nch,)
    deq = qs.reshape(nch, chunk).astype(jnp.float32) * srow[:, None]
    return deq.reshape(padded)[:cols]


def _quantized_reduce_row(x, axis, wire_dtype):
    """(n, cols) local bucket -> this replica's reduced row (cols,) fp32.

    Row j is destined for replica j: one all_to_all moves every row to its
    owner at the wire dtype; the owner dequantizes and accumulates in fp32."""
    n, cols = x.shape
    if wire_dtype is jnp.int8:
        chunk, _, padded = _int8_chunking(cols)
        xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (0, padded - cols)))
        xc = xp.reshape(n, -1, chunk)
        scale = jnp.max(jnp.abs(xc), axis=-1) / 127.0          # (n, nch)
        inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
        q = jnp.clip(jnp.round(xc * inv[..., None]), -127, 127
                     ).astype(jnp.int8)
        qr = lax.all_to_all(q.reshape(n, padded), axis,
                            split_axis=0, concat_axis=0)
        sr = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0)
        deq = qr.reshape(n, -1, chunk).astype(jnp.float32) * sr[..., None]
        return deq.sum(axis=0).reshape(padded)[:cols]
    y = lax.all_to_all(x.astype(wire_dtype), axis, split_axis=0, concat_axis=0)
    return y.astype(jnp.float32).sum(axis=0)


def reduce_scatter_grads(plan, grads, axis, wire_dtype, denom=1, meta=None,
                         fixed16=False, idx=None):
    """Local per-replica grads -> this replica's flat shard of the MEAN
    gradient, {name: (cols,)}. Routes, per bucket:
      * `meta` set (fused backend, single-axis mesh): the Pallas ring-RS
        kernel (`fused_rs_bucket`) whose epilogue compresses each hop's
        traveling accumulator to the bf16 wire and accumulates fp32 —
        fp32 and bf16 wires; the int8 wire keeps the all_to_all exchange;
      * `fixed16` (fused backend, dp x mp composed step, bf16 wire): the
        int16 fixed-point psum_scatter (`_fixed16_reduce_row`, needs the
        `idx` replica-index operand);
      * otherwise psum_scatter at full precision / quantized all_to_all
        exchange (non-float buckets always full precision)."""
    shards = {}
    for b in plan.buckets:
        x = _pack_bucket(plan, b, grads)
        wd = wire_dtype if (wire_dtype is not None and
                            jnp.issubdtype(b.dtype, jnp.floating)) else None
        is_float = jnp.issubdtype(b.dtype, jnp.floating)
        if meta is not None and is_float and wd is not jnp.int8:
            from ..ops.pallas_kernels import fused_collectives as _fc
            row = _fc.fused_rs_bucket(meta, x, wd)
        elif fixed16 and is_float and wd is jnp.bfloat16:
            row = _fixed16_reduce_row(x, axis, idx)
        elif wd is None:
            row = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True
                                   ).reshape(-1)
        else:
            row = _quantized_reduce_row(x, axis, wd)
        if denom != 1:
            row = row / denom
        row = row.astype(b.dtype) if jnp.issubdtype(b.dtype, jnp.floating) \
            else row
        shards.update(_split_row(plan, b, row))
    return shards


def all_gather_shards(plan, shards, axis, idx=None, meta=None):
    """Per-replica flat shards -> full arrays, {name: shape/dtype of plan}.
    Bucketed: one all_gather per bucket — the Pallas ring-AG kernel under
    the fused backend (`meta` set). With `idx` given (the mp-composed
    partial-manual region, where jax 0.4.x cannot partition `all_gather`),
    the gather is emulated as placement-into-zeros + psum — same result,
    2x the wire bytes of a ring all-gather (the ledger accounts for it)."""
    out = {}
    for b in plan.buckets:
        row = jnp.concatenate([shards[name] for name in b.names]) \
            if len(b.names) > 1 else shards[b.names[0]]
        if meta is not None and idx is None:
            from ..ops.pallas_kernels import fused_collectives as _fc
            full = _fc.fused_ag_bucket(meta, row)              # (n, cols)
        elif idx is None:
            full = lax.all_gather(row, axis, tiled=False)      # (n, cols)
        else:
            full = jnp.zeros((plan.n,) + row.shape, row.dtype)
            full = lax.dynamic_update_slice_in_dim(full, row[None], idx,
                                                   axis=0)
            full = lax.psum(full, axis)
        for name in b.names:
            e = plan.entries[name]
            flat = full[:, e.offset:e.offset + e.cols].reshape(-1)[:e.size]
            out[name] = flat.reshape(e.shape).astype(e.dtype)
    return out


def shard_of(plan, name, arr, idx):
    """This replica's flat shard (cols,) of a replicated full array."""
    e = plan.entries[name]
    flat = arr.reshape(-1)
    pad = e.cols * plan.n - e.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return lax.dynamic_index_in_dim(flat.reshape(plan.n, e.cols), idx,
                                    keepdims=False)


def clip_shards(grad_clip, shards, axis):
    """Gradient clipping computed from flat shards: any norm the clip needs
    is a psum of shard-local partial sums, so no full gradient materializes."""
    if grad_clip is None:
        return shards
    from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                           ClipGradByValue)
    if isinstance(grad_clip, ClipGradByValue):
        lo, hi = grad_clip.min, grad_clip.max
        return {n: jnp.clip(g, lo, hi) for n, g in shards.items()}
    if isinstance(grad_clip, ClipGradByGlobalNorm):
        local = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in shards.values())
        norm = jnp.sqrt(lax.psum(local, axis))
        scale = jnp.minimum(grad_clip.clip_norm / jnp.maximum(norm, 1e-12),
                            1.0)
        return {n: (g * scale).astype(g.dtype) for n, g in shards.items()}
    if isinstance(grad_clip, ClipGradByNorm):
        out = {}
        for n, g in shards.items():
            norm = jnp.sqrt(lax.psum(
                jnp.sum(jnp.square(g.astype(jnp.float32))), axis))
            scale = jnp.minimum(
                grad_clip.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out[n] = (g * scale).astype(g.dtype)
        return out
    raise TypeError(f"unsupported grad clip for grad_comm: {type(grad_clip)}")


# ---------------------------------------------------------------------------
# packed (sharded) slot/accumulator storage


def pack_array(arr, n):
    """Param-shaped array -> (n, cols) packed layout (leading axis shards)."""
    flat = jnp.asarray(arr).reshape(-1)
    size = flat.shape[0] if flat.shape else 1
    cols = -(-size // n)
    pad = cols * n - size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n, cols)


def unpack_array(arr2d, shape, dtype=None):
    size = int(np.prod(shape)) if shape else 1
    flat = jnp.asarray(arr2d).reshape(-1)[:size]
    out = flat.reshape(shape)
    return out.astype(dtype) if dtype is not None else out


def packed_shape(pshape, n):
    return (n, -(-int(np.prod(pshape) or 1) // n))


def _pack_leaf(v, pshape, n):
    """To packed (n, cols); a leaf already packed (restored checkpoint)
    passes through. The `!= pshape` guard keeps a 2D param whose own shape
    happens to equal (n, cols) packable. A leaf packed for a DIFFERENT
    axis size (reshard-on-load: a checkpoint from another mesh restored
    before the first compile) is re-packed — source tail padding stripped,
    destination padding re-applied."""
    if tuple(v.shape) == packed_shape(pshape, n) and tuple(v.shape) != pshape:
        return v
    from . import topology as _rs
    m = _rs.packed_n(np.shape(v), pshape)
    if m is not None and m != n:
        size = int(np.prod(pshape)) if pshape else 1
        _rs.note_leaf_reshard()
        return pack_array(jnp.asarray(v).reshape(-1)[:size], n)
    return pack_array(v, n)


def _unpack_leaf(v, pshape):
    """To param shape; already param-shaped leaves pass through, so this
    safely normalizes a weight-update-sharding checkpoint restored into a
    step running a replicated-update schedule."""
    return v if tuple(v.shape) == tuple(pshape) else unpack_array(v, pshape)


def pack_opt_state(state, params, n):
    return {"step": state["step"],
            "slots": {name: {k: _pack_leaf(v, tuple(params[name].shape), n)
                             for k, v in sl.items()}
                      for name, sl in state["slots"].items()}}


def pack_accum(gacc, params, n):
    return {name: _pack_leaf(a, tuple(params[name].shape), n)
            for name, a in gacc.items()}


def unpack_opt_state(state, params):
    return {"step": state["step"],
            "slots": {name: {k: _unpack_leaf(v, tuple(params[name].shape))
                             for k, v in sl.items()}
                      for name, sl in state["slots"].items()}}


def unpack_accum(gacc, params):
    return {name: _unpack_leaf(a, tuple(params[name].shape))
            for name, a in gacc.items()}


# ---------------------------------------------------------------------------
# config resolution


@dataclass
class GradCommConfig:
    axis: str
    n: int
    weight_update_sharding: bool
    wire_dtype: object            # None (native) | jnp.bfloat16 | jnp.int8
    bucket_bytes: int
    plan: BucketPlan = None
    # mesh axes left in GSPMD-auto mode (the mp composition): the dp
    # schedule binds only its own axis manually and the model's mp
    # collectives/constraints keep working inside
    auto_axes: tuple = ()
    # dp-axis comm backend ('ring' = the explicit lax-collective schedule,
    # 'fused' = Pallas in-kernel rings where eligible) and whether the
    # fused kernels actually run (False on the composed dp x mp step,
    # where only the fixed-point wire realization applies)
    backend: str = "ring"
    fused_kernels: bool = False

    @property
    def fixed16(self):
        """Whether the composed step's bf16 wire rides the int16
        fixed-point psum_scatter (see _fixed16_reduce_row)."""
        return (self.backend == "fused" and bool(self.auto_axes)
                and self.wire_dtype is jnp.bfloat16)


_warned = set()


def _warn_once(key, msg):
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg)


def resolve(mesh, optimizer, opt_state=None, params=None, offload=False,
            param_specs=None):
    """Decide whether the explicit grad-comm schedule applies to this step.

    Returns a GradCommConfig or None (None = keep the default GSPMD
    schedule). Activation, per flags:
      * FLAGS_grad_comm=False       -> never;
      * FLAGS_grad_comm=True/"on"   -> whenever supported (gives the
        explicit allreduce-fp32 baseline its own counters);
      * FLAGS_grad_comm="auto"      -> only when FLAGS_weight_update_sharding
        or a compressed FLAGS_allreduce_dtype asks for a non-default
        schedule (the shipped default: everything off, path unchanged).
    """
    from .. import flags as _flags
    from . import comm_backend
    F = _flags._FLAGS
    req = comm_backend.requested("dp")
    mode = F.get("FLAGS_grad_comm", "auto")
    if mode is False or mode in ("off", "0"):
        if req in ("ring", "fused"):
            _warn_once(("dp-off", req),
                       f"FLAGS_comm_backend='dp={req}' ignored because "
                       f"FLAGS_grad_comm is off — set FLAGS_grad_comm="
                       f"'auto' (or 'on') to activate the explicit dp "
                       f"schedule")
        return None
    wus = bool(F.get("FLAGS_weight_update_sharding", False))
    raw = F.get("FLAGS_allreduce_dtype", "float32")
    if raw not in _WIRE_DTYPES:
        _warn_once(("dtype", raw),
                   f"FLAGS_allreduce_dtype={raw!r} unknown; using float32")
        raw = "float32"
    wire = _WIRE_DTYPES[raw]
    if req == "gspmd":
        if wus or wire is not None:
            _warn_once("dp-gspmd",
                       "FLAGS_comm_backend='dp=gspmd' keeps the GSPMD "
                       "all-reduce schedule, so FLAGS_weight_update_sharding"
                       "/FLAGS_allreduce_dtype are ignored — set "
                       "FLAGS_comm_backend='dp=ring' (or 'dp=fused') to "
                       "activate them")
        return None
    explicit = mode in (True, "on", "1") or req in ("ring", "fused")
    if not explicit and not (wus or wire is not None):
        return None
    if mesh is None:
        return None
    backend = req or "ring"

    def bail(key, msg):
        _warn_once(key, msg + " — falling back to the GSPMD schedule")
        return None

    active = [a for a in mesh.axis_names if mesh.shape.get(a, 1) > 1]
    dp_like = [a for a in active if a in ("dp", "sharding")]
    if not dp_like:
        return None
    others = [a for a in active if a not in dp_like]
    if len(dp_like) > 1 or (others and others != ["mp"]):
        return bail(("axes", tuple(active)),
                    f"grad_comm needs a single active dp/sharding axis "
                    f"(plus at most a tensor-parallel 'mp' axis), "
                    f"mesh has {active} — set the other axes to 1")
    # mp composition: the step compiles PARTIAL-manual — only the dp axis
    # is bound, mp stays GSPMD-auto so the model's tensor-parallel
    # constraints/collectives keep working inside the region
    auto_axes = ("mp",) if others else ()
    fused_kernels = False
    if backend == "fused":
        if auto_axes:
            # the partitioner cannot partition an opaque pallas_call over
            # the auto mp axis, so the composed step keeps the lax
            # collectives; only the wire format picks up the fused
            # epilogue's fixed-point realization (below)
            pass
        else:
            from ..ops.pallas_kernels import fused_collectives as _fc
            ok, why = _fc.supported(mesh, why="dp axis")
            if ok:
                fused_kernels = True
            else:
                _warn_once(("fused-dp", tuple(mesh.axis_names)),
                           f"fused dp backend unavailable: {why} — falling "
                           f"back to FLAGS_comm_backend='dp=ring'")
                backend = "ring"
    if auto_axes and wire is not None:
        if backend == "fused" and wire is jnp.bfloat16:
            pass  # int16 fixed-point wire, exact accumulation (0.5x bytes)
        elif backend == "fused":
            return bail(
                ("mp-wire-int8", raw),
                f"compressed FLAGS_allreduce_dtype={raw!r} with an active "
                f"mp axis is only available at bf16 width — set "
                f"FLAGS_allreduce_dtype='bfloat16' (keeping "
                f"FLAGS_comm_backend='dp=fused')")
        else:
            return bail(
                ("mp-wire", raw),
                f"compressed FLAGS_allreduce_dtype={raw!r} uses all_to_all, "
                f"which jax 0.4.x cannot partition inside a partial-manual "
                f"region (active mp axis) — set FLAGS_comm_backend="
                f"'dp=fused' to route the reduction through the fused RS "
                f"epilogue's quantized wire instead")
    if offload:
        return bail("offload", "grad_comm does not compose with host "
                    "offload of optimizer states yet — set "
                    "HybridTrainStep(offload=False) / drop the offloading "
                    "optimizer to use the explicit dp schedule")
    axis = dp_like[0]
    n = int(mesh.shape[axis])
    if param_specs:
        # params partitioned over the active axis (ZeRO stage-3 dist_spec):
        # the explicit step's replicated param specs would silently undo
        # that sharding — keep GSPMD's schedule instead. Specs over size-1
        # axes are no-ops and stay eligible.
        for name, spec in param_specs.items():
            if spec is None:
                continue
            parts = [p for part in spec
                     for p in (part if isinstance(part, tuple) else (part,))]
            if axis in parts:
                return bail(("spec", name),
                            f"param {name} is sharded over {axis!r} "
                            f"(dist_spec {spec}); grad_comm would "
                            f"replicate it")
    if wus:
        # only the shard-local update needs the elementwise/slot-shape
        # gate; the explicit all-reduce and quantized-reduce schedules
        # update full params and work for any optimizer
        supports = getattr(optimizer, "supports_sharded_update",
                           lambda *a: getattr(optimizer,
                                              "_elementwise_update", False))
        if not supports():
            return bail(("opt", type(optimizer).__name__),
                        f"{type(optimizer).__name__} does not support a "
                        f"shard-local weight update (non-elementwise rule)")
        if opt_state is not None and params is not None:
            from . import topology as _rs
            for name, sl in opt_state["slots"].items():
                pshape = tuple(params[name].shape)
                for k, v in sl.items():
                    # accept the packed (n, cols) layout too: a checkpoint
                    # saved under weight-update sharding restores its slots
                    # packed before the first compile — including a layout
                    # packed for a DIFFERENT axis size (reshard-on-load:
                    # _pack_leaf re-packs it for this mesh)
                    if tuple(v.shape) not in (pshape,
                                              packed_shape(pshape, n)) \
                            and _rs.packed_n(tuple(v.shape), pshape) is None:
                        return bail(("slot", name, k),
                                    f"slot {name}.{k} shape {tuple(v.shape)}"
                                    f" is neither param-shaped nor packed")
    grad_clip = getattr(optimizer, "_grad_clip", None)
    if grad_clip is not None:
        from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                               ClipGradByValue)
        if not isinstance(grad_clip, (ClipGradByGlobalNorm, ClipGradByNorm,
                                      ClipGradByValue)):
            return bail(("clip", type(grad_clip).__name__),
                        f"unsupported grad clip {type(grad_clip).__name__}")
    return GradCommConfig(axis=axis, n=n,
                          weight_update_sharding=wus, wire_dtype=wire,
                          bucket_bytes=int(F.get("FLAGS_grad_bucket_bytes",
                                                 16 * 2 ** 20)),
                          auto_axes=auto_axes, backend=backend,
                          fused_kernels=fused_kernels)


# ---------------------------------------------------------------------------
# step counters (profiler.comm_counters surface)


_lock = threading.Lock()


def _zero_counters():
    return {"steps": 0, "collectives": 0, "reduce_bytes": 0,
            "reduce_bytes_by_dtype": {}, "gather_bytes": 0, "buckets": 0,
            "payload_bytes": 0, "padded_bytes": 0, "fused_dispatches": 0,
            "backend": {}}


_counters = _zero_counters()


@dataclass
class StepComm:
    """Static per-step communication record of one compiled schedule."""
    reduce_bytes_by_dtype: dict = field(default_factory=dict)
    gather_bytes: int = 0
    collectives: int = 0
    buckets: int = 0
    payload_bytes: int = 0
    padded_bytes: int = 0
    fused_dispatches: int = 0     # Pallas kernel launches (fused backend)
    backend: str = "ring"


def make_step_record(plan, wire_dtype, weight_update_sharding,
                     with_update=True, emulated_gather=False,
                     backend="ring", fused_kernels=False, fixed16=False,
                     sdc=False):
    """Byte/collective ledger for one executed step of this plan. The
    explicit all-reduce baseline (weight_update_sharding=False) counts
    RS+grad-AG as reduce bytes (= ring all-reduce); the sharded-update
    schedule counts RS as reduce and the param all-gather as gather.
    `emulated_gather` (mp-composed partial-manual steps) doubles the
    gather-side bytes — see all_gather_shards. Under the fused backend
    (`fused_kernels`) each eligible bucket's RS/AG is one Pallas kernel
    launch, counted in `fused_dispatches`. ``sdc`` accounts the integrity
    check step's extra collective: one all-gather of a per-replica uint32
    fingerprint (4*(n-1) wire bytes per device)."""
    rec = StepComm()
    rec.backend = backend
    by_dtype, coll = plan.reduce_record(
        wire_dtype, two_sided=not weight_update_sharding, fixed16=fixed16)
    if fused_kernels:
        rs_k = sum(1 for b in plan.buckets
                   if jnp.issubdtype(b.dtype, jnp.floating)
                   and wire_dtype is not jnp.int8)
        ag_k = len(plan.buckets) if (not weight_update_sharding
                                     or with_update) else 0
        rec.fused_dispatches = rs_k + ag_k
    if not weight_update_sharding and emulated_gather:
        # the grad-AG half of the explicit all-reduce is emulated too
        for b in plan.buckets:
            key = str(jnp.dtype(b.dtype))
            gb = int(b.cols * plan.n * jnp.dtype(b.dtype).itemsize
                     * (plan.n - 1) / plan.n)
            by_dtype[key] = by_dtype.get(key, 0) + gb
    rec.reduce_bytes_by_dtype = by_dtype
    rec.collectives = coll
    rec.buckets = len(plan.buckets)
    rec.payload_bytes = plan.payload_bytes()
    rec.padded_bytes = plan.padded_bytes()
    if weight_update_sharding and with_update:
        gb, gcoll = plan.gather_record(emulated=emulated_gather)
        rec.gather_bytes = gb
        rec.collectives += gcoll
    if sdc:
        rec.gather_bytes += 4 * (plan.n - 1)
        rec.collectives += 1
    return rec


def record_step(rec):
    if rec is None:
        return
    with _lock:
        _counters["steps"] += 1
        _counters["collectives"] += rec.collectives
        _counters["gather_bytes"] += rec.gather_bytes
        _counters["buckets"] += rec.buckets
        _counters["payload_bytes"] += rec.payload_bytes
        _counters["padded_bytes"] += rec.padded_bytes
        _counters["fused_dispatches"] += rec.fused_dispatches
        _counters["backend"]["dp"] = rec.backend
        for k, v in rec.reduce_bytes_by_dtype.items():
            _counters["reduce_bytes"] += v
            d = _counters["reduce_bytes_by_dtype"]
            d[k] = d.get(k, 0) + v


def comm_counters():
    with _lock:
        out = dict(_counters)
        out["reduce_bytes_by_dtype"] = dict(out["reduce_bytes_by_dtype"])
        out["backend"] = dict(out["backend"])
    out["bucket_fill"] = (out["payload_bytes"] / out["padded_bytes"]
                          if out["padded_bytes"] else 0.0)
    return out


def reset_comm_counters():
    global _counters
    with _lock:
        _counters = _zero_counters()
