"""Silent-data-corruption sentinel: cross-replica integrity fingerprints,
majority-vote localization, and in-place peer repair.

Every exactness guarantee in this repo is *bitwise by construction*, yet
the anomaly guards (jit/train_step.py, serving/engine.py) only catch
non-finite values — a flaky chip flipping one mantissa bit in a param
replica corrupts training silently. The dp axis carries natural
redundancy: after the weight update every dp replica holds (what should
be) the SAME param bytes. This module turns that redundancy into a
detector and a repair channel:

  * ``fingerprint_arrays`` — a TRACEABLE uint32 fingerprint over a
    pytree's raw bits (per-leaf bitcast + modular uint32 sum, leaf sums
    XOR-folded with per-position odd multipliers), cheap enough — in
    compile time too — to fuse into every Nth step executable
    (``FLAGS_sdc_check_every``). Computed per device inside the manual
    (shard_map) region, all-gathered over dp, the per-replica vector
    rides the step's existing combined host fetch — zero extra syncs.
  * ``localize_minority`` — the host-side majority vote over the gathered
    fingerprint vector: the replicas disagreeing with the majority value
    are the corrupted ones (needs dp >= 3 for a strict majority; a dp=2
    tie is reported as unlocalizable).
  * ``inject_bitflips`` / ``repair_tree`` — both sides of the repair
    channel, built on the same mechanism: a replicated jax.Array exposes
    one full-shape buffer per device (``addressable_shards``), and
    ``jax.make_array_from_single_device_arrays`` reassembles an array
    from per-device buffers WITHOUT verifying they are equal. Injection
    makes one replica's copy diverge (the chaos harness's
    ``FaultPlan.bitflip_at``); repair overwrites the minority replica's
    buffer with a healthy peer's bytes in place — no disk rewind, zero
    steps lost.
  * the ``sdc`` ledger — fingerprint checks/mismatches/repairs, serving
    shadow-audit verdicts, checkpoint-scrub results, per-rank repair
    charges and per-replica suspicion gauges; surfaced as the registry's
    "sdc" family and in ``fault_summary``/``serving_summary``.

A rank repaired more than ``FLAGS_sdc_quarantine_threshold`` times is a
repeat offender: ``quarantined_ranks()`` reports it, and the
ElasticMeshSupervisor's ``quarantine`` policy treats it as a lost chip —
the reform path, not a fleet-wide rewind.
"""
from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp


# -- the sdc ledger -----------------------------------------------------------

_sdc_lock = threading.Lock()


def _zero_sdc():
    return {"fingerprint_checks": 0, "fingerprint_mismatches": 0,
            "repairs": 0, "repair_redispatches": 0,
            "audits": 0, "audit_failures": 0,
            "scrubs": 0, "rot_found": 0,
            "crc_checks": 0, "crc_refusals": 0,
            "quarantined_ranks": 0}


_sdc_counters = _zero_sdc()
_repairs_by_rank: dict[int, int] = {}
_suspicion_by_replica: dict[int, int] = {}


def sdc_counters():
    """Snapshot of the sdc ledger, including dynamic per-rank repair
    charges (``repairs_rank{i}``) and per-replica serving suspicion
    gauges (``suspicion_replica{i}``)."""
    with _sdc_lock:
        out = dict(_sdc_counters)
        for r, n in sorted(_repairs_by_rank.items()):
            out[f"repairs_rank{r}"] = n
        for i, n in sorted(_suspicion_by_replica.items()):
            out[f"suspicion_replica{i}"] = n
        return out


def reset_sdc_counters():
    global _sdc_counters
    with _sdc_lock:
        _sdc_counters = _zero_sdc()
        _repairs_by_rank.clear()
        _suspicion_by_replica.clear()


def _count(key, n=1):
    with _sdc_lock:
        _sdc_counters[key] += n


def note_repair(rank):
    """Charge one peer repair to ``rank``; past the quarantine threshold
    the rank shows up in ``quarantined_ranks()``."""
    with _sdc_lock:
        _repairs_by_rank[int(rank)] = _repairs_by_rank.get(int(rank), 0) + 1


def quarantined_ranks():
    """Ranks whose repair charge reached ``FLAGS_sdc_quarantine_threshold``
    — repeat offenders a ``quarantine``-policy elastic supervisor treats
    as lost chips. Frozenset; empty when nothing was ever repaired."""
    from .. import flags as _flags
    thresh = int(_flags._FLAGS.get("FLAGS_sdc_quarantine_threshold", 2))
    with _sdc_lock:
        bad = frozenset(r for r, n in _repairs_by_rank.items()
                        if n >= max(1, thresh))
        _sdc_counters["quarantined_ranks"] = len(bad)
    return bad


def note_audit(ok, replica=None):
    """Record one serving shadow-audit verdict; a failure bumps the owning
    replica's suspicion gauge. Returns the replica's suspicion count."""
    with _sdc_lock:
        _sdc_counters["audits"] += 1
        if ok:
            return 0
        _sdc_counters["audit_failures"] += 1
        if replica is None:
            return 0
        i = int(replica)
        _suspicion_by_replica[i] = _suspicion_by_replica.get(i, 0) + 1
        return _suspicion_by_replica[i]


def clear_suspicion(replica):
    """Reset a replica's suspicion after the supervisor failed it over
    (the fresh engine starts with a clean slate)."""
    with _sdc_lock:
        _suspicion_by_replica.pop(int(replica), None)


# -- traceable fingerprint ----------------------------------------------------


def _leaf_sum(x):
    """Modular uint32 sum over a leaf's raw bits (traceable). Any single
    bit flip changes the sum: each element contributes its exact bit
    pattern, and addition mod 2^32 cannot cancel a one-element change."""
    arr = jnp.asarray(x)
    dt = arr.dtype
    if jnp.issubdtype(dt, jnp.floating):
        u = jax.lax.bitcast_convert_type(
            arr, jnp.dtype(f"uint{dt.itemsize * 8}"))
        if dt.itemsize == 8:
            u = (u ^ (u >> np.uint64(32))).astype(jnp.uint32)
        else:
            u = u.astype(jnp.uint32)
    else:
        u = arr.astype(jnp.uint32)
    return jnp.sum(u.reshape(-1), dtype=jnp.uint32)


def fingerprint_arrays(tree):
    """TRACEABLE uint32 fingerprint over every leaf of ``tree`` (leaf
    bit-sums combined in tree-leaf order, so leaf identity matters, not
    just the multiset of sums). Inside a shard_map manual region this
    fingerprints the device-LOCAL bytes — exactly what cross-replica
    comparison needs.

    Each leaf sum is multiplied by a distinct ODD constant (bijective mod
    2^32: a changed sum always changes the product, and position is baked
    into the multiplier) and XOR-folded. The accumulator is referenced
    ONCE per leaf on purpose: a boost-style chain touching it three times
    per step compiles as a 3^N-node scalar expression tree under the SPMD
    partitioner — minutes of XLA time by N~13 leaves."""
    acc = jnp.uint32(0)
    for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
        if hasattr(leaf, "_data"):
            leaf = leaf._data
        s = _leaf_sum(leaf)
        c = np.uint32(((0x9E3779B9 * (2 * i + 1)) & 0xFFFFFFFF) | 1)
        acc = acc ^ (s * c)
    return acc


def localize_minority(fps):
    """Majority vote over a per-replica fingerprint vector. Returns ``()``
    when all agree, the tuple of minority replica indices when a strict
    majority exists, or ``None`` when the vote ties (dp=2 — detection
    without localization)."""
    fps = np.asarray(fps).reshape(-1)
    vals, counts = np.unique(fps, return_counts=True)
    if len(vals) == 1:
        return ()
    if counts.max() * 2 <= len(fps):
        return None
    maj = vals[int(np.argmax(counts))]
    return tuple(int(i) for i in np.nonzero(fps != maj)[0])


# -- divergent-copy injection + in-place peer repair --------------------------


def _is_replicated(arr, devices):
    """True when ``arr`` holds one full-shape buffer on each of
    ``devices`` — the per-device redundancy both injection and repair
    need. dp-SHARDED leaves (packed slots under weight-update sharding)
    have no peer copy and are skipped by ``repair_tree``."""
    shards = getattr(arr, "addressable_shards", None)
    if shards is None or len(shards) != len(devices):
        return False
    return all(s.data.shape == arr.shape for s in shards)


def _rebuild(arr, devices, replace):
    """Reassemble ``arr`` with the buffers of the ranks in ``replace``
    (``{rank: np.ndarray}``) swapped out. jax does NOT verify replicated
    buffers are equal — the mechanism behind both fault injection and
    peer repair."""
    by_dev = {s.device: s.data for s in arr.addressable_shards}
    bufs = []
    for i, d in enumerate(devices):
        if i in replace:
            bufs.append(jax.device_put(replace[i], d))
        else:
            bufs.append(by_dev[d])
    return jax.make_array_from_single_device_arrays(
        arr.shape, arr.sharding, bufs)


def inject_bitflips(params, flips, devices):
    """Chaos-harness entry (``FaultPlan.bitflip_at``): flip bit ``bit`` of
    element 0 of param leaf ``name`` in rank ``rank``'s replica copy ONLY
    — the divergent-copy state a real flipped bit leaves behind.
    ``params`` is a name->array mapping; ``devices`` the dp-axis device
    order (rank i's copy lives on devices[i]). Returns a new mapping."""
    out = dict(params)
    names = sorted(out)
    for rank, name, bit in flips:
        if name is None or name not in out:
            name = names[0]
        arr = out[name]
        if not _is_replicated(arr, devices):
            raise ValueError(
                f"bitflip target {name!r} is not replicated over "
                f"{len(devices)} devices")
        by_dev = {s.device: s.data for s in arr.addressable_shards}
        data = np.asarray(by_dev[devices[int(rank)]]).copy()
        flat = data.view(np.uint8).reshape(-1)
        byte, off = divmod(int(bit), 8)
        flat[byte] ^= np.uint8(1 << off)
        out[name] = _rebuild(arr, devices, {int(rank): data})
    return out


def repair_tree(tree, bad_ranks, devices):
    """In-place peer repair: overwrite each ``bad_ranks`` replica buffer
    of every REPLICATED leaf with a healthy peer's bytes. Sharded leaves
    (packed dp-sharded optimizer slots) have no redundant copy and pass
    through untouched — their integrity story is the checkpoint CRC
    manifest. Returns the repaired tree (same treedef)."""
    bad = set(int(r) for r in bad_ranks)
    donor = next(i for i in range(len(devices)) if i not in bad)

    def fix(arr):
        leaf = arr._data if hasattr(arr, "_data") else arr
        if not isinstance(leaf, jax.Array) or not _is_replicated(leaf,
                                                                 devices):
            return arr
        by_dev = {s.device: s.data for s in leaf.addressable_shards}
        good = np.asarray(by_dev[devices[donor]])
        fixed = _rebuild(leaf, devices, {r: good for r in bad})
        if hasattr(arr, "_data"):
            arr._data = fixed
            return arr
        return fixed

    return jax.tree_util.tree_map(fix, tree)
