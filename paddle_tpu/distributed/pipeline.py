"""Pipeline parallelism.

Re-design of fleet.meta_parallel.PipelineParallel (ref: python/paddle/
distributed/fleet/meta_parallel/pipeline_parallel.py, pp_utils/
p2p_communication.py). The reference implements 1F1B with explicit NCCL
send/recv between per-rank processes and a Python scheduler.

TPU-native: the schedule is a `lax.scan` over T = M + S - 1 ticks inside a
`shard_map` manual over the 'pp' mesh axis. Each tick every stage applies its
block stack and `ppermute`s the activation one hop around the ICI ring — a
circular GPipe. The BACKWARD schedule is not hand-written at all: jax
differentiates the scan+ppermute program, which yields the reversed-ring,
reversed-time schedule automatically, and XLA overlaps the collective with
compute. Bubble fraction matches GPipe: (S-1)/(M+S-1).

Stage bodies must be homogeneous (same program on every device — SPMD), which
matches the transformer use-case: embed/head run outside the pipelined region,
the repeated blocks run inside. Layer params are stacked on a leading [S,
layers_per_stage] axis, sharded P('pp') on axis 0.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import env


def pipeline_spmd(block_fn, stage_params, x_mb, *, axis_name="pp"):
    """Run inside a shard_map manual over `axis_name`.

    block_fn: (layer_params, activation) -> activation — ONE block; it is
        scanned over the local layers of the stage.
    stage_params: pytree, leaves [1, local_L, ...] (this stage's slice).
    x_mb: [M, mb, ...] microbatches (same on all stages; only stage 0 reads).
    Returns [M, mb, ...]: on the LAST stage these are the pipeline outputs;
    other stages return garbage that the caller discards (out_specs selects
    from the last stage).
    """
    S = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    T = M + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]
    local_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)

    def stage_fn(act):
        def scan_layer(h, layer_params):
            return block_fn(layer_params, h), None
        out, _ = lax.scan(scan_layer, act, local_params)
        return out

    def _varying(a):
        # mark carry values as device-varying over the pp axis (vma typing)
        if hasattr(lax, "pcast"):
            return lax.pcast(a, (axis_name,), to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(a, (axis_name,))
        return a

    outputs0 = _varying(jnp.zeros_like(x_mb))
    hold0 = _varying(jnp.zeros(x_mb.shape[1:], x_mb.dtype))

    def tick(carry, t):
        outputs, prev_out = carry
        shifted = lax.ppermute(prev_out, axis_name, perm)
        mb_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        inp = jnp.where(stage == 0, first_in, shifted)
        out = stage_fn(inp)
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        write = jnp.logical_and(stage == S - 1, t >= S - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, cur), out_idx, 0)
        return (outputs, out), None

    (outputs, _), _ = lax.scan(tick, (outputs0, hold0), jnp.arange(T))
    # broadcast the last stage's outputs to every stage (replicated result):
    # mask + psum over the ring — cheap relative to the per-tick traffic
    masked = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(masked, axis_name)


def run_pipeline(block_fn, stacked_params, x, num_microbatches, mesh=None,
                 axis_name="pp", data_spec=P()):
    """Host-side wrapper: shard_map(manual over 'pp', auto elsewhere).

    stacked_params: pytree, leaves [S * local_L, ...] stacked layer params.
    x: [B, ...] activations entering the pipelined blocks.
    Returns [B, ...] outputs of the last stage (broadcast to all stages).
    """
    mesh = mesh or env.get_mesh()
    S = mesh.shape[axis_name]
    M = num_microbatches
    B = x.shape[0]
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"

    def reshape_stages(a):
        return a.reshape((S, a.shape[0] // S) + a.shape[1:])

    staged = jax.tree_util.tree_map(reshape_stages, stacked_params)
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda a: P("pp", *([None] * (a.ndim - 1))), staged)

    inner = functools.partial(pipeline_spmd, block_fn, axis_name=axis_name)
    mapped = jax.shard_map(
        lambda p, xm: inner(p, xm),
        mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
        axis_names=frozenset({axis_name}))
    out_mb = mapped(staged, x_mb)
    return out_mb.reshape((B,) + out_mb.shape[2:])


# ---------------------------------------------------------------------------
# fleet-style API surface (ref: fleet/meta_parallel/parallel_layers/pp_layers.py)
class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer:
    """API-parity container describing a pipelined model. On TPU the pipeline
    executes via `run_pipeline` (scan+ppermute); this class assigns descs to
    stages and materializes the homogeneous middle blocks for stacking."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        self.descs = layers
        self.num_stages = num_stages or (env.get_mesh().shape.get("pp", 1)
                                         if env.get_mesh() else 1)
        self.loss_fn = loss_fn
        self._layers = [d.build_layer() if isinstance(d, LayerDesc) else d
                        for d in layers]

    def get_stage_from_index(self, idx):
        per = max(len(self._layers) // self.num_stages, 1)
        return min(idx // per, self.num_stages - 1)

    def forward(self, x):
        for l in self._layers:
            x = l(x) if callable(l) else l.forward(x)
        return x

    def __call__(self, x):
        return self.forward(x)

    def sublayers(self):
        return list(self._layers)

    def parameters(self):
        out = []
        for l in self._layers:
            if hasattr(l, "parameters"):
                out.extend(l.parameters())
        return out
