"""Pipeline parallelism.

Re-design of fleet.meta_parallel.PipelineParallel (ref: python/paddle/
distributed/fleet/meta_parallel/pipeline_parallel.py, pp_utils/
p2p_communication.py). The reference implements 1F1B with explicit NCCL
send/recv between per-rank processes and a Python scheduler.

TPU-native: the schedule is a `lax.scan` over T = M + S - 1 ticks inside a
`shard_map` manual over the 'pp' mesh axis. Each tick every stage applies its
block stack and `ppermute`s the activation one hop around the ICI ring — a
circular GPipe. The BACKWARD schedule is not hand-written at all: jax
differentiates the scan+ppermute program, which yields the reversed-ring,
reversed-time schedule automatically, and XLA overlaps the collective with
compute. Bubble fraction matches GPipe: (S-1)/(M+S-1).

Stage bodies must be homogeneous (same program on every device — SPMD), which
matches the transformer use-case: embed/head run outside the pipelined region,
the repeated blocks run inside. Layer params are stacked on a leading [S,
layers_per_stage] axis, sharded P('pp') on axis 0.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import env


def pipeline_spmd(block_fn, stage_params, x_mb, *, axis_name="pp"):
    """Run inside a shard_map manual over `axis_name`.

    block_fn: (layer_params, activation) -> activation — ONE block; it is
        scanned over the local layers of the stage.
    stage_params: pytree, leaves [1, local_L, ...] (this stage's slice).
    x_mb: [M, mb, ...] microbatches (same on all stages; only stage 0 reads).
    Returns [M, mb, ...]: on the LAST stage these are the pipeline outputs;
    other stages return garbage that the caller discards (out_specs selects
    from the last stage).
    """
    S = env.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    T = M + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]
    local_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    full_stage_fn = _stage_fn_of(block_fn)

    def stage_fn(act):
        return full_stage_fn(local_params, act)

    outputs0 = _varying(jnp.zeros_like(x_mb), axis_name)
    hold0 = _varying(jnp.zeros(x_mb.shape[1:], x_mb.dtype), axis_name)

    def tick(carry, t):
        outputs, prev_out = carry
        shifted = lax.ppermute(prev_out, axis_name, perm)
        mb_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        inp = jnp.where(stage == 0, first_in, shifted)
        out = stage_fn(inp)
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        write = jnp.logical_and(stage == S - 1, t >= S - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, cur), out_idx, 0)
        return (outputs, out), None

    (outputs, _), _ = lax.scan(tick, (outputs0, hold0), jnp.arange(T))
    # broadcast the last stage's outputs to every stage (replicated result):
    # mask + psum over the ring — cheap relative to the per-tick traffic
    masked = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(masked, axis_name)


def _stage_fn_of(block_fn, remat_policy=None):
    """remat_policy (jax.checkpoint policy or None=full recompute) controls
    which per-layer residuals the stage vjp keeps during a backward tick —
    the per-tick analog of the single-chip selective-save policies
    (distributed/recompute.py POLICIES). Only meaningful on the hand-written
    1f1b backward paths, where jax.vjp(stage_fn, ...) runs within one tick.
    """
    if remat_policy is not None:
        block_fn = jax.checkpoint(block_fn, policy=remat_policy)

    def stage_fn(local_params, act):
        def scan_layer(h, layer_params):
            return block_fn(layer_params, h), None
        out, _ = lax.scan(scan_layer, act, local_params)
        return out
    return stage_fn


def _varying(a, axis_name):
    try:
        if hasattr(lax, "pcast"):
            return lax.pcast(a, (axis_name,), to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(a, (axis_name,))
    except ValueError:
        pass  # already varying over axis_name
    return a


def _gated_fwd(stage_fn, axis_name, active, pv, inp):
    """stage forward, skipped entirely (lax.cond) on inactive schedule slots
    so warmup/cooldown ticks don't burn MXU time on masked garbage."""
    return lax.cond(
        active,
        lambda a: stage_fn(pv, a),
        lambda a: _varying(jnp.zeros(inp.shape, inp.dtype), axis_name),
        inp)


def _gated_vjp(stage_fn, axis_name, active, pv, inp, gout):
    """(param_grads, input_grad) of the stage at `inp`, cond-gated like
    _gated_fwd."""
    def run(args):
        i, go = args
        _, vjp_fn = jax.vjp(stage_fn, pv, i)
        return vjp_fn(go)

    def zero(args):
        i, _ = args
        return (jax.tree_util.tree_map(
            lambda a: _varying(jnp.zeros_like(a), axis_name), pv),
            _varying(jnp.zeros(i.shape, i.dtype), axis_name))

    return lax.cond(active, run, zero, (inp, gout))


def pipeline_spmd_1f1b(block_fn, stage_params, x_mb, *, axis_name="pp",
                       remat_policy=None):
    """1F1B-scheduled pipeline (ref: fleet/meta_parallel/pipeline_parallel.py:230
    `forward_backward_pipeline`, the "1f1b scheduling strategy").

    Same contract as `pipeline_spmd`, but the backward pass is hand-scheduled
    instead of autodiff'd through the forward scan. Why: autodiff of the GPipe
    scan stores per-tick residuals for all T = M+S-1 ticks — activation
    residency O(M). Here the backward runs its own combined schedule: each tick
    does one forward (recomputing the activation stream) and one backward
    microbatch per stage, with a circular stash of at most K = 2S-1 in-flight
    stage *inputs* — residency O(S), independent of the microbatch count.

    Scheduling (stage s, tick t, microbatch indices):
      forward  of mb  fm = t - s
      backward of mb  bm = t - 2(S-1) + s     (same tick as fm on last stage)
      T = M + 2S - 2 ticks; stash slot = mb mod K, lifetime exactly <= K ticks.

    Cost: stage-input checkpointing (Megatron "full recompute" mode) — the
    backward recomputes each stage forward from the stashed input rather than
    stashing per-layer residuals, because vjp residuals would carry K copies of
    (cast) stage params. ~1 extra forward vs GPipe+autodiff, in exchange for
    O(S) instead of O(M) activation memory.
    """
    S = env.axis_size(axis_name)
    M = x_mb.shape[0]
    stage_fn = _stage_fn_of(block_fn, remat_policy)

    @jax.custom_vjp
    def pipe(sp, xm):
        return pipeline_spmd(block_fn, sp, xm, axis_name=axis_name)

    def pipe_fwd(sp, xm):
        return pipe(sp, xm), (sp, xm)

    def pipe_bwd(res, g):
        sp, xm = res
        local_params = jax.tree_util.tree_map(lambda a: a[0], sp)
        stage = lax.axis_index(axis_name)
        K = 2 * S - 1
        T = M + 2 * S - 2
        perm_down = [(i, (i + 1) % S) for i in range(S)]
        perm_up = [(i, (i - 1) % S) for i in range(S)]
        mb_shape = x_mb.shape[1:]

        def vv(a):
            return _varying(a, axis_name)

        stash0 = vv(jnp.zeros((K,) + mb_shape, xm.dtype))
        send_f0 = vv(jnp.zeros(mb_shape, xm.dtype))
        send_b0 = vv(jnp.zeros(mb_shape, g.dtype))
        pgrads0 = jax.tree_util.tree_map(
            lambda a: vv(jnp.zeros(a.shape, a.dtype)), local_params)
        gx0 = vv(jnp.zeros_like(xm))

        def tick(carry, t):
            stash, send_f, send_b, pgrads, gx = carry
            recv_f = lax.ppermute(send_f, axis_name, perm_down)
            recv_b = lax.ppermute(send_b, axis_name, perm_up)

            # ---- forward sub-tick: recompute the activation stream
            fm = t - stage
            f_act = jnp.logical_and(fm >= 0, fm < M)
            first_in = lax.dynamic_index_in_dim(
                xm, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, first_in, recv_f)
            out_f = _gated_fwd(stage_fn, axis_name, f_act, local_params, inp)
            slot_f = jnp.mod(fm, K)
            cur = lax.dynamic_index_in_dim(stash, slot_f, 0, keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_act, inp, cur), slot_f, 0)

            # ---- backward sub-tick
            bm = t - 2 * (S - 1) + stage
            b_act = jnp.logical_and(bm >= 0, bm < M)
            slot_b = jnp.mod(bm, K)
            stashed_in = lax.dynamic_index_in_dim(
                stash, slot_b, 0, keepdims=False)
            g_last = lax.dynamic_index_in_dim(
                g, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
            g_out = jnp.where(stage == S - 1, g_last.astype(send_b.dtype),
                              recv_b)
            gp, gi = _gated_vjp(stage_fn, axis_name, b_act, local_params,
                                stashed_in, g_out.astype(stashed_in.dtype))
            pgrads = jax.tree_util.tree_map(
                lambda acc, gg: acc + gg.astype(acc.dtype), pgrads, gp)
            write_gx = jnp.logical_and(b_act, stage == 0)
            cur_gx = lax.dynamic_index_in_dim(
                gx, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
            gx = lax.dynamic_update_index_in_dim(
                gx, jnp.where(write_gx, gi.astype(gx.dtype), cur_gx),
                jnp.clip(bm, 0, M - 1), 0)
            return (stash, out_f, gi.astype(send_b.dtype), pgrads, gx), None

        carry0 = (stash0, send_f0, send_b0, pgrads0, gx0)
        (_, _, _, pgrads, gx), _ = lax.scan(tick, carry0, jnp.arange(T))
        # grads wrt the [1, L, ...] per-device param slice; x grads live on
        # stage 0 only (shard_map psums replicated-input cotangents).
        g_sp = jax.tree_util.tree_map(lambda a: a[None], pgrads)
        # xm entered replicated (in_spec P()), so its cotangent must leave
        # replicated/invariant too: mask to stage 0's contribution and psum.
        gx = lax.psum(jnp.where(stage == 0, gx, jnp.zeros_like(gx)), axis_name)
        return g_sp, gx

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(stage_params, x_mb)


def pipeline_spmd_interleaved_1f1b(block_fn, stage_params, x_mb, *,
                                   num_virtual, axis_name="pp",
                                   remat_policy=None):
    """Interleaved ("virtual pipeline") 1F1B (ref: fleet/meta_parallel/
    pipeline_parallel.py:613 interleaved schedule / VPP).

    Device s hosts V = num_virtual chunks — virtual stages p = s, s+S, ...,
    s+(V-1)S of a flat S' = V*S stage pipeline. Per tick each device runs its
    active virtual-stage chunks (fwd of mb t-p, bwd of mb t-2(S'-1)+p),
    `lax.cond`-gated so inactive warmup/cooldown slots skip the matmuls
    (interleaving only pays off when idle slots are cheap). All V streams
    ride one stacked ppermute per direction; the lap boundary (device S-1 →
    device 0, lap v → v+1) is a roll of the stacked recv buffer.

    stage_params leaves: [1, V, L_chunk, ...] — this device's V chunks.
    x_mb: [M, mb...]; returns [M, mb...] like pipeline_spmd.
    """
    S = env.axis_size(axis_name)
    V = num_virtual
    Sv = V * S
    M = x_mb.shape[0]
    stage_fn = _stage_fn_of(block_fn, remat_policy)
    mb_shape = x_mb.shape[1:]
    perm_down = [(i, (i + 1) % S) for i in range(S)]
    perm_up = [(i, (i - 1) % S) for i in range(S)]

    def chunk_params(sp, v):
        return jax.tree_util.tree_map(lambda a: a[0, v], sp)

    def gated_fwd(active, pv, inp):
        return _gated_fwd(stage_fn, axis_name, active, pv, inp)

    @jax.custom_vjp
    def pipe(sp, xm):
        stage = lax.axis_index(axis_name)
        T = M + Sv - 1

        def vv(a):
            return _varying(a, axis_name)

        fsend0 = vv(jnp.zeros((V,) + mb_shape, xm.dtype))
        outputs0 = vv(jnp.zeros_like(xm))

        def tick(carry, t):
            fsend, outputs = carry
            recv = lax.ppermute(fsend, axis_name, perm_down)
            # lap boundary: device 0's lap v reads device S-1's lap v-1
            recv = jnp.where(stage == 0, jnp.roll(recv, 1, axis=0), recv)
            outs = []
            for v in range(V):
                p = stage + v * S
                fm = t - p
                active = jnp.logical_and(fm >= 0, fm < M)
                first_in = lax.dynamic_index_in_dim(
                    xm, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
                inp = recv[v]
                if v == 0:
                    inp = jnp.where(stage == 0, first_in, inp)
                outs.append(gated_fwd(active, chunk_params(sp, v), inp))
            out_last = outs[V - 1]
            out_idx = jnp.clip(t - (Sv - 1), 0, M - 1)
            write = jnp.logical_and(
                jnp.logical_and(stage == S - 1, t >= Sv - 1), t - (Sv - 1) < M)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, out_last, cur), out_idx, 0)
            return (jnp.stack(outs), outputs), None

        (_, outputs), _ = lax.scan(tick, (fsend0, outputs0), jnp.arange(T))
        masked = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
        return lax.psum(masked, axis_name)

    def pipe_fwd(sp, xm):
        return pipe(sp, xm), (sp, xm)

    def pipe_bwd(res, g):
        sp, xm = res
        stage = lax.axis_index(axis_name)
        K = 2 * Sv - 1
        T = M + 2 * Sv - 2

        def vv(a):
            return _varying(a, axis_name)

        stash0 = vv(jnp.zeros((V, K) + mb_shape, xm.dtype))
        fsend0 = vv(jnp.zeros((V,) + mb_shape, xm.dtype))
        bsend0 = vv(jnp.zeros((V,) + mb_shape, g.dtype))
        pgrads0 = jax.tree_util.tree_map(
            lambda a: vv(jnp.zeros(a.shape[1:], a.dtype)), sp)  # [V, Lc, ...]
        gx0 = vv(jnp.zeros_like(xm))

        def gated_vjp(active, pv, inp, gout):
            return _gated_vjp(stage_fn, axis_name, active, pv, inp, gout)

        def tick(carry, t):
            stash, fsend, bsend, pgrads, gx = carry
            recv_f = lax.ppermute(fsend, axis_name, perm_down)
            recv_f = jnp.where(stage == 0, jnp.roll(recv_f, 1, axis=0), recv_f)
            recv_b = lax.ppermute(bsend, axis_name, perm_up)
            # lap boundary reversed: device S-1's lap v reads dev 0's lap v+1
            recv_b = jnp.where(stage == S - 1, jnp.roll(recv_b, -1, axis=0),
                               recv_b)

            f_outs, b_outs = [], []
            new_pgrads = []
            for v in range(V):
                p = stage + v * S
                pv = chunk_params(sp, v)
                # ---- forward sub-tick for chunk v
                fm = t - p
                f_act = jnp.logical_and(fm >= 0, fm < M)
                first_in = lax.dynamic_index_in_dim(
                    xm, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
                inp = recv_f[v]
                if v == 0:
                    inp = jnp.where(stage == 0, first_in, inp)
                f_outs.append(gated_fwd(f_act, pv, inp))
                slot_f = jnp.mod(fm, K)
                cur = lax.dynamic_index_in_dim(stash[v], slot_f, 0,
                                               keepdims=False)
                stash = stash.at[v].set(lax.dynamic_update_index_in_dim(
                    stash[v], jnp.where(f_act, inp, cur), slot_f, 0))

                # ---- backward sub-tick for chunk v
                bm = t - 2 * (Sv - 1) + p
                b_act = jnp.logical_and(bm >= 0, bm < M)
                slot_b = jnp.mod(bm, K)
                stashed_in = lax.dynamic_index_in_dim(stash[v], slot_b, 0,
                                                      keepdims=False)
                g_last = lax.dynamic_index_in_dim(
                    g, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
                gout = recv_b[v]
                if v == V - 1:
                    gout = jnp.where(stage == S - 1,
                                     g_last.astype(gout.dtype), gout)
                gp, gi = gated_vjp(b_act, pv, stashed_in,
                                   gout.astype(stashed_in.dtype))
                new_pgrads.append(gp)
                b_outs.append(gi.astype(bsend.dtype))
                if v == 0:
                    write_gx = jnp.logical_and(b_act, stage == 0)
                    cur_gx = lax.dynamic_index_in_dim(
                        gx, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
                    gx = lax.dynamic_update_index_in_dim(
                        gx, jnp.where(write_gx, gi.astype(gx.dtype), cur_gx),
                        jnp.clip(bm, 0, M - 1), 0)

            pgrads = jax.tree_util.tree_map(
                lambda acc, *gs: acc + jnp.stack(gs).astype(acc.dtype),
                pgrads, *new_pgrads)
            return (stash, jnp.stack(f_outs), jnp.stack(b_outs), pgrads,
                    gx), None

        carry0 = (stash0, fsend0, bsend0, pgrads0, gx0)
        (_, _, _, pgrads, gx), _ = lax.scan(tick, carry0, jnp.arange(T))
        g_sp = jax.tree_util.tree_map(lambda a: a[None], pgrads)
        gx = lax.psum(jnp.where(stage == 0, gx, jnp.zeros_like(gx)), axis_name)
        return g_sp, gx

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(stage_params, x_mb)


def vpp_storage_perm(L, S, V):
    """Stage-major storage order for interleaved VPP: storage slot
    s*(V*Lc)+v*Lc+p holds logical layer (v*S+s)*Lc+p. Stacked params
    pre-permuted this way shard over 'pp' as a plain contiguous split —
    no cross-device reshard at the shard_map boundary (the layout the
    swapaxes in run_pipeline would otherwise create on the fly)."""
    Lc = L // (S * V)
    assert Lc * S * V == L, f"layers {L} != pp {S} x interleave {V} x chunk"
    return [(v * S + s) * Lc + p
            for s in range(S) for v in range(V) for p in range(Lc)]


def run_pipeline(block_fn, stacked_params, x, num_microbatches, mesh=None,
                 axis_name="pp", data_spec=P(), schedule="gpipe",
                 interleave=1, vpp_stage_major=False, remat_policy=None):
    """Host-side wrapper: shard_map(manual over 'pp', auto elsewhere).

    stacked_params: pytree, leaves [S * local_L, ...] stacked layer params.
    x: [B, ...] activations entering the pipelined blocks.
    Returns [B, ...] outputs of the last stage (broadcast to all stages).

    With ``vpp_stage_major`` the caller stores stacked params in
    `vpp_storage_perm` order so the interleaved reshape is contiguous and
    the 'pp' sharding of storage matches chunk placement exactly (avoids
    XLA's involuntary full rematerialization of every block param).
    """
    mesh = mesh or env.get_mesh()
    S = mesh.shape[axis_name]
    M = num_microbatches
    B = x.shape[0]
    V = interleave
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"

    if V > 1 and vpp_stage_major:
        def reshape_stages(a):
            Lc = a.shape[0] // (V * S)
            return a.reshape((S, V, Lc) + a.shape[1:])  # contiguous
    elif V > 1:
        # chunk c of V*S covers layers [c*Lc, (c+1)*Lc); device c%S, lap c//S
        def reshape_stages(a):
            Lc = a.shape[0] // (V * S)
            vs_major = a.reshape((V, S, Lc) + a.shape[1:])
            return jnp.swapaxes(vs_major, 0, 1)          # [S, V, Lc, ...]
    else:
        def reshape_stages(a):
            return a.reshape((S, a.shape[0] // S) + a.shape[1:])

    staged = jax.tree_util.tree_map(reshape_stages, stacked_params)
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda a: P("pp", *([None] * (a.ndim - 1))), staged)

    if V > 1:
        assert schedule == "1f1b", "interleaving requires the 1f1b schedule"
        spmd = functools.partial(pipeline_spmd_interleaved_1f1b,
                                 num_virtual=V, remat_policy=remat_policy)
    elif schedule == "1f1b":
        spmd = functools.partial(pipeline_spmd_1f1b,
                                 remat_policy=remat_policy)
    else:
        if remat_policy is not None:
            raise ValueError(
                "remat_policy requires the 1f1b schedule (the gpipe autodiff "
                "path derives its own recompute from the scan)")
        spmd = pipeline_spmd
    inner = functools.partial(spmd, block_fn, axis_name=axis_name)
    mapped = env.shard_map_compat(
        lambda p, xm: inner(p, xm),
        mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
        axis_names=frozenset({axis_name}))
    out_mb = mapped(staged, x_mb)
    return out_mb.reshape((B,) + out_mb.shape[2:])


# ---------------------------------------------------------------------------
# fleet-style API surface (ref: fleet/meta_parallel/parallel_layers/pp_layers.py)
class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer:
    """API-parity container describing a pipelined model. On TPU the pipeline
    executes via `run_pipeline` (scan+ppermute); this class assigns descs to
    stages and materializes the homogeneous middle blocks for stacking."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        self.descs = layers
        self.num_stages = num_stages or (env.get_mesh().shape.get("pp", 1)
                                         if env.get_mesh() else 1)
        self.loss_fn = loss_fn
        self._layers = [d.build_layer() if isinstance(d, LayerDesc) else d
                        for d in layers]

    def get_stage_from_index(self, idx):
        per = max(len(self._layers) // self.num_stages, 1)
        return min(idx // per, self.num_stages - 1)

    def forward(self, x):
        for l in self._layers:
            x = l(x) if callable(l) else l.forward(x)
        return x

    def __call__(self, x):
        return self.forward(x)

    def sublayers(self):
        return list(self._layers)

    def parameters(self):
        out = []
        for l in self._layers:
            if hasattr(l, "parameters"):
                out.extend(l.parameters())
        return out
