"""Pipeline parallelism.

Re-design of fleet.meta_parallel.PipelineParallel (ref: python/paddle/
distributed/fleet/meta_parallel/pipeline_parallel.py, pp_utils/
p2p_communication.py). The reference implements 1F1B with explicit NCCL
send/recv between per-rank processes and a Python scheduler.

TPU-native: the schedule is a `lax.scan` over T = M + S - 1 ticks inside a
`shard_map` manual over the 'pp' mesh axis. Each tick every stage applies its
block stack and `ppermute`s the activation one hop around the ICI ring — a
circular GPipe. The BACKWARD schedule is not hand-written at all: jax
differentiates the scan+ppermute program, which yields the reversed-ring,
reversed-time schedule automatically, and XLA overlaps the collective with
compute. Bubble fraction matches GPipe: (S-1)/(M+S-1).

Stage bodies must be homogeneous (same program on every device — SPMD), which
matches the transformer use-case: embed/head run outside the pipelined region,
the repeated blocks run inside. Layer params are stacked on a leading [S,
layers_per_stage] axis, sharded P('pp') on axis 0.
"""
from __future__ import annotations

import functools
import threading
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import env


def pipeline_spmd(block_fn, stage_params, x_mb, *, axis_name="pp"):
    """Run inside a shard_map manual over `axis_name`.

    block_fn: (layer_params, activation) -> activation — ONE block; it is
        scanned over the local layers of the stage.
    stage_params: pytree, leaves [1, local_L, ...] (this stage's slice).
    x_mb: [M, mb, ...] microbatches (same on all stages; only stage 0 reads).
    Returns [M, mb, ...]: on the LAST stage these are the pipeline outputs;
    other stages return garbage that the caller discards (out_specs selects
    from the last stage).
    """
    S = env.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    T = M + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]
    local_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    full_stage_fn = _stage_fn_of(block_fn)

    def stage_fn(act):
        return full_stage_fn(local_params, act)

    outputs0 = _varying(jnp.zeros_like(x_mb), axis_name)
    hold0 = _varying(jnp.zeros(x_mb.shape[1:], x_mb.dtype), axis_name)

    def tick(carry, t):
        outputs, prev_out = carry
        shifted = lax.ppermute(prev_out, axis_name, perm)
        mb_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        inp = jnp.where(stage == 0, first_in, shifted)
        out = stage_fn(inp)
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        write = jnp.logical_and(stage == S - 1, t >= S - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, cur), out_idx, 0)
        return (outputs, out), None

    (outputs, _), _ = lax.scan(tick, (outputs0, hold0), jnp.arange(T))
    # broadcast the last stage's outputs to every stage (replicated result):
    # mask + psum over the ring — cheap relative to the per-tick traffic
    masked = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(masked, axis_name)


def _stage_fn_of(block_fn, remat_policy=None):
    """remat_policy (jax.checkpoint policy or None=full recompute) controls
    which per-layer residuals the stage vjp keeps during a backward tick —
    the per-tick analog of the single-chip selective-save policies
    (distributed/recompute.py POLICIES). Only meaningful on the hand-written
    1f1b backward paths, where jax.vjp(stage_fn, ...) runs within one tick.
    """
    if remat_policy is not None:
        block_fn = jax.checkpoint(block_fn, policy=remat_policy)

    def stage_fn(local_params, act):
        def scan_layer(h, layer_params):
            return block_fn(layer_params, h), None
        out, _ = lax.scan(scan_layer, act, local_params)
        return out
    return stage_fn


def _varying(a, axis_name):
    try:
        if hasattr(lax, "pcast"):
            return lax.pcast(a, (axis_name,), to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(a, (axis_name,))
    except ValueError:
        pass  # already varying over axis_name
    return a


def _gated_fwd(stage_fn, axis_name, active, pv, inp):
    """stage forward, skipped entirely (lax.cond) on inactive schedule slots
    so warmup/cooldown ticks don't burn MXU time on masked garbage."""
    return lax.cond(
        active,
        lambda a: stage_fn(pv, a),
        lambda a: _varying(jnp.zeros(inp.shape, inp.dtype), axis_name),
        inp)


def _gated_vjp(stage_fn, axis_name, active, pv, inp, gout):
    """(param_grads, input_grad) of the stage at `inp`, cond-gated like
    _gated_fwd."""
    def run(args):
        i, go = args
        _, vjp_fn = jax.vjp(stage_fn, pv, i)
        return vjp_fn(go)

    def zero(args):
        i, _ = args
        return (jax.tree_util.tree_map(
            lambda a: _varying(jnp.zeros_like(a), axis_name), pv),
            _varying(jnp.zeros(i.shape, i.dtype), axis_name))

    return lax.cond(active, run, zero, (inp, gout))


def pipeline_spmd_1f1b(block_fn, stage_params, x_mb, *, axis_name="pp",
                       remat_policy=None):
    """1F1B-scheduled pipeline (ref: fleet/meta_parallel/pipeline_parallel.py:230
    `forward_backward_pipeline`, the "1f1b scheduling strategy").

    Same contract as `pipeline_spmd`, but the backward pass is hand-scheduled
    instead of autodiff'd through the forward scan. Why: autodiff of the GPipe
    scan stores per-tick residuals for all T = M+S-1 ticks — activation
    residency O(M). Here the backward runs its own combined schedule: each tick
    does one forward (recomputing the activation stream) and one backward
    microbatch per stage, with a circular stash of at most K = 2S-1 in-flight
    stage *inputs* — residency O(S), independent of the microbatch count.

    Scheduling (stage s, tick t, microbatch indices):
      forward  of mb  fm = t - s
      backward of mb  bm = t - 2(S-1) + s     (same tick as fm on last stage)
      T = M + 2S - 2 ticks; stash slot = mb mod K, lifetime exactly <= K ticks.

    Cost: stage-input checkpointing (Megatron "full recompute" mode) — the
    backward recomputes each stage forward from the stashed input rather than
    stashing per-layer residuals, because vjp residuals would carry K copies of
    (cast) stage params. ~1 extra forward vs GPipe+autodiff, in exchange for
    O(S) instead of O(M) activation memory.
    """
    S = env.axis_size(axis_name)
    M = x_mb.shape[0]
    stage_fn = _stage_fn_of(block_fn, remat_policy)

    @jax.custom_vjp
    def pipe(sp, xm):
        return pipeline_spmd(block_fn, sp, xm, axis_name=axis_name)

    def pipe_fwd(sp, xm):
        return pipe(sp, xm), (sp, xm)

    def pipe_bwd(res, g):
        sp, xm = res
        local_params = jax.tree_util.tree_map(lambda a: a[0], sp)
        stage = lax.axis_index(axis_name)
        K = 2 * S - 1
        T = M + 2 * S - 2
        perm_down = [(i, (i + 1) % S) for i in range(S)]
        perm_up = [(i, (i - 1) % S) for i in range(S)]
        mb_shape = x_mb.shape[1:]

        def vv(a):
            return _varying(a, axis_name)

        stash0 = vv(jnp.zeros((K,) + mb_shape, xm.dtype))
        send_f0 = vv(jnp.zeros(mb_shape, xm.dtype))
        send_b0 = vv(jnp.zeros(mb_shape, g.dtype))
        pgrads0 = jax.tree_util.tree_map(
            lambda a: vv(jnp.zeros(a.shape, a.dtype)), local_params)
        gx0 = vv(jnp.zeros_like(xm))

        def tick(carry, t):
            stash, send_f, send_b, pgrads, gx = carry
            recv_f = lax.ppermute(send_f, axis_name, perm_down)
            recv_b = lax.ppermute(send_b, axis_name, perm_up)

            # ---- forward sub-tick: recompute the activation stream
            fm = t - stage
            f_act = jnp.logical_and(fm >= 0, fm < M)
            first_in = lax.dynamic_index_in_dim(
                xm, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, first_in, recv_f)
            out_f = _gated_fwd(stage_fn, axis_name, f_act, local_params, inp)
            slot_f = jnp.mod(fm, K)
            cur = lax.dynamic_index_in_dim(stash, slot_f, 0, keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_act, inp, cur), slot_f, 0)

            # ---- backward sub-tick
            bm = t - 2 * (S - 1) + stage
            b_act = jnp.logical_and(bm >= 0, bm < M)
            slot_b = jnp.mod(bm, K)
            stashed_in = lax.dynamic_index_in_dim(
                stash, slot_b, 0, keepdims=False)
            g_last = lax.dynamic_index_in_dim(
                g, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
            g_out = jnp.where(stage == S - 1, g_last.astype(send_b.dtype),
                              recv_b)
            gp, gi = _gated_vjp(stage_fn, axis_name, b_act, local_params,
                                stashed_in, g_out.astype(stashed_in.dtype))
            pgrads = jax.tree_util.tree_map(
                lambda acc, gg: acc + gg.astype(acc.dtype), pgrads, gp)
            write_gx = jnp.logical_and(b_act, stage == 0)
            cur_gx = lax.dynamic_index_in_dim(
                gx, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
            gx = lax.dynamic_update_index_in_dim(
                gx, jnp.where(write_gx, gi.astype(gx.dtype), cur_gx),
                jnp.clip(bm, 0, M - 1), 0)
            return (stash, out_f, gi.astype(send_b.dtype), pgrads, gx), None

        carry0 = (stash0, send_f0, send_b0, pgrads0, gx0)
        (_, _, _, pgrads, gx), _ = lax.scan(tick, carry0, jnp.arange(T))
        # grads wrt the [1, L, ...] per-device param slice; x grads live on
        # stage 0 only (shard_map psums replicated-input cotangents).
        g_sp = jax.tree_util.tree_map(lambda a: a[None], pgrads)
        # xm entered replicated (in_spec P()), so its cotangent must leave
        # replicated/invariant too: mask to stage 0's contribution and psum.
        gx = lax.psum(jnp.where(stage == 0, gx, jnp.zeros_like(gx)), axis_name)
        return g_sp, gx

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(stage_params, x_mb)


def pipeline_spmd_interleaved_1f1b(block_fn, stage_params, x_mb, *,
                                   num_virtual, axis_name="pp",
                                   remat_policy=None):
    """Interleaved ("virtual pipeline") 1F1B (ref: fleet/meta_parallel/
    pipeline_parallel.py:613 interleaved schedule / VPP).

    Device s hosts V = num_virtual chunks — virtual stages p = s, s+S, ...,
    s+(V-1)S of a flat S' = V*S stage pipeline. Per tick each device runs its
    active virtual-stage chunks (fwd of mb t-p, bwd of mb t-2(S'-1)+p),
    `lax.cond`-gated so inactive warmup/cooldown slots skip the matmuls
    (interleaving only pays off when idle slots are cheap). All V streams
    ride one stacked ppermute per direction; the lap boundary (device S-1 →
    device 0, lap v → v+1) is a roll of the stacked recv buffer.

    stage_params leaves: [1, V, L_chunk, ...] — this device's V chunks.
    x_mb: [M, mb...]; returns [M, mb...] like pipeline_spmd.
    """
    S = env.axis_size(axis_name)
    V = num_virtual
    Sv = V * S
    M = x_mb.shape[0]
    stage_fn = _stage_fn_of(block_fn, remat_policy)
    mb_shape = x_mb.shape[1:]
    perm_down = [(i, (i + 1) % S) for i in range(S)]
    perm_up = [(i, (i - 1) % S) for i in range(S)]

    def chunk_params(sp, v):
        return jax.tree_util.tree_map(lambda a: a[0, v], sp)

    def gated_fwd(active, pv, inp):
        return _gated_fwd(stage_fn, axis_name, active, pv, inp)

    @jax.custom_vjp
    def pipe(sp, xm):
        stage = lax.axis_index(axis_name)
        T = M + Sv - 1

        def vv(a):
            return _varying(a, axis_name)

        fsend0 = vv(jnp.zeros((V,) + mb_shape, xm.dtype))
        outputs0 = vv(jnp.zeros_like(xm))

        def tick(carry, t):
            fsend, outputs = carry
            recv = lax.ppermute(fsend, axis_name, perm_down)
            # lap boundary: device 0's lap v reads device S-1's lap v-1
            recv = jnp.where(stage == 0, jnp.roll(recv, 1, axis=0), recv)
            outs = []
            for v in range(V):
                p = stage + v * S
                fm = t - p
                active = jnp.logical_and(fm >= 0, fm < M)
                first_in = lax.dynamic_index_in_dim(
                    xm, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
                inp = recv[v]
                if v == 0:
                    inp = jnp.where(stage == 0, first_in, inp)
                outs.append(gated_fwd(active, chunk_params(sp, v), inp))
            out_last = outs[V - 1]
            out_idx = jnp.clip(t - (Sv - 1), 0, M - 1)
            write = jnp.logical_and(
                jnp.logical_and(stage == S - 1, t >= Sv - 1), t - (Sv - 1) < M)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, out_last, cur), out_idx, 0)
            return (jnp.stack(outs), outputs), None

        (_, outputs), _ = lax.scan(tick, (fsend0, outputs0), jnp.arange(T))
        masked = jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs))
        return lax.psum(masked, axis_name)

    def pipe_fwd(sp, xm):
        return pipe(sp, xm), (sp, xm)

    def pipe_bwd(res, g):
        sp, xm = res
        stage = lax.axis_index(axis_name)
        K = 2 * Sv - 1
        T = M + 2 * Sv - 2

        def vv(a):
            return _varying(a, axis_name)

        stash0 = vv(jnp.zeros((V, K) + mb_shape, xm.dtype))
        fsend0 = vv(jnp.zeros((V,) + mb_shape, xm.dtype))
        bsend0 = vv(jnp.zeros((V,) + mb_shape, g.dtype))
        pgrads0 = jax.tree_util.tree_map(
            lambda a: vv(jnp.zeros(a.shape[1:], a.dtype)), sp)  # [V, Lc, ...]
        gx0 = vv(jnp.zeros_like(xm))

        def gated_vjp(active, pv, inp, gout):
            return _gated_vjp(stage_fn, axis_name, active, pv, inp, gout)

        def tick(carry, t):
            stash, fsend, bsend, pgrads, gx = carry
            recv_f = lax.ppermute(fsend, axis_name, perm_down)
            recv_f = jnp.where(stage == 0, jnp.roll(recv_f, 1, axis=0), recv_f)
            recv_b = lax.ppermute(bsend, axis_name, perm_up)
            # lap boundary reversed: device S-1's lap v reads dev 0's lap v+1
            recv_b = jnp.where(stage == S - 1, jnp.roll(recv_b, -1, axis=0),
                               recv_b)

            f_outs, b_outs = [], []
            new_pgrads = []
            for v in range(V):
                p = stage + v * S
                pv = chunk_params(sp, v)
                # ---- forward sub-tick for chunk v
                fm = t - p
                f_act = jnp.logical_and(fm >= 0, fm < M)
                first_in = lax.dynamic_index_in_dim(
                    xm, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
                inp = recv_f[v]
                if v == 0:
                    inp = jnp.where(stage == 0, first_in, inp)
                f_outs.append(gated_fwd(f_act, pv, inp))
                slot_f = jnp.mod(fm, K)
                cur = lax.dynamic_index_in_dim(stash[v], slot_f, 0,
                                               keepdims=False)
                stash = stash.at[v].set(lax.dynamic_update_index_in_dim(
                    stash[v], jnp.where(f_act, inp, cur), slot_f, 0))

                # ---- backward sub-tick for chunk v
                bm = t - 2 * (Sv - 1) + p
                b_act = jnp.logical_and(bm >= 0, bm < M)
                slot_b = jnp.mod(bm, K)
                stashed_in = lax.dynamic_index_in_dim(stash[v], slot_b, 0,
                                                      keepdims=False)
                g_last = lax.dynamic_index_in_dim(
                    g, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
                gout = recv_b[v]
                if v == V - 1:
                    gout = jnp.where(stage == S - 1,
                                     g_last.astype(gout.dtype), gout)
                gp, gi = gated_vjp(b_act, pv, stashed_in,
                                   gout.astype(stashed_in.dtype))
                new_pgrads.append(gp)
                b_outs.append(gi.astype(bsend.dtype))
                if v == 0:
                    write_gx = jnp.logical_and(b_act, stage == 0)
                    cur_gx = lax.dynamic_index_in_dim(
                        gx, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
                    gx = lax.dynamic_update_index_in_dim(
                        gx, jnp.where(write_gx, gi.astype(gx.dtype), cur_gx),
                        jnp.clip(bm, 0, M - 1), 0)

            pgrads = jax.tree_util.tree_map(
                lambda acc, *gs: acc + jnp.stack(gs).astype(acc.dtype),
                pgrads, *new_pgrads)
            return (stash, jnp.stack(f_outs), jnp.stack(b_outs), pgrads,
                    gx), None

        carry0 = (stash0, fsend0, bsend0, pgrads0, gx0)
        (_, _, _, pgrads, gx), _ = lax.scan(tick, carry0, jnp.arange(T))
        g_sp = jax.tree_util.tree_map(lambda a: a[None], pgrads)
        gx = lax.psum(jnp.where(stage == 0, gx, jnp.zeros_like(gx)), axis_name)
        return g_sp, gx

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(stage_params, x_mb)


# ---------------------------------------------------------------------------
# explicit pp backend (FLAGS_comm_backend='pp=ring|fused'): the SAME schedules
# rewritten to run under a FULL-manual shard_map over every mesh axis. The
# partitioner never sees this region, so the `stage == k` selects operate on
# per-device shards — no replicated-then-repartitioned tensor exists for
# GSPMD to involuntarily rematerialize. Boundary sends are issued at the END
# of each scan tick (the ppermute start rides the ICI while the next tick's
# stage GEMMs run; the done lands where the next tick consumes it).
#
# Contract differences vs the gspmd schedules above:
#   * x_mb is the LOCAL batch shard [M, mb/dp, ...] (in_spec P(None, 'dp'...))
#     — not the replicated full microbatch array;
#   * the result is STAGE-MAJOR: [1, M, mb/dp, ...] per device, out_spec
#     P('pp', ...), and the caller slices stage S-1 outside the region. This
#     is load-bearing for autodiff: an out_spec that mentions 'pp' makes the
#     shard_map transpose hand each stage its own slice's cotangent verbatim
#     (an UNMENTIONED manual axis would divide the cotangent by S — observed);
#   * scan tick indices are explicitly int32: with jax_enable_x64 the default
#     int64 `jnp.arange` mixed with the int32 `lax.axis_index` produces
#     invalid partitioned HLO (s64/s32 compare) when the out_spec mentions
#     the manual axis.


def pipeline_ring_gpipe(block_fn, stage_params, x_mb, *, axis_name="pp",
                        wire_dtype=None, boundary=None):
    """Circular GPipe under full-manual: autodiff derives the backward
    (reversed-ring, reversed-time) schedule, including the transpose of the
    tick-end boundary send. `wire_dtype` compresses the boundary hop (e.g.
    bf16 wire under fp32 compute); `boundary` is the fused rung's hook —
    ``boundary(last_layer_params, h) -> (block_out, received)`` runs the
    stage's LAST layer with the boundary send fused into its final GEMM's
    epilogue (fused_collectives.fused_gemm_ppsend); the hook owns the hop,
    so no separate ppermute is issued for it."""
    S = env.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    T = M + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]
    local_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    wire = jnp.dtype(wire_dtype) if wire_dtype is not None else x_mb.dtype

    if boundary is None:
        full_stage_fn = _stage_fn_of(block_fn)

        def run_stage(act):
            out = full_stage_fn(local_params, act)
            recv = lax.ppermute(out.astype(wire), axis_name, perm)
            return out, recv
    else:
        head = jax.tree_util.tree_map(lambda a: a[:-1], local_params)
        last = jax.tree_util.tree_map(lambda a: a[-1], local_params)
        head_fn = _stage_fn_of(block_fn)

        def run_stage(act):
            h = head_fn(head, act)
            out, recv = boundary(last, h)
            return out, recv.astype(wire)

    outputs0 = jnp.zeros_like(x_mb)
    recv0 = jnp.zeros(x_mb.shape[1:], wire)

    def tick(carry, t):
        t = t.astype(stage.dtype)
        outputs, recv = carry
        mb_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        inp = jnp.where(stage == 0, first_in, recv.astype(x_mb.dtype))
        out, recv_next = run_stage(inp)
        out_idx = jnp.clip(t - (S - 1), 0, M - 1)
        cur = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        write = jnp.logical_and(stage == S - 1, t >= S - 1)
        outputs = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(write, out, cur), out_idx, 0)
        return (outputs, recv_next), None

    (outputs, _), _ = lax.scan(tick, (outputs0, recv0),
                               jnp.arange(T, dtype=jnp.int32))
    # stage-major result; the last ring hop's cotangent closes the loop in
    # the transpose — no masked-psum broadcast (its all-reduce is exactly
    # the replicated tensor this path exists to kill)
    return outputs[None]


def pipeline_ring_1f1b(block_fn, stage_params, x_mb, *, axis_name="pp",
                       wire_dtype=None, remat_policy=None):
    """1F1B under full-manual — `pipeline_spmd_1f1b`'s hand-scheduled
    backward (stash K=2S-1, combined fwd/bwd ticks, O(S) residency) with the
    explicit-backend contract: boundary activations ride a `wire_dtype` hop
    issued at tick end, cotangents ride the reversed ring the same way, and
    per-stage param grads accumulate in the PARAM dtype (fp32 master params
    give fp32 accumulation under a bf16 wire for free)."""
    S = env.axis_size(axis_name)
    M = x_mb.shape[0]
    stage_fn = _stage_fn_of(block_fn, remat_policy)
    wire = jnp.dtype(wire_dtype) if wire_dtype is not None else x_mb.dtype

    @jax.custom_vjp
    def pipe(sp, xm):
        return pipeline_ring_gpipe(block_fn, sp, xm, axis_name=axis_name,
                                   wire_dtype=wire_dtype)

    def pipe_fwd(sp, xm):
        return pipe(sp, xm), (sp, xm)

    def pipe_bwd(res, g):
        sp, xm = res
        g = g[0]  # stage-major [1, M, mb, ...] output cotangent, this shard
        local_params = jax.tree_util.tree_map(lambda a: a[0], sp)
        stage = lax.axis_index(axis_name)
        K = 2 * S - 1
        T = M + 2 * S - 2
        perm_down = [(i, (i + 1) % S) for i in range(S)]
        perm_up = [(i, (i - 1) % S) for i in range(S)]
        mb_shape = xm.shape[1:]

        stash0 = jnp.zeros((K,) + mb_shape, xm.dtype)
        recv_f0 = jnp.zeros(mb_shape, wire)
        recv_b0 = jnp.zeros(mb_shape, wire)
        pgrads0 = jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape, a.dtype), local_params)
        gx0 = jnp.zeros_like(xm)

        def tick(carry, t):
            t = t.astype(stage.dtype)
            stash, recv_f, recv_b, pgrads, gx = carry

            # ---- forward sub-tick: recompute the activation stream
            fm = t - stage
            f_act = jnp.logical_and(fm >= 0, fm < M)
            first_in = lax.dynamic_index_in_dim(
                xm, jnp.clip(fm, 0, M - 1), 0, keepdims=False)
            inp = jnp.where(stage == 0, first_in, recv_f.astype(xm.dtype))
            out_f = _gated_fwd(stage_fn, axis_name, f_act, local_params, inp)
            slot_f = jnp.mod(fm, K)
            cur = lax.dynamic_index_in_dim(stash, slot_f, 0, keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_act, inp, cur), slot_f, 0)

            # ---- backward sub-tick
            bm = t - 2 * (S - 1) + stage
            b_act = jnp.logical_and(bm >= 0, bm < M)
            slot_b = jnp.mod(bm, K)
            stashed_in = lax.dynamic_index_in_dim(
                stash, slot_b, 0, keepdims=False)
            g_last = lax.dynamic_index_in_dim(
                g, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
            g_out = jnp.where(stage == S - 1, g_last.astype(wire), recv_b)
            gp, gi = _gated_vjp(stage_fn, axis_name, b_act, local_params,
                                stashed_in, g_out.astype(stashed_in.dtype))
            pgrads = jax.tree_util.tree_map(
                lambda acc, gg: acc + gg.astype(acc.dtype), pgrads, gp)
            write_gx = jnp.logical_and(b_act, stage == 0)
            cur_gx = lax.dynamic_index_in_dim(
                gx, jnp.clip(bm, 0, M - 1), 0, keepdims=False)
            gx = lax.dynamic_update_index_in_dim(
                gx, jnp.where(write_gx, gi.astype(gx.dtype), cur_gx),
                jnp.clip(bm, 0, M - 1), 0)

            # ---- boundary sends, issued at tick end: both hops ride the
            # wire while the NEXT tick's stage fwd+bwd GEMMs run
            recv_f = lax.ppermute(out_f.astype(wire), axis_name, perm_down)
            recv_b = lax.ppermute(gi.astype(wire), axis_name, perm_up)
            return (stash, recv_f, recv_b, pgrads, gx), None

        carry0 = (stash0, recv_f0, recv_b0, pgrads0, gx0)
        (_, _, _, pgrads, gx), _ = lax.scan(tick, carry0,
                                            jnp.arange(T, dtype=jnp.int32))
        g_sp = jax.tree_util.tree_map(lambda a: a[None], pgrads)
        # xm entered as a batch shard replicated over 'pp' only; mask the
        # cotangent to stage 0's contribution WITHOUT a psum — shard_map's
        # transpose already psums over the in_spec-unmentioned pp axis
        gx = jnp.where(stage == 0, gx, jnp.zeros_like(gx))
        return g_sp, gx

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(stage_params, x_mb)


def vpp_storage_perm(L, S, V):
    """Stage-major storage order for interleaved VPP: storage slot
    s*(V*Lc)+v*Lc+p holds logical layer (v*S+s)*Lc+p. Stacked params
    pre-permuted this way shard over 'pp' as a plain contiguous split —
    no cross-device reshard at the shard_map boundary (the layout the
    swapaxes in run_pipeline would otherwise create on the fly)."""
    Lc = L // (S * V)
    assert Lc * S * V == L, f"layers {L} != pp {S} x interleave {V} x chunk"
    return [(v * S + s) * Lc + p
            for s in range(S) for v in range(V) for p in range(Lc)]


def run_pipeline(block_fn, stacked_params, x, num_microbatches, mesh=None,
                 axis_name="pp", data_spec=P(), schedule="gpipe",
                 interleave=1, vpp_stage_major=False, remat_policy=None,
                 backend=None, pp_param_specs=None, x_spec=None,
                 wire_dtype=None, boundary=None):
    """Host-side wrapper: shard_map(manual over 'pp', auto elsewhere).

    stacked_params: pytree, leaves [S * local_L, ...] stacked layer params.
    x: [B, ...] activations entering the pipelined blocks.
    Returns [B, ...] outputs of the last stage (broadcast to all stages).

    With ``vpp_stage_major`` the caller stores stacked params in
    `vpp_storage_perm` order so the interleaved reshape is contiguous and
    the 'pp' sharding of storage matches chunk placement exactly (avoids
    XLA's involuntary full rematerialization of every block param).

    ``backend`` 'ring'|'fused' (comm_backend.resolve_pp) switches to the
    FULL-manual explicit schedules (`pipeline_ring_*`): every mesh axis is
    bound, so ``pp_param_specs`` must give the stacked leaves' full specs
    (leading 'pp'; e.g. gpt_param_specs' blocks) and ``x_spec`` the batch
    activation spec — any axis they name is sharded INTO the region instead
    of replicated-then-repartitioned by the partitioner. ``boundary`` is the
    fused rung's last-GEMM hook (see pipeline_ring_gpipe); 'fused' without a
    boundary runs identically to 'ring'.
    """
    mesh = mesh or env.get_mesh()
    S = mesh.shape[axis_name]
    M = num_microbatches
    B = x.shape[0]
    V = interleave
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"

    if V > 1 and vpp_stage_major:
        def reshape_stages(a):
            Lc = a.shape[0] // (V * S)
            return a.reshape((S, V, Lc) + a.shape[1:])  # contiguous
    elif V > 1:
        # chunk c of V*S covers layers [c*Lc, (c+1)*Lc); device c%S, lap c//S
        def reshape_stages(a):
            Lc = a.shape[0] // (V * S)
            vs_major = a.reshape((V, S, Lc) + a.shape[1:])
            return jnp.swapaxes(vs_major, 0, 1)          # [S, V, Lc, ...]
    else:
        def reshape_stages(a):
            return a.reshape((S, a.shape[0] // S) + a.shape[1:])

    staged = jax.tree_util.tree_map(reshape_stages, stacked_params)
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda a: P("pp", *([None] * (a.ndim - 1))), staged)

    if backend in ("ring", "fused"):
        if V > 1:
            raise ValueError(
                "the explicit pp backend does not interleave virtual stages"
                " (comm_backend.resolve_pp gates this)")
        if pp_param_specs is not None:
            # stacked-leaf specs (leading 'pp' over [L, ...]) -> staged
            # [S, L/S, ...]: the layer dim splits in two, sharding unchanged
            param_specs = jax.tree_util.tree_map(
                lambda a, s: P("pp", None, *tuple(s)[1:]),
                staged, pp_param_specs)
        xs = tuple(x_spec) if x_spec is not None else ()
        if schedule == "1f1b":
            inner = functools.partial(
                pipeline_ring_1f1b, block_fn, axis_name=axis_name,
                wire_dtype=wire_dtype, remat_policy=remat_policy)
        else:
            if remat_policy is not None:
                raise ValueError(
                    "remat_policy requires the 1f1b schedule (the gpipe "
                    "autodiff path derives its own recompute from the scan)")
            inner = functools.partial(
                pipeline_ring_gpipe, block_fn, axis_name=axis_name,
                wire_dtype=wire_dtype, boundary=boundary)
        mapped = env.shard_map_compat(
            lambda p, xm: inner(p, xm), mesh=mesh,
            in_specs=(param_specs, P(None, *xs)),
            out_specs=P("pp", None, *xs), axis_names=None)
        out_smb = mapped(staged, x_mb)
        # stage-major [S, M, mb, ...]: slice the last stage's outputs (the
        # one cross-stage broadcast of the step, replacing the seed's
        # masked-psum of the whole output buffer every scan tick)
        out_mb = lax.index_in_dim(out_smb, S - 1, 0, keepdims=False)
        return out_mb.reshape((B,) + out_mb.shape[2:])

    if V > 1:
        assert schedule == "1f1b", "interleaving requires the 1f1b schedule"
        spmd = functools.partial(pipeline_spmd_interleaved_1f1b,
                                 num_virtual=V, remat_policy=remat_policy)
    elif schedule == "1f1b":
        spmd = functools.partial(pipeline_spmd_1f1b,
                                 remat_policy=remat_policy)
    else:
        if remat_policy is not None:
            raise ValueError(
                "remat_policy requires the 1f1b schedule (the gpipe autodiff "
                "path derives its own recompute from the scan)")
        spmd = pipeline_spmd
    inner = functools.partial(spmd, block_fn, axis_name=axis_name)
    mapped = env.shard_map_compat(
        lambda p, xm: inner(p, xm),
        mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
        axis_names=frozenset({axis_name}))
    out_mb = mapped(staged, x_mb)
    return out_mb.reshape((B,) + out_mb.shape[2:])


# ---------------------------------------------------------------------------
# static schedule ledger + per-step counters (profiler.pp_comm_counters —
# the pp-axis sibling of tp_overlap's mp ledger and grad_comm's dp ledger)


@dataclass
class PpStepRecord:
    """Per-device pp-axis boundary traffic of one executed step (fwd+bwd).
    ``bubble_fraction`` is the schedule's idle-slot estimate — gpipe
    (S-1)/(M+S-1), 1f1b (2S-2)/(M+2S-2) — not a measurement."""
    backend: str = "gspmd"       # the pp backend that produced this step
    schedule: str = "gpipe"
    stages: int = 1
    microbatches: int = 1
    boundary_bytes: int = 0      # wire bytes over the boundary hops
    ppermute_hops: int = 0       # explicit ppermutes issued (ring/fused)
    fused_dispatches: int = 0    # boundary Pallas kernel launches (fused)
    bubble_fraction: float = 0.0


def bubble_fraction(schedule, S, M):
    """Idle-slot fraction of the schedule at S stages, M microbatches."""
    if S <= 1:
        return 0.0
    if schedule == "1f1b":
        return (2 * S - 2) / (M + 2 * S - 2)
    return (S - 1) / (M + S - 1)


def gpt_pp_step_record(config, ppc, batch, seq, num_microbatches, S=None,
                       mp=1):
    """Ledger of one gpt_hybrid pipelined step. ``ppc`` is the resolved
    comm_backend.PpConfig or None (None = GSPMD schedule: backend label and
    bubble estimate only — the partitioner owns that wire traffic)."""
    import jax.numpy as _jnp
    S = int(ppc.n if ppc is not None else S)
    M = int(num_microbatches)
    sched = (ppc.schedule if ppc is not None
             else (getattr(config, "pp_schedule", "1f1b") or "1f1b"))
    rec = PpStepRecord(backend=ppc.backend if ppc is not None else "gspmd",
                       schedule=sched, stages=S, microbatches=M,
                       bubble_fraction=bubble_fraction(sched, S, M))
    if ppc is None:
        return rec
    compute = _jnp.dtype(config.compute_dtype or "float32")
    wire = _jnp.dtype(ppc.wire_dtype) if ppc.wire_dtype is not None \
        else compute
    # one boundary hop moves the LOCAL microbatch activation shard
    hop_bytes = (batch // M) * (seq // mp) * config.hidden_size \
        * wire.itemsize
    T_fwd = M + S - 1
    if sched == "1f1b":
        # fwd = the gpipe stream (custom-vjp primal), bwd = T=M+2S-2
        # combined ticks x (one activation hop down + one cotangent hop up)
        hops = T_fwd + 2 * (M + 2 * S - 2)
    else:
        hops = 2 * T_fwd  # autodiff'd transpose mirrors the fwd hops
    rec.boundary_bytes = hops * hop_bytes
    if ppc.backend == "fused" and ppc.fused_rdma:
        rec.fused_dispatches = 2 * T_fwd  # one boundary kernel per tick
        # the kernel epilogue's RDMA replaces the fwd/bwd boundary
        # ppermutes; only the 1f1b-style scheduling hops remain (none
        # on the gpipe schedule the fused rung runs)
        hops -= 2 * T_fwd
    rec.ppermute_hops = hops
    return rec


_pp_lock = threading.Lock()


def _zero_pp_counters():
    return {"steps": 0, "boundary_bytes": 0, "ppermute_hops": 0,
            "fused_dispatches": 0, "backend": {}, "schedule": "",
            "stages": 0, "microbatches": 0, "bubble_fraction": 0.0}


_pp_counters = _zero_pp_counters()


def record_pp_step(rec: PpStepRecord | None):
    if rec is None:
        return
    with _pp_lock:
        _pp_counters["steps"] += 1
        _pp_counters["boundary_bytes"] += rec.boundary_bytes
        _pp_counters["ppermute_hops"] += rec.ppermute_hops
        _pp_counters["fused_dispatches"] += rec.fused_dispatches
        _pp_counters["backend"]["pp"] = rec.backend
        _pp_counters["schedule"] = rec.schedule
        _pp_counters["stages"] = rec.stages
        _pp_counters["microbatches"] = rec.microbatches
        _pp_counters["bubble_fraction"] = rec.bubble_fraction


def pp_counters():
    with _pp_lock:
        out = dict(_pp_counters)
        out["backend"] = dict(out["backend"])
    return out


def reset_pp_counters():
    global _pp_counters
    with _pp_lock:
        _pp_counters = _zero_pp_counters()


# ---------------------------------------------------------------------------
# fleet-style API surface (ref: fleet/meta_parallel/parallel_layers/pp_layers.py)
class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer:
    """API-parity container describing a pipelined model. On TPU the pipeline
    executes via `run_pipeline` (scan+ppermute); this class assigns descs to
    stages and materializes the homogeneous middle blocks for stacking."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        self.descs = layers
        self.num_stages = num_stages or (env.get_mesh().shape.get("pp", 1)
                                         if env.get_mesh() else 1)
        self.loss_fn = loss_fn
        self._layers = [d.build_layer() if isinstance(d, LayerDesc) else d
                        for d in layers]

    def get_stage_from_index(self, idx):
        per = max(len(self._layers) // self.num_stages, 1)
        return min(idx // per, self.num_stages - 1)

    def forward(self, x):
        for l in self._layers:
            x = l(x) if callable(l) else l.forward(x)
        return x

    def __call__(self, x):
        return self.forward(x)

    def sublayers(self):
        return list(self._layers)

    def parameters(self):
        out = []
        for l in self._layers:
            if hasattr(l, "parameters"):
                out.extend(l.parameters())
        return out
