"""paddle.distributed.io (ref: python/paddle/distributed/io.py —
save/load for distributed persistables). Single-controller SPMD: sharded
arrays are globally addressable, so these reduce to framework.io with a
device_get that assembles global values."""
from __future__ import annotations

from ..framework.io import save, load  # noqa: F401


def save_persistables(executor, dirname, main_program=None, filename=None):
    """ref distributed/io.py save_persistables."""
    from ..static.extras import default_main_program, serialize_persistables
    import os
    prog = main_program or default_main_program()
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "persistables.pdparams")
    data = serialize_persistables(program=prog)
    with open(path, "wb") as f:
        f.write(data)
    return path


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..static.extras import default_main_program, deserialize_persistables
    import os
    prog = main_program or default_main_program()
    path = os.path.join(dirname, filename or "persistables.pdparams")
    with open(path, "rb") as f:
        deserialize_persistables(prog, f.read())
    return prog
