"""Activation recomputation (ref: python/paddle/distributed/fleet/recompute/).

Functional/jit path: `jax.checkpoint` (remat) — XLA drops the activations and
recomputes them in the backward, trading FLOPs for HBM exactly like the
reference's RecomputeFunction, but fused into the compiled program.

Eager path: a PyLayer that runs forward under no_grad and replays it with the
tape enabled inside backward.
"""
from __future__ import annotations

import jax

from ..tensor_impl import Tensor
from ..framework import state as _st
from ..framework.random import next_key, fork_rng


# remat policy presets, keyed per the "save matmul outputs" heuristic that
# works well on TPU (MXU results are expensive to recompute, elementwise cheap)
POLICIES = {
    "full": None,  # save nothing, recompute all
    "dots": jax.checkpoint_policies.checkpoint_dots,
    "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    "nothing": jax.checkpoint_policies.everything_saveable,
    # keep only the attention context (checkpoint_name'd in gpt_block_fn):
    # +B*S*H bf16 per layer, and backward skips the flash-forward rerun
    "save_attn": jax.checkpoint_policies.save_only_these_names("attn_ctx"),
}


def recompute(function, *args, policy=None, use_reentrant=True, **kwargs):
    """ref: paddle.distributed.fleet.utils.recompute(function, *args)."""
    if _st.in_functional_trace():
        # under to_static/TrainStep tracing: lower to jax.checkpoint
        from ..jit.functional import _unwrap, _wrap

        def pure(arg_arrays):
            wrapped = _wrap(arg_arrays)
            out = function(*wrapped) if isinstance(wrapped, tuple) else function(wrapped)
            return _unwrap(out)

        arg_arrays = _unwrap(tuple(args))
        ck = jax.checkpoint(pure, policy=POLICIES.get(policy, policy))
        return _wrap(ck(arg_arrays))

    # eager: PyLayer replay
    from ..autograd import PyLayer

    key = next_key()

    class _Recompute(PyLayer):
        @staticmethod
        def forward(ctx, *tensors):
            ctx.save_for_backward(*tensors)
            with _st.no_grad(), fork_rng(key):
                out = function(*tensors, **kwargs)
            return out

        @staticmethod
        def backward(ctx, *grads):
            saved = ctx.saved_tensor()
            detached = [t.detach() for t in saved]
            for t in detached:
                t.stop_gradient = False
            with _st.enable_grad(), fork_rng(key):
                out = function(*detached, **kwargs)
            outs = out if isinstance(out, (tuple, list)) else [out]
            from ..autograd.engine import run_backward
            run_backward(list(outs), list(grads))
            return tuple(t._grad for t in detached)

    return _Recompute.apply(*args)


def recompute_sequential(ctx, functions, *args):
    for fn in functions:
        args = (recompute(fn, *args),)
    return args[0]
