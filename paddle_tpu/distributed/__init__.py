"""paddle_tpu.distributed (ref: python/paddle/distributed/__init__.py)."""
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, world_size, ParallelEnv,
    set_mesh, get_mesh, create_hybrid_mesh, HYBRID_AXES,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, all_gather,
    all_gather_object, reduce_scatter, broadcast, scatter, alltoall,
    alltoall_single, send, recv, p2p_shift, barrier, wait, reduce,
)
from . import fleet  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, reshard, shard_layer,
    shard_optimizer, dtensor_from_fn, dtensor_from_local, to_static, DistModel,
)
from .pipeline import pipeline_spmd, run_pipeline, PipelineLayer, LayerDesc  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention, ring_attention_spmd, ulysses_attention, ulysses_attention_spmd,
)
from .recompute import recompute  # noqa: F401
from . import elastic  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from .fleet.mp_layers import split  # noqa: F401


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Single-controller SPMD: all devices are driven by this process, so spawn
    degenerates to a direct call (ref: distributed/spawn.py launches N procs)."""
    func(*args)


def launch():
    raise NotImplementedError("use `python your_script.py` — single-controller "
                              "SPMD drives all TPU chips from one process")


def get_backend():
    return "xla"


def is_initialized():
    from . import env as _env
    return _env.is_initialized()
