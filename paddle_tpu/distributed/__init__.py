"""paddle_tpu.distributed (ref: python/paddle/distributed/__init__.py)."""
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, world_size, ParallelEnv,
    set_mesh, get_mesh, create_hybrid_mesh, HYBRID_AXES,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, all_gather,
    all_gather_object, reduce_scatter, broadcast, scatter, alltoall,
    alltoall_single, send, recv, p2p_shift, barrier, wait, reduce,
)
from . import fleet  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, reshard, shard_layer,
    shard_optimizer, dtensor_from_fn, dtensor_from_local, to_static, DistModel,
)
from .auto_parallel_static import Engine, Strategy  # noqa: F401
from .pipeline import pipeline_spmd, run_pipeline, PipelineLayer, LayerDesc  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention, ring_attention_spmd, ulysses_attention, ulysses_attention_spmd,
)
from .recompute import recompute  # noqa: F401
from . import elastic  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from . import grad_comm  # noqa: F401
from . import tp_overlap  # noqa: F401
from .fleet.mp_layers import split  # noqa: F401


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Single-controller SPMD: all devices are driven by this process, so spawn
    degenerates to a direct call (ref: distributed/spawn.py launches N procs)."""
    func(*args)


def launch():
    raise NotImplementedError("use `python your_script.py` — single-controller "
                              "SPMD drives all TPU chips from one process")


def get_backend():
    return "xla"


def is_initialized():
    from . import env as _env
    return _env.is_initialized()


class ParallelMode:
    """Parallelism mode ids (ref distributed/parallel.py ParallelMode)."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def gloo_init_parallel_env(*a, **k):
    """Gloo is the reference's CPU rendezvous; single-controller SPMD needs
    none (ref distributed/parallel.py gloo_init_parallel_env)."""
    return None


def gloo_barrier():
    from .collective import barrier
    barrier()


def gloo_release():
    return None


def _ps_era(name, hint):
    class _Stub:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"{name} configures parameter-server sparse tables "
                f"(ref incubate/distributed/fleet); on TPU use {hint}")
    _Stub.__name__ = name
    return _Stub


# parameter-server sparse-table config & dataset feeders: PS async training
# is superseded by sharded SPMD (SURVEY.md out-of-scope list); the names
# raise with guidance instead of silently half-working
CountFilterEntry = _ps_era("CountFilterEntry", "dense embeddings + ZeRO")
ProbabilityEntry = _ps_era("ProbabilityEntry", "dense embeddings + ZeRO")
ShowClickEntry = _ps_era("ShowClickEntry", "dense embeddings + ZeRO")
InMemoryDataset = _ps_era("InMemoryDataset", "paddle_tpu.io.DataLoader")
QueueDataset = _ps_era("QueueDataset", "paddle_tpu.io.DataLoader")

from .collective import (  # noqa: E402,F401
    gather, isend, irecv, broadcast_object_list, scatter_object_list,
    destroy_process_group, is_available,
)
from . import io  # noqa: E402,F401
