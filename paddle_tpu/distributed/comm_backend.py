"""Per-axis communication-schedule backend selection (FLAGS_comm_backend).

The collective schedule of each mesh axis is a pluggable backend:

  * ``gspmd`` — whole collectives, emitted by the SPMD partitioner (the
    seed's schedule; bitwise-untouched default);
  * ``ring``  — scheduling-level decomposition: the collective splits into
    per-chunk ``ppermute`` hops with compute issued on chunk arrival
    (``tp_overlap.ring_ag_gemm``/``gemm_ring_rs`` on the mp axis,
    ``grad_comm``'s explicit bucketed RS/AG schedule on the dp axis);
  * ``fused`` — kernel-level fusion: Pallas kernels where each grid step
    DMAs the next remote chunk while the current chunk's tile GEMM runs,
    and the reduce-scatter epilogue accumulates partial tiles directly
    into the scatter destination (``ops/pallas_kernels/fused_collectives``)
    — no intermediate full-size HBM buffer is ever materialized.

``FLAGS_comm_backend`` is a comma-separated ``axis=backend`` list (e.g.
``"mp=fused,dp=ring"``); a bare backend name applies to every axis. The
empty default hands control to the legacy flags (``FLAGS_mp_overlap`` ->
``mp=ring``; ``FLAGS_grad_comm``/``FLAGS_weight_update_sharding`` ->
``dp=ring``) so existing configurations are untouched. ``resolve``-time
eligibility checks degrade an ineligible selection one rung (``fused`` ->
``ring`` -> ``gspmd``) with a once-per-reason warning that names the exact
flag setting that would fix the bail.
"""
from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

BACKENDS = ("gspmd", "ring", "fused")

_warned = set()


def _warn_once(key, msg):
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg)


def _flags():
    from .. import flags as _f
    return _f._FLAGS


def parse(spec):
    """``"mp=fused,dp=ring"`` | ``"fused"`` | dict -> {axis: backend}.

    Unknown backends/garbage entries warn once and are dropped (the axis
    falls back to its legacy-flag default) — scripts written against a
    newer flag vocabulary must not crash the step."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        items = list(spec.items())
    else:
        items = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                axis, _, backend = part.partition("=")
                items.append((axis.strip(), backend.strip()))
            else:
                items.append((None, part))  # bare backend: every axis
    out = {}
    for axis, backend in items:
        if backend not in BACKENDS:
            _warn_once(("backend", axis, backend),
                       f"FLAGS_comm_backend names unknown backend "
                       f"{backend!r} for axis {axis or '*'}; valid backends "
                       f"are {'/'.join(BACKENDS)} — entry ignored")
            continue
        if axis is None:
            for a in ("dp", "mp"):
                out[a] = backend
        else:
            out[axis] = backend
    return out


def requested(axis):
    """The backend FLAGS_comm_backend names for ``axis``, or None when the
    flag leaves this axis to the legacy flags."""
    return parse(_flags().get("FLAGS_comm_backend", "")).get(axis)


def serving_requested():
    """The serving engine's mp rung from ``FLAGS_comm_backend`` (None when
    the flag leaves mp alone — the engine then defaults to ``gspmd``).
    Serving interprets the rungs over its GATHER-ONLY schedule
    (``tp_overlap.resolve_serving``): ``gspmd`` = whole all-gather
    collectives, ``ring`` = ppermute decomposition, ``fused`` = Pallas
    in-kernel rings (``fused_gemm_ag`` on the column-parallel projections,
    ``fused_ag_bucket`` on the context/activation gathers). All rungs are
    bitwise-identical — the backend choice moves bytes differently, never
    changes math."""
    return requested("mp")


def fused_mesh_ok(mesh):
    """Interpret-mode remote DMA (jax<0.5 discharge rule) supports exactly
    ONE named mesh axis; on a real TPU the kernels compute flat logical
    device ids themselves and any full-manual mesh works. (Convenience
    alias of ops.pallas_kernels.fused_collectives.supported.)"""
    from ..ops.pallas_kernels import fused_collectives as _fc
    return _fc.supported(mesh)[0]
