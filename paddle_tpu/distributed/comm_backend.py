"""Per-axis communication-schedule backend selection (FLAGS_comm_backend).

The collective schedule of each mesh axis is a pluggable backend:

  * ``gspmd`` — whole collectives, emitted by the SPMD partitioner (the
    seed's schedule; bitwise-untouched default);
  * ``ring``  — scheduling-level decomposition: the collective splits into
    per-chunk ``ppermute`` hops with compute issued on chunk arrival
    (``tp_overlap.ring_ag_gemm``/``gemm_ring_rs`` on the mp axis,
    ``grad_comm``'s explicit bucketed RS/AG schedule on the dp axis);
  * ``fused`` — kernel-level fusion: Pallas kernels where each grid step
    DMAs the next remote chunk while the current chunk's tile GEMM runs,
    and the reduce-scatter epilogue accumulates partial tiles directly
    into the scatter destination (``ops/pallas_kernels/fused_collectives``)
    — no intermediate full-size HBM buffer is ever materialized.

The pp axis binds the PIPELINE schedule the same way (``resolve_pp``):
``ring`` runs the 1F1B/GPipe scan full-manual with ppermute boundary sends
issued at tick end (overlapping the next microbatch's stage compute);
``fused`` additionally runs each stage's last GEMM as a Pallas kernel whose
epilogue issues the boundary RDMA (``fused_gemm_ppsend``).

``FLAGS_comm_backend`` is a comma-separated ``axis=backend`` list (e.g.
``"mp=fused,dp=ring"``); a bare backend name applies to every axis. The
empty default hands control to the legacy flags (``FLAGS_mp_overlap`` ->
``mp=ring``; ``FLAGS_grad_comm``/``FLAGS_weight_update_sharding`` ->
``dp=ring``) so existing configurations are untouched. ``resolve``-time
eligibility checks degrade an ineligible selection one rung (``fused`` ->
``ring`` -> ``gspmd``) with a once-per-reason warning that names the exact
flag setting that would fix the bail.
"""
from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

BACKENDS = ("gspmd", "ring", "fused")

_warned = set()


def _warn_once(key, msg):
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg)


def _flags():
    from .. import flags as _f
    return _f._FLAGS


def parse(spec):
    """``"mp=fused,dp=ring"`` | ``"fused"`` | dict -> {axis: backend}.

    Unknown backends/garbage entries warn once and are dropped (the axis
    falls back to its legacy-flag default) — scripts written against a
    newer flag vocabulary must not crash the step."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        items = list(spec.items())
    else:
        items = []
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            if "=" in part:
                axis, _, backend = part.partition("=")
                items.append((axis.strip(), backend.strip()))
            else:
                items.append((None, part))  # bare backend: every axis
    out = {}
    for axis, backend in items:
        if backend not in BACKENDS:
            _warn_once(("backend", axis, backend),
                       f"FLAGS_comm_backend names unknown backend "
                       f"{backend!r} for axis {axis or '*'}; valid backends "
                       f"are {'/'.join(BACKENDS)} — entry ignored")
            continue
        if axis is None:
            for a in ("dp", "mp", "pp"):
                out[a] = backend
        else:
            out[axis] = backend
    return out


def requested(axis):
    """The backend FLAGS_comm_backend names for ``axis``, or None when the
    flag leaves this axis to the legacy flags."""
    return parse(_flags().get("FLAGS_comm_backend", "")).get(axis)


def serving_requested():
    """The serving engine's mp rung from ``FLAGS_comm_backend`` (None when
    the flag leaves mp alone — the engine then defaults to ``gspmd``).
    Serving interprets the rungs over its GATHER-ONLY schedule
    (``tp_overlap.resolve_serving``): ``gspmd`` = whole all-gather
    collectives, ``ring`` = ppermute decomposition, ``fused`` = Pallas
    in-kernel rings (``fused_gemm_ag`` on the column-parallel projections,
    ``fused_ag_bucket`` on the context/activation gathers). All rungs are
    bitwise-identical — the backend choice moves bytes differently, never
    changes math."""
    return requested("mp")


def fused_mesh_ok(mesh):
    """Interpret-mode remote DMA (jax<0.5 discharge rule) supports exactly
    ONE named mesh axis; on a real TPU the kernels compute flat logical
    device ids themselves and any full-manual mesh works. (Convenience
    alias of ops.pallas_kernels.fused_collectives.supported.)"""
    from ..ops.pallas_kernels import fused_collectives as _fc
    return _fc.supported(mesh)[0]


# ---------------------------------------------------------------------------
# pp axis: explicit pipeline-boundary schedule (FLAGS_comm_backend='pp=...')
#
#   * gspmd — the seed pipeline: partial-manual shard_map over 'pp' only,
#     microbatches replicated into the region, the partitioner placing the
#     `stage == k` selects (and involuntarily rematerializing around them);
#   * ring  — FULL-manual shard_map over every mesh axis; the schedule's
#     boundary sends are explicit `ppermute`s issued at the END of each
#     scan tick so the hop rides the ICI while the next tick's stage GEMMs
#     run. No tensor in the region is replicated-then-repartitioned, so
#     the partitioner never sees the stage selects at all — the
#     "[SPMD] Involuntary full rematerialization" warnings die structurally;
#   * fused — ring, plus the LAST GEMM of each stage runs as a Pallas
#     kernel whose epilogue issues the boundary RDMA directly
#     (fused_collectives.fused_gemm_ppsend, the fused_gemm_ag pattern) with
#     a custom VJP so the backward boundary hop is fused too.


from dataclasses import dataclass


@dataclass(frozen=True)
class PpConfig:
    """Static pp-axis schedule of one pipelined step (hashable — keys the
    trace-time resolution memo and the step-record builders)."""
    axis: str            # mesh axis name ("pp")
    n: int               # stage count
    backend: str         # "ring" | "fused" (gspmd resolves to None, not this)
    schedule: str        # "gpipe" | "1f1b" — what the explicit path RUNS
    wire_dtype: object   # boundary wire dtype, or None = compute dtype
    fused_rdma: bool     # fused kernels may issue real remote DMA here

    def kernel_meta(self, mesh):
        if self.backend != "fused":
            return None
        from ..ops.pallas_kernels import fused_collectives as _fc
        return _fc.meta_for(mesh, self.axis)


def pp_requested():
    """The pp-axis backend FLAGS_comm_backend names (None = legacy gspmd)."""
    return requested("pp")


def pp_explicit_requested():
    return requested("pp") in ("ring", "fused")


def resolve_pp(config, mesh, batch=None, num_microbatches=1, sp=None):
    """Decide whether the explicit pipeline schedule applies to this step.

    Returns PpConfig or None (None = the seed GSPMD pipeline, byte-identical
    to flags-off). `sp` is the step's resolved tp_overlap.SPConfig (or None):
    the full-manual region binds EVERY mesh axis, so an active mp axis is
    only composable when its schedule is ALSO explicit. Every bail warns
    once naming the exact flag setting that would fix it."""
    import jax.numpy as jnp

    req = requested("pp")
    if req in (None, "gspmd"):
        return None
    if mesh is None:
        return None
    pp = mesh.shape.get("pp", 1)
    if pp <= 1:
        return None
    backend = req

    def bail(key, msg):
        _warn_once(key, msg + " — falling back to the GSPMD pp schedule")
        return None

    if getattr(config, "pp_interleave", 1) > 1:
        return bail("pp-vpp", "the explicit pp schedule does not interleave "
                    "virtual stages yet; set config.pp_interleave=1 (or keep "
                    "VPP with FLAGS_comm_backend='pp=gspmd')")
    if getattr(config, "zero3_params", False):
        return bail("pp-zero3", "ZeRO stage-3 FSDP params need the GSPMD "
                    "per-layer all-gather inside the stage scan, which a "
                    "full-manual region cannot emit; set zero_stage=1 (host "
                    "offload of optimizer moments composes either way)")
    mp = mesh.shape.get("mp", 1)
    if mp > 1 and sp is None:
        return bail(("pp-mp", mp), "the explicit pp schedule binds the whole "
                    "mesh manually, so an active mp axis needs an explicit "
                    "mp schedule too; set FLAGS_comm_backend='mp=ring,pp="
                    + backend + "' (FLAGS_sequence_parallel layout implied)")
    extra = [a for a in mesh.axis_names
             if a not in ("dp", "mp", "pp") and mesh.shape.get(a, 1) > 1]
    if extra:
        return bail(("pp-axes", tuple(extra)),
                    f"the explicit pp schedule binds the whole mesh "
                    f"manually; axes {extra} must be size 1 (set them to 1 "
                    f"in create_hybrid_mesh, or keep "
                    f"FLAGS_comm_backend='pp=gspmd')")
    M = int(num_microbatches)
    dp = mesh.shape.get("dp", 1)
    if batch is not None:
        if batch % M:
            return bail(("pp-mb", batch, M),
                        f"batch {batch} not divisible by num_microbatches="
                        f"{M} (choose a microbatch count dividing the "
                        f"global batch)")
        if dp > 1 and (batch // M) % dp:
            return bail(("pp-mb-dp", batch, M, dp),
                        f"microbatch size {batch // M} not divisible by "
                        f"dp={dp}; the explicit schedule shards each "
                        f"microbatch over dp — lower num_microbatches (or "
                        f"the dp degree)")
    schedule = getattr(config, "pp_schedule", "1f1b") or "1f1b"
    if backend == "fused" and sp is not None:
        _warn_once(("pp-fused-mp", mp),
                   "pp=fused boundary kernels take the plain (mp=1) block "
                   "tail; with an explicit mp schedule active the boundary "
                   "hop runs as FLAGS_comm_backend='pp=ring' instead")
        backend = "ring"
    fused_rdma = False
    if backend == "fused":
        if schedule == "1f1b":
            # at a combined 1F1B tick the cotangent a stage consumes was
            # produced one tick EARLIER on its down-neighbor — the hop is a
            # schedule-level scan carry, which an intra-vjp kernel epilogue
            # cannot express. The fused rung therefore runs the gpipe
            # autodiff schedule (its custom VJP fuses the hop transposes).
            _warn_once("pp-fused-1f1b",
                       "pp=fused runs the gpipe autodiff schedule (the 1f1b "
                       "combined tick needs a scan-carried cotangent hop); "
                       "set FLAGS_comm_backend='pp=ring' to keep the 1f1b "
                       "schedule explicit")
            schedule = "gpipe"
        from ..ops.pallas_kernels import fused_collectives as _fc
        H = getattr(config, "hidden_size", 0)
        ok, why = _fc.supported(mesh, shapes=(H,) if H else (), why="pp axis")
        if ok:
            fused_rdma = True
        else:
            _warn_once(("pp-fused-rdma", tuple(mesh.axis_names)),
                       f"fused pp boundary RDMA unavailable: {why} — the "
                       f"boundary runs the unfused GEMM tail with the hop "
                       f"as an explicit ppermute (a single-axis "
                       f"create_single_axis_mesh('pp', n) enables the full "
                       f"RDMA kernel in interpret mode)")
    # boundary wire dtype: grad_comm's wire-dtype vocabulary, 'auto' =
    # the compute dtype (bf16 compute wires bf16 natively; stage grads
    # accumulate fp32 in the 1f1b tick regardless — see pipeline.py)
    raw = _flags().get("FLAGS_pp_wire_dtype", "auto")
    wire = None
    if raw not in ("auto", None, ""):
        from .grad_comm import _WIRE_DTYPES
        wire = _WIRE_DTYPES.get(raw, "?")
        if wire == "?" or wire is jnp.int8:
            _warn_once(("pp-wire", raw),
                       f"FLAGS_pp_wire_dtype={raw!r} unsupported for the "
                       f"boundary wire (float32/bfloat16/auto) — using the "
                       f"compute dtype; set FLAGS_pp_wire_dtype='bfloat16' "
                       f"for the compressed wire")
            wire = None
    if backend == "fused" and wire is not None:
        _warn_once(("pp-fused-wire", raw),
                   "pp=fused issues the boundary RDMA from the GEMM epilogue "
                   "at the compute dtype (a cast copy would reintroduce the "
                   "buffer the kernel exists to remove) — "
                   "FLAGS_pp_wire_dtype ignored; set "
                   "FLAGS_comm_backend='pp=ring' to compress the wire")
        wire = None
    return PpConfig(axis="pp", n=int(pp), backend=backend, schedule=schedule,
                    wire_dtype=wire, fused_rdma=fused_rdma)
