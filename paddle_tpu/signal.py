"""Signal processing: frame, overlap_add, stft, istft
(ref: python/paddle/signal.py).

TPU-native design: framing is a static gather (indices computed at trace
time, so the whole STFT — pad → frame → window → rfft — fuses into one XLA
program with an MXU-friendly batched FFT); overlap-add is its transpose, a
scatter-add. Everything is jit/grad compatible with static shapes.
"""
from __future__ import annotations

import jax.numpy as jnp

from .dispatch import apply
from .tensor_impl import as_tensor_data

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_idx(seq_len, frame_length, hop_length, axis):
    if frame_length > seq_len:
        raise ValueError(
            f"Attribute frame_length should be less equal than sequence length, "
            f"but got ({frame_length}) > ({seq_len}).")
    n_frames = 1 + (seq_len - frame_length) // hop_length
    offsets = jnp.arange(n_frames) * hop_length
    within = jnp.arange(frame_length)
    if axis == -1 or axis is None:
        # output (..., frame_length, n_frames)
        return within[:, None] + offsets[None, :]
    # axis == 0: output (n_frames, frame_length, ...)
    return offsets[:, None] + within[None, :]


def _frame_data(a, frame_length, hop_length, axis):
    # axis == 0 must win for 1-D inputs (where 0 is also the last axis):
    # the layouts differ — (num_frames, frame_length) vs (frame_length, num_frames)
    if axis == 0:
        idx = _frame_idx(a.shape[0], frame_length, hop_length, 0)
        return a[idx]
    elif axis in (-1, a.ndim - 1):
        idx = _frame_idx(a.shape[-1], frame_length, hop_length, -1)
        return a[..., idx]
    raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice input into overlapping frames.

    axis=-1: (..., seq_len) -> (..., frame_length, num_frames)
    axis=0:  (seq_len, ...) -> (num_frames, frame_length, ...)
    """
    if hop_length < 1:
        raise ValueError(f"Unexpected hop_length: {hop_length}. It should be an "
                         f"positive integer.")
    return apply(_frame_data, x, frame_length=frame_length,
                 hop_length=hop_length, axis=axis)


def _overlap_add_data(a, hop_length, axis):
    if axis in (-1, a.ndim - 1):
        frame_length, n_frames = a.shape[-2], a.shape[-1]
        seq = (n_frames - 1) * hop_length + frame_length
        pos = _frame_idx(seq, frame_length, hop_length, -1)  # (flen, nf)
        out = jnp.zeros(a.shape[:-2] + (seq,), a.dtype)
        return out.at[..., pos].add(a)
    elif axis == 0:
        n_frames, frame_length = a.shape[0], a.shape[1]
        seq = (n_frames - 1) * hop_length + frame_length
        pos = _frame_idx(seq, frame_length, hop_length, 0)  # (nf, flen)
        out = jnp.zeros((seq,) + a.shape[2:], a.dtype)
        return out.at[pos].add(a)
    raise ValueError(f"Unexpected axis: {axis}. It should be 0 or -1.")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct a signal from overlapping frames (transpose of `frame`)."""
    if hop_length < 1:
        raise ValueError(f"Unexpected hop_length: {hop_length}. It should be an "
                         f"positive integer.")
    return apply(_overlap_add_data, x, hop_length=hop_length, axis=axis)


def _resolve_window(window, win_length, n_fft, dtype):
    if window is None:
        w = jnp.ones((win_length,), dtype)
    else:
        w = jnp.asarray(as_tensor_data(window), dtype)
        if w.ndim != 1 or w.shape[0] != win_length:
            raise ValueError(
                f"expected a 1D window tensor of size equal to win_length"
                f"({win_length}), but got window with shape {w.shape}.")
    if win_length < n_fft:  # center-pad window to n_fft
        pad_l = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad_l, n_fft - win_length - pad_l))
    return w


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform.

    Input (..., seq_len) real or complex; output (..., n_fft//2+1, num_frames)
    when onesided else (..., n_fft, num_frames), complex.
    """
    hop_length = hop_length if hop_length is not None else n_fft // 4
    win_length = win_length if win_length is not None else n_fft

    def _stft(a, w):
        if jnp.iscomplexobj(a):
            one = False
        else:
            one = onesided
        y = a
        if center:
            pad = n_fft // 2
            cfg = [(0, 0)] * (y.ndim - 1) + [(pad, pad)]
            y = jnp.pad(y, cfg, mode=pad_mode)
        frames = _frame_data(y, n_fft, hop_length, -1)  # (..., n_fft, nf)
        frames = frames * w[:, None].astype(frames.dtype)
        if one:
            spec = jnp.fft.rfft(frames, axis=-2)
        else:
            spec = jnp.fft.fft(frames, axis=-2)
        if normalized:
            spec = spec * (float(n_fft) ** -0.5)
        return spec

    a = as_tensor_data(x)
    w = _resolve_window(window, win_length, n_fft,
                        jnp.real(jnp.zeros((), a.dtype)).dtype)
    if jnp.iscomplexobj(a) and onesided:
        raise ValueError("onesided is not supported for complex input")
    return apply(_stft, x, w)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with least-squares window compensation."""
    hop_length = hop_length if hop_length is not None else n_fft // 4
    win_length = win_length if win_length is not None else n_fft

    def _istft(spec, w):
        n_frames = spec.shape[-1]
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(spec, axis=-2)
            if not return_complex:
                frames = jnp.real(frames)
        if normalized:
            frames = frames * (float(n_fft) ** 0.5)
        wf = w.astype(frames.real.dtype)
        frames = frames * wf[:, None]
        sig = _overlap_add_data(frames, hop_length, -1)
        # window envelope for least-squares inversion
        env = _overlap_add_data(
            jnp.broadcast_to((wf * wf)[:, None], (n_fft, n_frames)),
            hop_length, -1)
        sig = sig / jnp.where(env > 1e-11, env, 1.0)
        expected = n_fft + hop_length * (n_frames - 1)
        start = n_fft // 2 if center else 0
        if length is not None:
            end = start + length
        else:
            end = expected - (n_fft // 2 if center else 0)
        return sig[..., start:end]

    a = as_tensor_data(x)
    if not jnp.iscomplexobj(a):
        raise ValueError("istft expects a complex spectrum input")
    w = _resolve_window(window, win_length, n_fft, jnp.float64
                        if a.dtype == jnp.complex128 else jnp.float32)
    return apply(_istft, x, w)
