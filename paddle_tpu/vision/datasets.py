"""Vision datasets (ref: python/paddle/vision/datasets/*).

Zero-egress environment: the loaders read the reference's on-disk formats when
files are present (MNIST idx-gzip, Cifar pickle-tar) and otherwise fall back to
a deterministic synthetic dataset with the right shapes/classes, so training
examples and tests run anywhere.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset


class FakeData(Dataset):
    """Synthetic labeled images (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx % 2 ** 31)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = idx % self.num_classes
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)


class MNIST(Dataset):
    """ref: python/paddle/vision/datasets/mnist.py — idx/gzip reader with
    synthetic fallback."""

    NUM_CLASSES = 10

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    def _load(self, image_path, label_path):
        if image_path and os.path.exists(image_path) and label_path and \
                os.path.exists(label_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8)
            return images.astype(np.float32)[..., None], labels.astype(np.int64)
        # synthetic fallback: blob-per-class images, learnable by LeNet
        n = 2048 if self.mode == "train" else 512
        rng = np.random.RandomState(42 if self.mode == "train" else 7)
        labels = rng.randint(0, 10, n).astype(np.int64)
        images = np.zeros((n, 28, 28, 1), np.float32)
        for i, lab in enumerate(labels):
            r, c = 4 + (lab // 5) * 10, 4 + (lab % 5) * 4
            images[i, r:r + 8, c:c + 4, 0] = 1.0
            images[i] += rng.randn(28, 28, 1).astype(np.float32) * 0.1
        return images, labels

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(data_file)

    def _load(self, data_file):
        if data_file and os.path.exists(data_file):
            images, labels = [], []
            with tarfile.open(data_file) as tf:
                names = [m for m in tf.getnames()
                         if ("data_batch" in m if self.mode == "train"
                             else "test_batch" in m)]
                for name in sorted(names):
                    d = pickle.load(tf.extractfile(name), encoding="bytes")
                    images.append(d[b"data"].reshape(-1, 3, 32, 32))
                    labels.extend(d[b"labels"])
            return (np.concatenate(images).transpose(0, 2, 3, 1).astype(np.float32),
                    np.asarray(labels, np.int64))
        n = 2048 if self.mode == "train" else 512
        rng = np.random.RandomState(0 if self.mode == "train" else 1)
        labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)
        images = rng.rand(n, 32, 32, 3).astype(np.float32)
        for i, lab in enumerate(labels):
            images[i, :, :, lab % 3] += lab / self.NUM_CLASSES
        return images, labels

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int64)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class DatasetFolder(Dataset):
    """ref: vision/datasets/folder.py — directory-of-class-folders images."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.extensions = extensions or (".npy",)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.endswith(tuple(self.extensions)):
                    self.samples.append((os.path.join(cdir, fname),
                                         self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = np.load(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)


ImageFolder = DatasetFolder


class Flowers(Dataset):
    """Oxford-102 flowers (ref vision/datasets/flowers.py). Offline:
    deterministic synthetic blobs with the right shape/label space."""
    NUM_CLASSES = 102

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 1020 if mode == "train" else 256
        rng = np.random.RandomState(0 if mode == 'train' else 1)
        self.labels = rng.randint(0, self.NUM_CLASSES, n).astype(np.int64)
        self.images = rng.rand(n, 64, 64, 3).astype(np.float32)
        for i, lab in enumerate(self.labels):
            self.images[i, :, :, lab % 3] += (lab % 17) / 17.0

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (ref vision/datasets/voc2012.py).
    Offline: synthetic image/mask pairs with the 21-class label space."""
    NUM_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 512 if mode == "train" else 128
        rng = np.random.RandomState(2 if mode == 'train' else 3)
        self.images = rng.rand(n, 64, 64, 3).astype(np.float32)
        self.masks = rng.randint(0, self.NUM_CLASSES,
                                 (n, 64, 64)).astype(np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]
