"""paddle_tpu.vision.models (ref: python/paddle/vision/models/__init__.py)."""
from .lenet import LeNet  # noqa: F401
from .alexnet import AlexNet, alexnet  # noqa: F401
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34, resnet50,
    resnet101, resnet152, resnext50_32x4d, resnext101_32x4d,
    resnext50_64x4d, resnext101_64x4d, resnext152_32x4d, resnext152_64x4d,
    wide_resnet50_2, wide_resnet101_2,
)
from .mobilenet import (  # noqa: F401
    MobileNetV1, MobileNetV2, MobileNetV3Large, MobileNetV3Small,
    mobilenet_v1, mobilenet_v2, mobilenet_v3_large, mobilenet_v3_small,
)
from .misc import (  # noqa: F401
    SqueezeNet, squeezenet1_0, squeezenet1_1,
    ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x0_5, shufflenet_v2_x1_0,
    shufflenet_v2_x1_5, shufflenet_v2_x2_0, shufflenet_v2_x0_33,
    shufflenet_v2_swish,
    DenseNet, densenet121, densenet161, densenet169, densenet201, densenet264,
    GoogLeNet, googlenet, InceptionV3, inception_v3,
)


def load_pretrained(model, arch, weight_path=None):
    """Offline pretrained-weight loading (ref: each builder's
    `pretrained=True` -> get_weights_path_from_url -> set_state_dict).

    Zero-egress: weights resolve through paddle_tpu.utils.download against
    the local cache ($PADDLE_TPU_HOME/weights/<arch>.pdparams) or an
    explicit `weight_path`. Missing files raise with placement
    instructions rather than silently returning random init."""
    from ...framework import io as fio
    from ...utils.download import get_weights_path_from_url
    path = weight_path or get_weights_path_from_url(f"{arch}.pdparams")
    state = fio.load(path)
    if isinstance(state, dict) and "model" in state and \
            not any(hasattr(v, "shape") for v in state.values()):
        state = state["model"]
    model.set_state_dict(state)
    return model
