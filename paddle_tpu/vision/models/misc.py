"""SqueezeNet, ShuffleNetV2, DenseNet, GoogLeNet, InceptionV3
(ref: python/paddle/vision/models/{squeezenet,shufflenetv2,densenet,googlenet,
inceptionv3}.py)."""
from __future__ import annotations

from ... import nn
from ...tensor import manipulation as M


# -- SqueezeNet --------------------------------------------------------------
class Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.e1 = nn.Conv2D(squeeze_c, e1, 1)
        self.e3 = nn.Conv2D(squeeze_c, e3, 3, padding=1)

    def forward(self, x):
        x = nn.functional.relu(self.squeeze(x))
        return M.concat([nn.functional.relu(self.e1(x)),
                         nn.functional.relu(self.e3(x))], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64), nn.MaxPool2D(3, 2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.features(x)
        x = self.classifier(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kwargs):
    return _maybe_pretrained(SqueezeNet("1.0", **kwargs), "squeezenet1_0", pretrained)


def squeezenet1_1(pretrained=False, **kwargs):
    return _maybe_pretrained(SqueezeNet("1.1", **kwargs), "squeezenet1_1", pretrained)


# -- ShuffleNetV2 ------------------------------------------------------------
def channel_shuffle(x, groups):
    return nn.functional.channel_shuffle(x, groups)


class InvertedResidualSF(nn.Layer):
    def __init__(self, inp, oup, stride):
        super().__init__()
        self.stride = stride
        branch = oup // 2
        if stride > 1:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride, 1, groups=inp, bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), nn.ReLU())
        in2 = inp if stride > 1 else branch
        self.branch2 = nn.Sequential(
            nn.Conv2D(in2, branch, 1, bias_attr=False), nn.BatchNorm2D(branch),
            nn.ReLU(),
            nn.Conv2D(branch, branch, 3, stride, 1, groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False), nn.BatchNorm2D(branch),
            nn.ReLU())

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = M.split(x, 2, axis=1)
            out = M.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = M.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        stage_repeats = [4, 8, 4]
        cfg = {0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
               0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
               1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048]}
        out_channels = cfg[scale]
        self.num_classes = num_classes
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, out_channels[0], 3, 2, 1, bias_attr=False),
            nn.BatchNorm2D(out_channels[0]), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, 2, 1)
        in_c = out_channels[0]
        stages = []
        for i, reps in enumerate(stage_repeats):
            out_c = out_channels[i + 1]
            seq = [InvertedResidualSF(in_c, out_c, 2)]
            for _ in range(reps - 1):
                seq.append(InvertedResidualSF(out_c, out_c, 1))
            stages.append(nn.Sequential(*seq))
            in_c = out_c
        self.stages = nn.LayerList(stages)
        self.conv5 = nn.Sequential(
            nn.Conv2D(in_c, out_channels[-1], 1, bias_attr=False),
            nn.BatchNorm2D(out_channels[-1]), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(out_channels[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        for s in self.stages:
            x = s(x)
        x = self.conv5(x)
        x = self.pool(x)
        return self.fc(x.flatten(1))


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _maybe_pretrained(ShuffleNetV2(1.0, **kwargs), "shufflenet_v2_x1_0", pretrained)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _maybe_pretrained(ShuffleNetV2(0.5, **kwargs), "shufflenet_v2_x0_5", pretrained)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _maybe_pretrained(ShuffleNetV2(0.25, **kwargs), "shufflenet_v2_x0_25", pretrained)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _maybe_pretrained(ShuffleNetV2(1.5, **kwargs), "shufflenet_v2_x1_5", pretrained)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _maybe_pretrained(ShuffleNetV2(2.0, **kwargs), "shufflenet_v2_x2_0", pretrained)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _maybe_pretrained(ShuffleNetV2(0.33, **kwargs), "shufflenet_v2_x0_33", pretrained)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _maybe_pretrained(ShuffleNetV2(1.0, act="swish", **kwargs), "shufflenet_v2_swish", pretrained)


# -- DenseNet ----------------------------------------------------------------
class DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                               bias_attr=False)
        self.drop_rate = drop_rate

    def forward(self, x):
        out = self.conv1(nn.functional.relu(self.bn1(x)))
        out = self.conv2(nn.functional.relu(self.bn2(out)))
        if self.drop_rate > 0:
            out = nn.functional.dropout(out, self.drop_rate, training=self.training)
        return M.concat([x, out], axis=1)


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        cfg = {121: (32, [6, 12, 24, 16]), 161: (48, [6, 12, 36, 24]),
               169: (32, [6, 12, 32, 32]), 201: (32, [6, 12, 48, 32]),
               264: (32, [6, 12, 64, 48])}
        growth, block_cfg = cfg[layers]
        num_init = 2 * growth
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, num_init, 7, 2, 3, bias_attr=False),
            nn.BatchNorm2D(num_init), nn.ReLU(), nn.MaxPool2D(3, 2, 1))
        blocks = []
        c = num_init
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if i != len(block_cfg) - 1:
                blocks.append(nn.Sequential(
                    nn.BatchNorm2D(c), nn.ReLU(),
                    nn.Conv2D(c, c // 2, 1, bias_attr=False), nn.AvgPool2D(2, 2)))
                c //= 2
        self.features = nn.Sequential(*blocks)
        self.bn_final = nn.BatchNorm2D(c)
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.conv1(x)
        x = self.features(x)
        x = nn.functional.relu(self.bn_final(x))
        x = self.pool(x)
        return self.fc(x.flatten(1))


def densenet121(pretrained=False, **kwargs):
    return _maybe_pretrained(DenseNet(121, **kwargs), "densenet121", pretrained)


def densenet161(pretrained=False, **kwargs):
    return _maybe_pretrained(DenseNet(161, **kwargs), "densenet161", pretrained)


def densenet169(pretrained=False, **kwargs):
    return _maybe_pretrained(DenseNet(169, **kwargs), "densenet169", pretrained)


def densenet201(pretrained=False, **kwargs):
    return _maybe_pretrained(DenseNet(201, **kwargs), "densenet201", pretrained)


def densenet264(pretrained=False, **kwargs):
    return _maybe_pretrained(DenseNet(264, **kwargs), "densenet264", pretrained)


# -- GoogLeNet ---------------------------------------------------------------
class Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_c, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_c, c5r, 1), nn.ReLU(),
                                nn.Conv2D(c5r, c5, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, 1),
                                nn.Conv2D(in_c, proj, 1), nn.ReLU())

    def forward(self, x):
        return M.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, 2, 3), nn.ReLU(), nn.MaxPool2D(3, 2, 1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(), nn.MaxPool2D(3, 2, 1))
        self.i3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, 1)
        self.i4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, 1)
        self.i5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = Inception(832, 384, 192, 384, 48, 128, 128)
        self.pool5 = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.4)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.pool4(self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x))))))
        x = self.i5b(self.i5a(x))
        x = self.pool5(x).flatten(1)
        out = self.fc(self.dropout(x))
        # reference returns (out, aux1, aux2); aux heads are train-time only
        return out, out, out


def googlenet(pretrained=False, **kwargs):
    return _maybe_pretrained(GoogLeNet(**kwargs), "googlenet", pretrained)


# -- InceptionV3 (compact faithful topology) ---------------------------------
class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()

        def conv_bn(i, o, k, s=1, p=0):
            return nn.Sequential(nn.Conv2D(i, o, k, s, p, bias_attr=False),
                                 nn.BatchNorm2D(o), nn.ReLU())
        self.stem = nn.Sequential(
            conv_bn(3, 32, 3, 2), conv_bn(32, 32, 3), conv_bn(32, 64, 3, 1, 1),
            nn.MaxPool2D(3, 2), conv_bn(64, 80, 1), conv_bn(80, 192, 3),
            nn.MaxPool2D(3, 2))
        self.mixed = nn.Sequential(
            Inception(192, 64, 48, 64, 64, 96, 32),
            Inception(256, 64, 48, 64, 64, 96, 64),
            Inception(288, 64, 48, 64, 64, 96, 64),
            nn.MaxPool2D(3, 2, 1),
            Inception(288, 192, 128, 192, 128, 192, 192),
            Inception(768, 192, 160, 192, 160, 192, 192),
            nn.MaxPool2D(3, 2, 1),
            Inception(768, 320, 192, 384, 192, 384, 192),
        )
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.5)
        self.fc = nn.Linear(1280, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.mixed(x)
        x = self.pool(x).flatten(1)
        return self.fc(self.dropout(x))


def inception_v3(pretrained=False, **kwargs):
    return _maybe_pretrained(InceptionV3(**kwargs), "inception_v3", pretrained)


def _maybe_pretrained(model, arch, pretrained):
    if pretrained:
        from . import load_pretrained
        load_pretrained(model, arch)
    return model
