"""Vision ops (ref: python/paddle/vision/ops.py) — detection-support subset."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..dispatch import apply as _apply
from ..tensor_impl import Tensor, as_tensor_data


def box_area(boxes):
    return _apply(lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]),
                  boxes, op_name="box_area")


def box_iou(boxes1, boxes2):
    def f(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return _apply(f, boxes1, boxes2, op_name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Host-side NMS (dynamic output size — eager only, like reference dygraph)."""
    b = np.asarray(as_tensor_data(boxes))
    s = np.asarray(as_tensor_data(scores)) if scores is not None else \
        np.arange(len(b), 0, -1).astype(np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """Simplified RoIAlign via bilinear grid sampling."""
    from ..nn.functional.common import grid_sample

    def f(feat, bx):
        oh, ow = (output_size, output_size) if isinstance(output_size, int) \
            else output_size
        n = bx.shape[0]
        x1, y1, x2, y2 = [bx[:, i] * spatial_scale for i in range(4)]
        H, W = feat.shape[2], feat.shape[3]
        ys = jnp.linspace(0, 1, oh)
        xs = jnp.linspace(0, 1, ow)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        cy = y1[:, None, None] + gy[None] * (y2 - y1)[:, None, None]
        cx = x1[:, None, None] + gx[None] * (x2 - x1)[:, None, None]
        # normalize to [-1, 1] for grid_sample
        ny = cy / (H - 1) * 2 - 1
        nx = cx / (W - 1) * 2 - 1
        grid = jnp.stack([nx, ny], axis=-1)
        # one roi per batch-0 feature (single-image simplification)
        feats = jnp.broadcast_to(feat[0:1], (n,) + feat.shape[1:])
        from ..nn.functional.common import grid_sample as _gs
        return _gs(Tensor(feats), Tensor(grid))._data
    return _apply(f, x, boxes, op_name="roi_align")


def deform_conv2d(*args, **kwargs):
    raise NotImplementedError("deform_conv2d: planned (gather-based impl)")
