"""Vision ops (ref: python/paddle/vision/ops.py) — detection-support subset."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..dispatch import apply as _apply
from ..tensor_impl import Tensor, as_tensor_data


def box_area(boxes):
    return _apply(lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]),
                  boxes, op_name="box_area")


def box_iou(boxes1, boxes2):
    def f(b1, b2):
        area1 = (b1[:, 2] - b1[:, 0]) * (b1[:, 3] - b1[:, 1])
        area2 = (b2[:, 2] - b2[:, 0]) * (b2[:, 3] - b2[:, 1])
        lt = jnp.maximum(b1[:, None, :2], b2[None, :, :2])
        rb = jnp.minimum(b1[:, None, 2:], b2[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)
    return _apply(f, boxes1, boxes2, op_name="box_iou")


def nms_static(boxes, scores, iou_threshold=0.3, top_k=64):
    """jit-compatible fixed-size NMS: exactly `top_k` output slots, padded
    with -1 (inference graphs need static shapes; the reference's dynamic
    keep-list is the eager path below). O(top_k * N) select-and-suppress
    via lax.fori_loop — each round takes the best remaining box and
    suppresses overlaps."""
    b = jnp.asarray(boxes)
    s = jnp.asarray(scores)
    N = b.shape[0]
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])

    def body(i, carry):
        live_s, keep = carry
        best = jnp.argmax(live_s)
        valid = live_s[best] > -jnp.inf
        keep = keep.at[i].set(jnp.where(valid, best, -1))
        xx1 = jnp.maximum(b[best, 0], b[:, 0])
        yy1 = jnp.maximum(b[best, 1], b[:, 1])
        xx2 = jnp.minimum(b[best, 2], b[:, 2])
        yy2 = jnp.minimum(b[best, 3], b[:, 3])
        inter = jnp.clip(xx2 - xx1, 0) * jnp.clip(yy2 - yy1, 0)
        iou = inter / (areas[best] + areas - inter + 1e-10)
        suppress = (iou > iou_threshold) | (
            jnp.arange(N) == best)
        live_s = jnp.where(valid & suppress, -jnp.inf, live_s)
        return live_s, keep

    # output width is EXACTLY top_k (pad with -1) so per-image results stack
    # into [B, top_k] regardless of proposal count
    k = int(top_k)
    _, keep = jax.lax.fori_loop(
        0, min(k, N), body, (s.astype(jnp.float32),
                             jnp.full((k,), -1, jnp.int64)))
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """NMS (ref: vision/ops.py nms). Eager concrete inputs use the host
    keep-list (dynamic output size, exact reference dygraph semantics);
    traced inputs route to the fixed-size `nms_static` (requires top_k)."""
    b_arr = as_tensor_data(boxes)
    s_arr = as_tensor_data(scores) if scores is not None else None
    if isinstance(b_arr, jax.core.Tracer) or isinstance(s_arr,
                                                        jax.core.Tracer):
        if top_k is None:
            raise ValueError(
                "nms under jit needs top_k (static output size); eager "
                "calls may omit it")
        if category_idxs is not None:
            raise NotImplementedError(
                "categorical nms under jit: call nms per category")
        if s_arr is None:
            s_arr = jnp.arange(b_arr.shape[0], 0, -1).astype(jnp.float32)
        return Tensor(nms_static(b_arr, s_arr, iou_threshold, top_k))
    b = np.asarray(b_arr)
    s = np.asarray(as_tensor_data(scores)) if scores is not None else \
        np.arange(len(b), 0, -1).astype(np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def _roi_align_fixed_grid(feat, bx, oh, ow, spatial_scale, gh, gw, aligned):
    """RoIAlign with a fixed (gh x gw) sampling grid per bin, fully
    vectorized: one gather + mean over the sample axis (ref semantics of
    vision/ops.py:1628 / the PHI roi_align kernel). feat: [R, C, H, W]
    (already one feature map per roi), bx: [R, 4]."""
    R, C, H, W = feat.shape
    offset = 0.5 if aligned else 0.0
    x1 = bx[:, 0] * spatial_scale - offset
    y1 = bx[:, 1] * spatial_scale - offset
    x2 = bx[:, 2] * spatial_scale - offset
    y2 = bx[:, 3] * spatial_scale - offset
    roi_w = x2 - x1
    roi_h = y2 - y1
    if not aligned:  # legacy: force malformed rois to be 1x1
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    bin_h = roi_h / oh
    bin_w = roi_w / ow
    # sample coords: y[r, i, iy] / x[r, j, ix]
    iy = (jnp.arange(gh) + 0.5) / gh
    ix = (jnp.arange(gw) + 0.5) / gw
    ys = y1[:, None, None] + (jnp.arange(oh)[None, :, None] + iy[None, None])\
        * bin_h[:, None, None]                       # [R, oh, gh]
    xs = x1[:, None, None] + (jnp.arange(ow)[None, :, None] + ix[None, None])\
        * bin_w[:, None, None]                       # [R, ow, gw]
    yy = ys[:, :, None, :, None]                     # [R, oh, 1, gh, 1]
    xx = xs[:, None, :, None, :]                     # [R, 1, ow, 1, gw]
    yy, xx = jnp.broadcast_arrays(yy, xx)            # [R, oh, ow, gh, gw]
    # reference exclusion is y < -1 or y > H (boundary values clamp+interp)
    valid = (yy >= -1.0) & (yy <= H) & (xx >= -1.0) & (xx <= W)
    yc = jnp.clip(yy, 0.0, H - 1)
    xc = jnp.clip(xx, 0.0, W - 1)
    y0 = jnp.floor(yc)
    x0 = jnp.floor(xc)
    y1i = jnp.minimum(y0 + 1, H - 1)
    x1i = jnp.minimum(x0 + 1, W - 1)
    ly = yc - y0
    lx = xc - x0
    flat = feat.reshape(R, C, H * W)

    def take(yi, xi):
        idx = (yi.astype(jnp.int32) * W + xi.astype(jnp.int32)).reshape(R, -1)
        got = jnp.take_along_axis(flat, idx[:, None, :], axis=-1)
        return got.reshape(R, C, oh, ow, gh, gw)

    v = ((1 - ly) * (1 - lx))[:, None] * take(y0, x0) \
        + ((1 - ly) * lx)[:, None] * take(y0, x1i) \
        + (ly * (1 - lx))[:, None] * take(y1i, x0) \
        + (ly * lx)[:, None] * take(y1i, x1i)
    v = jnp.where(valid[:, None], v, 0.0)
    return v.mean(axis=(-2, -1))                     # [R, C, oh, ow]


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Region-of-Interest align (ref: python/paddle/vision/ops.py:1628).

    `boxes_num[i]` rois belong to image i (rois are concatenated in image
    order); each roi bilinearly samples ITS image's feature map. On TPU the
    sampling is one batched gather + mean (static shapes, MXU-friendly).
    `sampling_ratio<=0` uses the reference's adaptive per-roi grid
    (ceil(roi_size/bin)) — data-dependent, so it requires concrete boxes
    (eager); pass sampling_ratio>0 for a jit-compatible fixed grid.
    """
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)

    def f(feat, bx, bn):
        img_idx = jnp.repeat(jnp.arange(feat.shape[0]), bn,
                             total_repeat_length=bx.shape[0])
        per_roi = feat[img_idx]                       # [R, C, H, W]
        if sampling_ratio > 0:
            return _roi_align_fixed_grid(per_roi, bx, oh, ow, spatial_scale,
                                         sampling_ratio, sampling_ratio,
                                         aligned)
        # adaptive grid (ceil(roi_h/oh) x ceil(roi_w/ow)): needs concrete
        # boxes; grid counts are per-roi so loop rois (eager path — the
        # detection pipelines that use adaptive sampling are eager anyway)
        if isinstance(bx, jax.core.Tracer):
            raise ValueError(
                "roi_align with sampling_ratio<=0 is data-dependent "
                "(adaptive grid); pass sampling_ratio>0 under jit")
        offset = 0.5 if aligned else 0.0
        outs = []
        for r in range(bx.shape[0]):
            roi_h = float(bx[r, 3] - bx[r, 1]) * spatial_scale
            roi_w = float(bx[r, 2] - bx[r, 0]) * spatial_scale
            if not aligned:
                roi_h, roi_w = max(roi_h, 1.0), max(roi_w, 1.0)
            gh = max(int(np.ceil(roi_h / oh)), 1)
            gw = max(int(np.ceil(roi_w / ow)), 1)
            outs.append(_roi_align_fixed_grid(
                per_roi[r:r + 1], bx[r:r + 1], oh, ow, spatial_scale,
                gh, gw, aligned)[0])
        return jnp.stack(outs) if outs else \
            jnp.zeros((0, feat.shape[1], oh, ow), feat.dtype)
    return _apply(f, x, boxes, boxes_num, op_name="roi_align")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (ref: vision/ops.py deform_conv2d).

    TPU-native: bilinear sampling at offset positions is a batched gather,
    then the kernel contraction is one einsum on the MXU (im2col form) —
    replacing the reference's CUDA deformable_im2col kernel.
    offset: [N, 2*dg*kh*kw, Ho, Wo]; mask (v2): [N, dg*kh*kw, Ho, Wo].
    """
    def to2(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    sh, sw = to2(stride)
    ph, pw = to2(padding)
    dh, dw = to2(dilation)

    def f(xv, off, w, *rest):
        N, Cin, H, W = xv.shape
        Cout, Cin_g, kh, kw = w.shape
        K = kh * kw
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        dg = deformable_groups
        offr = off.reshape(N, dg, K, 2, Ho, Wo)
        oy = offr[:, :, :, 0]
        ox = offr[:, :, :, 1]
        base_y = (jnp.arange(Ho) * sh - ph)[None, None, None, :, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, None, None, None, :]
        k_y = (jnp.arange(kh) * dh).repeat(kw).reshape(1, 1, K, 1, 1)
        k_x = jnp.tile(jnp.arange(kw) * dw, kh).reshape(1, 1, K, 1, 1)
        py = base_y + k_y + oy                      # [N, dg, K, Ho, Wo]
        px = base_x + k_x + ox

        y0 = jnp.floor(py)
        x0 = jnp.floor(px)
        wy = py - y0
        wx = px - x0

        def gather(yi, xi):
            inb = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            flat = xv.reshape(N, dg, Cin // dg, H * W)
            idx = (yc * W + xc).reshape(N, dg, -1)       # [N, dg, K*Ho*Wo]
            got = jnp.take_along_axis(flat, idx[:, :, None, :], axis=-1)
            got = got.reshape(N, dg, Cin // dg, K, Ho, Wo)
            return got * inb[:, :, None].astype(xv.dtype)

        v00 = gather(y0, x0)
        v01 = gather(y0, x0 + 1)
        v10 = gather(y0 + 1, x0)
        v11 = gather(y0 + 1, x0 + 1)
        wy_ = wy[:, :, None]
        wx_ = wx[:, :, None]
        samp = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
                + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
        samp = samp.reshape(N, Cin, K, Ho, Wo)
        i = 0
        if mask is not None:
            m = rest[i]; i += 1
            m = m.reshape(N, dg, 1, K, Ho, Wo).reshape(N, dg, K, Ho, Wo)
            samp = samp.reshape(N, dg, Cin // dg, K, Ho, Wo) * m[:, :, None]
            samp = samp.reshape(N, Cin, K, Ho, Wo)
        # grouped contraction: [g, Cout/g, Cin/g*K] x [N, g, Cin/g*K, Ho*Wo]
        wg = w.reshape(groups, Cout // groups, Cin_g * K)
        sg = samp.reshape(N, groups, (Cin // groups) * K, Ho * Wo)
        out = jnp.einsum("gok,ngkp->ngop", wg, sg).reshape(N, Cout, Ho, Wo)
        if bias is not None:
            out = out + rest[i].reshape(1, -1, 1, 1)
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return _apply(f, *args, op_name="conv2d")


class DeformConv2D:
    """Layer wrapper over deform_conv2d (ref: vision/ops.py DeformConv2D)."""

    def __new__(cls, *args, **kwargs):
        # late import to avoid a vision<->nn import cycle at module load
        from ..nn import Layer as _Layer

        class _DeformConv2D(_Layer):
            def __init__(self, in_channels, out_channels, kernel_size,
                         stride=1, padding=0, dilation=1, deformable_groups=1,
                         groups=1, weight_attr=None, bias_attr=None):
                super().__init__()
                from ..nn.initializer import XavierUniform, Constant
                k = (kernel_size, kernel_size) if isinstance(kernel_size, int)                     else tuple(kernel_size)
                self.stride = stride
                self.padding = padding
                self.dilation = dilation
                self.deformable_groups = deformable_groups
                self.groups = groups
                self.weight = self.create_parameter(
                    (out_channels, in_channels // groups) + k,
                    default_initializer=XavierUniform())
                self.bias = None if bias_attr is False else                     self.create_parameter((out_channels,), is_bias=True,
                                          default_initializer=Constant(0.0))

            def forward(self, x, offset, mask=None):
                return deform_conv2d(x, offset, self.weight, self.bias,
                                     self.stride, self.padding, self.dilation,
                                     self.deformable_groups, self.groups, mask)

        return _DeformConv2D(*args, **kwargs)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes for one feature map (ref: vision/ops.py
    prior_box). Pure trace-time geometry — no device work needed."""
    feat = as_tensor_data(input)
    img = as_tensor_data(image)
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    step_w = steps[0] or float(IW) / W
    step_h = steps[1] or float(IH) / H

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    boxes = []
    for h in range(H):
        for w in range(W):
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            per = []
            for k, ms in enumerate(min_sizes):
                if min_max_aspect_ratios_order:
                    per.append((ms, ms))
                    if max_sizes:
                        import math as _m
                        sz = _m.sqrt(ms * max_sizes[k])
                        per.append((sz, sz))
                    for ar in ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        per.append((ms * ar ** 0.5, ms / ar ** 0.5))
                else:
                    for ar in ars:
                        per.append((ms * ar ** 0.5, ms / ar ** 0.5))
                    if max_sizes:
                        import math as _m
                        sz = _m.sqrt(ms * max_sizes[k])
                        per.append((sz, sz))
            for bw, bh in per:
                boxes.append([(cx - bw / 2) / IW, (cy - bh / 2) / IH,
                              (cx + bw / 2) / IW, (cy + bh / 2) / IH])
    num = len(boxes) // (H * W)
    out = jnp.asarray(np.array(boxes, np.float32).reshape(H, W, num, 4))
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), out.shape)
    return Tensor(out), Tensor(var)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (ref: vision/ops.py box_coder)."""
    pb = as_tensor_data(prior_box)
    pbv = as_tensor_data(prior_box_var) if prior_box_var is not None else None
    tb = as_tensor_data(target_box)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, None, 2] - tb[:, None, 0] + norm
        th = tb[:, None, 3] - tb[:, None, 1] + norm
        tcx = tb[:, None, 0] + tw * 0.5
        tcy = tb[:, None, 1] + th * 0.5
        ox = (tcx - pcx[None]) / pw[None]
        oy = (tcy - pcy[None]) / ph[None]
        ow = jnp.log(jnp.abs(tw / pw[None]))
        oh = jnp.log(jnp.abs(th / ph[None]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if pbv is not None:
            out = out / pbv[None]
        return Tensor(out)
    # decode_center_size
    if pbv is not None:
        pbv_b = pbv if pbv.ndim == 2 else jnp.broadcast_to(pbv, pb.shape)
        tb = tb * (pbv_b[None] if tb.ndim == 3 else pbv_b)
    if tb.ndim == 2:
        tb = tb[:, None]
    dcx = pcx[None] if axis == 0 else pcx[:, None]
    dcy = pcy[None] if axis == 0 else pcy[:, None]
    dw = pw[None] if axis == 0 else pw[:, None]
    dh = ph[None] if axis == 0 else ph[:, None]
    # tb layout [N, M, 4]
    ocx = tb[..., 0] * dw + dcx
    ocy = tb[..., 1] * dh + dcy
    ow = jnp.exp(tb[..., 2]) * dw
    oh = jnp.exp(tb[..., 3]) * dh
    out = jnp.stack([ocx - ow * 0.5, ocy - oh * 0.5,
                     ocx + ow * 0.5 - norm, ocy + oh * 0.5 - norm], axis=-1)
    return Tensor(out)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes+scores (ref: vision/ops.py
    yolo_box). x: [N, C, H, W] with C = na*(5+classes)."""
    xv = as_tensor_data(x)
    imgs = as_tensor_data(img_size)
    na = len(anchors) // 2
    N, C, H, W = xv.shape
    an = jnp.asarray(np.array(anchors, np.float32).reshape(na, 2))
    feats = xv.reshape(N, na, -1, H, W)
    box_xy_raw = feats[:, :, 0:2]
    gx = jnp.arange(W, dtype=jnp.float32)
    gy = jnp.arange(H, dtype=jnp.float32)
    bx = (jax.nn.sigmoid(box_xy_raw[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) / 2 + gx[None, None, None, :]) / W
    by = (jax.nn.sigmoid(box_xy_raw[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) / 2 + gy[None, None, :, None]) / H
    input_h = downsample_ratio * H
    input_w = downsample_ratio * W
    bw = jnp.exp(feats[:, :, 2]) * an[None, :, 0, None, None] / input_w
    bh = jnp.exp(feats[:, :, 3]) * an[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(feats[:, :, 4])
    probs = jax.nn.sigmoid(feats[:, :, 5:5 + class_num])
    score = conf[:, :, None] * probs
    keep = (conf > conf_thresh).astype(xv.dtype)
    imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(N, -1, 4)
    scores = (score * keep[:, :, None]).transpose(0, 1, 3, 4, 2) \
        .reshape(N, -1, class_num)
    return Tensor(boxes), Tensor(scores)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (ref: vision/ops.py yolo_loss): coordinate BCE/L1
    + objectness BCE with ignore mask + classification BCE, assembled from
    XLA primitives rather than the reference's fused CUDA kernel."""
    na = len(anchor_mask)
    an_all = np.array(anchors, np.float32).reshape(-1, 2)

    def f(xv, gtb, gtl, sc):
        gtb = gtb.astype(jnp.float32)    # [N, B, 4] cx cy w h (normalized)
        gtl = gtl.astype(jnp.int32)      # [N, B]
        N, C, H, W = xv.shape
        feats = xv.reshape(N, na, 5 + class_num, H, W)
        input_w = downsample_ratio * W
        input_h = downsample_ratio * H

        px = jax.nn.sigmoid(feats[:, :, 0])
        py = jax.nn.sigmoid(feats[:, :, 1])
        pw = feats[:, :, 2]
        ph = feats[:, :, 3]
        pobj = feats[:, :, 4]
        pcls = feats[:, :, 5:]

        # build targets host-free: for each gt, its cell + best anchor
        gx = gtb[..., 0] * W
        gy = gtb[..., 1] * H
        gi = jnp.clip(gx.astype(jnp.int32), 0, W - 1)
        gj = jnp.clip(gy.astype(jnp.int32), 0, H - 1)
        gw_in = gtb[..., 2] * input_w
        gh_in = gtb[..., 3] * input_h
        inter = (jnp.minimum(gw_in[..., None], an_all[None, None, :, 0])
                 * jnp.minimum(gh_in[..., None], an_all[None, None, :, 1]))
        union = (gw_in[..., None] * gh_in[..., None]
                 + an_all[None, None, :, 0] * an_all[None, None, :, 1] - inter)
        best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N, B]
        valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)

        loss = jnp.zeros((N,), jnp.float32)
        obj_target = jnp.zeros((N, na, H, W))
        obj_has = jnp.zeros((N, na, H, W), bool)
        B = gtb.shape[1]
        sc = sc.astype(jnp.float32)
        for k, am in enumerate(anchor_mask):
            sel = valid & (best == am)                       # [N, B]
            w_box = 2.0 - gtb[..., 2] * gtb[..., 3]
            tx = gx - gi
            ty = gy - gj
            tw = jnp.log(jnp.maximum(gw_in / an_all[am, 0], 1e-9))
            th = jnp.log(jnp.maximum(gh_in / an_all[am, 1], 1e-9))
            bidx = jnp.arange(N)[:, None]
            pxk = px[:, k][bidx, gj, gi]
            pyk = py[:, k][bidx, gj, gi]
            pwk = pw[:, k][bidx, gj, gi]
            phk = ph[:, k][bidx, gj, gi]
            m = sel.astype(jnp.float32) * sc * w_box
            eps = 1e-7
            bce = lambda p, t: -(t * jnp.log(jnp.clip(p, eps, 1 - eps))
                                 + (1 - t) * jnp.log(jnp.clip(1 - p, eps, 1 - eps)))
            loss = loss + jnp.sum(m * (bce(pxk, tx) + bce(pyk, ty)
                                       + jnp.abs(pwk - tw) + jnp.abs(phk - th)),
                                  axis=1)
            cls_t = jax.nn.one_hot(gtl, class_num)
            if use_label_smooth:
                delta = 1.0 / max(class_num, 1)
                cls_t = cls_t * (1 - delta) + delta * (1.0 / class_num)
            pck = jax.nn.sigmoid(pcls[:, k].transpose(0, 2, 3, 1)[bidx, gj, gi])
            loss = loss + jnp.sum(sel.astype(jnp.float32)[..., None]
                                  * bce(pck, cls_t), axis=(1, 2))
            obj_target = obj_target.at[bidx, k, gj, gi].max(sel.astype(jnp.float32) * sc)
            obj_has = obj_has.at[bidx, k, gj, gi].max(sel)
        # ignore mask: unmatched predictions whose decoded box overlaps some
        # gt with IoU > ignore_thresh get no objectness gradient (reference
        # semantics: only confident-and-correct cells are excused)
        an_sel = jnp.asarray(an_all[np.array(anchor_mask)])
        bx_p = (px + jnp.arange(W)[None, None, None, :]) / W
        by_p = (py + jnp.arange(H)[None, None, :, None]) / H
        bw_p = jnp.exp(pw) * an_sel[None, :, 0, None, None] / input_w
        bh_p = jnp.exp(ph) * an_sel[None, :, 1, None, None] / input_h
        px1 = bx_p - bw_p / 2; px2 = bx_p + bw_p / 2
        py1 = by_p - bh_p / 2; py2 = by_p + bh_p / 2
        gx1 = (gtb[..., 0] - gtb[..., 2] / 2)
        gx2 = (gtb[..., 0] + gtb[..., 2] / 2)
        gy1 = (gtb[..., 1] - gtb[..., 3] / 2)
        gy2 = (gtb[..., 1] + gtb[..., 3] / 2)
        ix = (jnp.minimum(px2[..., None], gx2[:, None, None, None])
              - jnp.maximum(px1[..., None], gx1[:, None, None, None]))
        iy = (jnp.minimum(py2[..., None], gy2[:, None, None, None])
              - jnp.maximum(py1[..., None], gy1[:, None, None, None]))
        inter_pg = jnp.clip(ix, 0) * jnp.clip(iy, 0)
        area_p = bw_p * bh_p
        area_g = (gtb[..., 2] * gtb[..., 3])[:, None, None, None]
        iou_pg = inter_pg / jnp.maximum(area_p[..., None] + area_g - inter_pg,
                                        1e-9)
        iou_pg = jnp.where(valid[:, None, None, None], iou_pg, 0.0)
        best_iou = jnp.max(iou_pg, axis=-1)              # [N, na, H, W]
        pobj_s = jax.nn.sigmoid(pobj)
        eps = 1e-7
        obj_bce = -(obj_target * jnp.log(jnp.clip(pobj_s, eps, 1 - eps))
                    + (1 - obj_target) * jnp.log(jnp.clip(1 - pobj_s, eps, 1 - eps)))
        loss = loss + jnp.sum(jnp.where(obj_has | (best_iou < ignore_thresh),
                                        obj_bce, 0.0), axis=(1, 2, 3))
        return loss

    sc_in = gt_score if gt_score is not None else \
        jnp.ones(as_tensor_data(gt_label).shape, jnp.float32)
    return _apply(f, x, gt_box, gt_label, sc_in, op_name="cross_entropy")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Max-pool each RoI into a fixed grid (ref: vision/ops.py roi_pool)."""
    feat = as_tensor_data(x)
    bx = np.asarray(jax.device_get(as_tensor_data(boxes)), np.float32)
    bn = np.asarray(jax.device_get(as_tensor_data(boxes_num)), np.int64)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    img_of_box = np.repeat(np.arange(len(bn)), bn)
    outs = []
    H, W = feat.shape[2], feat.shape[3]
    for b, img in zip(bx, img_of_box):
        x1, y1, x2, y2 = np.round(b * spatial_scale).astype(np.int64)
        x2 = max(x2, x1 + 1); y2 = max(y2, y1 + 1)
        ys = np.linspace(y1, y2, oh + 1).astype(np.int64)
        xs = np.linspace(x1, x2, ow + 1).astype(np.int64)
        cell = []
        for i in range(oh):
            for j in range(ow):
                y_lo, y_hi = ys[i], max(ys[i + 1], ys[i] + 1)
                x_lo, x_hi = xs[j], max(xs[j + 1], xs[j] + 1)
                patch = feat[int(img), :, int(np.clip(y_lo, 0, H - 1)):int(np.clip(y_hi, 1, H)),
                             int(np.clip(x_lo, 0, W - 1)):int(np.clip(x_hi, 1, W))]
                cell.append(jnp.max(patch, axis=(1, 2)))
        outs.append(jnp.stack(cell, 1).reshape(feat.shape[1], oh, ow))
    return Tensor(jnp.stack(outs))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI average pooling (ref: vision/ops.py psroi_pool):
    channel c of output cell (i,j) reads input channel (i*ow+j)*C_out + c."""
    feat = as_tensor_data(x)
    bx = np.asarray(jax.device_get(as_tensor_data(boxes)), np.float32)
    bn = np.asarray(jax.device_get(as_tensor_data(boxes_num)), np.int64)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    C = feat.shape[1]
    assert C % (oh * ow) == 0, "channels must divide output_size^2"
    c_out = C // (oh * ow)
    img_of_box = np.repeat(np.arange(len(bn)), bn)
    H, W = feat.shape[2], feat.shape[3]
    outs = []
    for b, img in zip(bx, img_of_box):
        x1, y1, x2, y2 = b * spatial_scale
        rh = max(y2 - y1, 0.1) / oh
        rw = max(x2 - x1, 0.1) / ow
        cells = []
        for i in range(oh):
            for j in range(ow):
                y_lo = int(np.clip(np.floor(y1 + i * rh), 0, H))
                y_hi = int(np.clip(np.ceil(y1 + (i + 1) * rh), 0, H))
                x_lo = int(np.clip(np.floor(x1 + j * rw), 0, W))
                x_hi = int(np.clip(np.ceil(x1 + (j + 1) * rw), 0, W))
                chan = (i * ow + j) * c_out
                if y_hi <= y_lo or x_hi <= x_lo:
                    cells.append(jnp.zeros((c_out,), feat.dtype))
                else:
                    patch = feat[int(img), chan:chan + c_out, y_lo:y_hi, x_lo:x_hi]
                    cells.append(jnp.mean(patch, axis=(1, 2)))
        outs.append(jnp.stack(cells, 1).reshape(c_out, oh, ow))
    return Tensor(jnp.stack(outs))


def matrix_nms(bboxes, scores, score_threshold, post_threshold, nms_top_k,
               keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): soft decay by pairwise IoU, no sequential
    suppression — one dense matrix op, MXU-friendly (ref: vision/ops.py)."""
    bx = as_tensor_data(bboxes)      # [N, M, 4]
    sc = as_tensor_data(scores)      # [N, C, M]
    N, C, M = sc.shape
    outs, idxs, nums = [], [], []
    for n in range(N):
        per_img = []
        per_idx = []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            keep = s > score_threshold
            k = min(int(np.asarray(jax.device_get(jnp.sum(keep)))), nms_top_k
                    if nms_top_k > 0 else M)
            if k == 0:
                continue
            order = jnp.argsort(-jnp.where(keep, s, -jnp.inf))[:k]
            b = bx[n][order]
            ss = s[order]
            iou = _pairwise_iou(b, b, normalized)
            iou = jnp.triu(iou, 1)
            # compensate_i: how much suppressor i was itself suppressed —
            # decay_ij = f(iou_ij) / f(compensate_i) (SOLOv2 eq. 4), min over i
            comp = jnp.max(iou, axis=0)
            if use_gaussian:
                decay = jnp.exp(-(iou ** 2 - comp[:, None] ** 2) / gaussian_sigma)
                decay = jnp.min(jnp.where(jnp.triu(jnp.ones_like(iou), 1) > 0,
                                          decay, 1.0), axis=0)
            else:
                decay = jnp.min(jnp.where(
                    jnp.triu(jnp.ones_like(iou), 1) > 0,
                    (1 - iou) / jnp.maximum(1 - comp[:, None], 1e-9), 1.0), axis=0)
            dec = ss * decay
            m2 = dec > post_threshold
            sel = np.asarray(jax.device_get(m2))
            cls = jnp.full((int(sel.sum()), 1), c, bx.dtype)
            kept = jnp.concatenate([cls, dec[m2][:, None], b[m2]], axis=1)
            per_img.append(kept)
            per_idx.append(np.asarray(jax.device_get(order))[sel] + n * M)
        if per_img:
            allc = jnp.concatenate(per_img)
            top = jnp.argsort(-allc[:, 1])
            if keep_top_k > 0:
                top = top[:keep_top_k]
            outs.append(allc[top])
            cat = np.concatenate(per_idx)[np.asarray(jax.device_get(top))]
            idxs.append(cat)
            nums.append(len(np.asarray(jax.device_get(top))))
        else:
            outs.append(jnp.zeros((0, 6), bx.dtype))
            idxs.append(np.zeros((0,), np.int64))
            nums.append(0)
    out = Tensor(jnp.concatenate(outs)) if outs else Tensor(jnp.zeros((0, 6)))
    res = [out]
    if return_index:
        res.append(Tensor(jnp.asarray(np.concatenate(idxs))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.array(nums, np.int64))))
    return tuple(res) if len(res) > 1 else out


def _pairwise_iou(a, b, normalized=True):
    norm = 0.0 if normalized else 1.0
    area = lambda t: (t[:, 2] - t[:, 0] + norm) * (t[:, 3] - t[:, 1] + norm)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + norm, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area(a)[:, None] + area(b)[None] - inter, 1e-9)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels by scale (ref: vision/ops.py)."""
    rois = np.asarray(jax.device_get(as_tensor_data(fpn_rois)), np.float32)
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum((rois[:, 2] - rois[:, 0] + off)
                               * (rois[:, 3] - rois[:, 1] + off), 0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, restore = [], []
    order = []
    for L in range(min_level, max_level + 1):
        sel = np.where(lvl == L)[0]
        multi.append(Tensor(jnp.asarray(rois[sel])))
        order.append(sel)
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.argsort(order)[:, None]
    nums = [Tensor(jnp.asarray(np.array([len(np.where(lvl == L)[0])], np.int32)))
            for L in range(min_level, max_level + 1)] if rois_num is not None else None
    out = (multi, Tensor(jnp.asarray(restore)))
    return out + (nums,) if nums is not None else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000, nms_thresh=0.5,
                       min_size=0.1, eta=1.0, pixel_offset=False,
                       return_rois_num=False, name=None):
    """RPN proposal generation (ref: vision/ops.py generate_proposals):
    decode deltas at anchors, clip, filter small, NMS — host-orchestrated
    (data-dependent sizes), math on device."""
    sc = np.asarray(jax.device_get(as_tensor_data(scores)), np.float32)
    bd = np.asarray(jax.device_get(as_tensor_data(bbox_deltas)), np.float32)
    ims = np.asarray(jax.device_get(as_tensor_data(img_size)), np.float32)
    an = np.asarray(jax.device_get(as_tensor_data(anchors)), np.float32).reshape(-1, 4)
    va = np.asarray(jax.device_get(as_tensor_data(variances)), np.float32).reshape(-1, 4)
    N = sc.shape[0]
    props, prop_scores, nums = [], [], []
    off = 1.0 if pixel_offset else 0.0
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)
        d = bd[n].transpose(1, 2, 0).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order % len(an)] if len(an) != len(s) else an[order], va[order % len(va)] if len(va) != len(s) else va[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], np.log(1000. / 16))) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], np.log(1000. / 16))) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2 - off, cy + h / 2 - off], 1)
        Hi, Wi = ims[n][0], ims[n][1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, Wi - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, Hi - off)
        keep = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[keep], s[keep]
        kept = np.asarray(jax.device_get(as_tensor_data(
            nms(Tensor(jnp.asarray(boxes)), nms_thresh,
                scores=Tensor(jnp.asarray(s)), top_k=post_nms_top_n))))
        props.append(boxes[kept])
        prop_scores.append(s[kept])
        nums.append(len(kept))
    rois = Tensor(jnp.asarray(np.concatenate(props) if props else np.zeros((0, 4), np.float32)))
    rscores = Tensor(jnp.asarray(np.concatenate(prop_scores) if prop_scores else np.zeros((0,), np.float32)))
    if return_rois_num:
        return rois, rscores, Tensor(jnp.asarray(np.array(nums, np.int32)))
    return rois, rscores


def read_file(filename, name=None):
    """Read raw file bytes as a uint8 tensor (ref: vision/ops.py read_file)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (PIL-backed host op)."""
    import io
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("decode_jpeg requires PIL in this environment") from e
    raw = bytes(np.asarray(jax.device_get(as_tensor_data(x))).tobytes())
    img = Image.open(io.BytesIO(raw))
    if mode != "unchanged":
        img = img.convert(mode.upper())
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


class RoIAlign:
    """Layer wrapper over roi_align (ref vision/ops.py RoIAlign)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool:
    """Layer wrapper over roi_pool (ref vision/ops.py RoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool:
    """Layer wrapper over psroi_pool (ref vision/ops.py PSRoIPool)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


_DEFAULT = object()


def ConvNormActivation(in_channels, out_channels, kernel_size=3, stride=1,
                       padding=None, groups=1, norm_layer=_DEFAULT,
                       activation_layer=_DEFAULT, dilation=1, bias=None):
    """Conv2D + norm + activation block (ref vision/ops.py
    ConvNormActivation). An EXPLICIT norm_layer=None/activation_layer=None
    omits that stage (the defaults are BatchNorm2D / ReLU)."""
    from .. import nn
    if padding is None:
        padding = (kernel_size - 1) // 2 * dilation
    if norm_layer is _DEFAULT:
        norm_layer = nn.BatchNorm2D
    if activation_layer is _DEFAULT:
        activation_layer = nn.ReLU
    if bias is None:
        bias = norm_layer is None
    layers = [nn.Conv2D(in_channels, out_channels, kernel_size, stride,
                        padding, dilation=dilation, groups=groups,
                        bias_attr=None if bias else False)]
    if norm_layer is not None:
        layers.append(norm_layer(out_channels))
    if activation_layer is not None:
        layers.append(activation_layer())
    return nn.Sequential(*layers)
