"""paddle_tpu.vision (ref: python/paddle/vision/__init__.py)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"


def image_load(path, backend=None):
    """Load an image file (ref: vision/image.py). PIL when present, else a
    raw-npy fallback (zero-dependency environments)."""
    try:
        from PIL import Image
        return Image.open(path)
    except ImportError:
        import numpy as np
        if str(path).endswith(".npy"):
            return np.load(path)
        raise
