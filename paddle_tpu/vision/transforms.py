"""Vision transforms (ref: python/paddle/vision/transforms/transforms.py).

Numpy/host-side preprocessing (HWC uint8 images in, CHW float tensors out) —
the device never sees un-batched images.
"""
from __future__ import annotations

import numbers

import numpy as np

from ..tensor_impl import Tensor
from ..framework.random import next_key


def _rand():
    import jax
    return float(jax.random.uniform(next_key(), ()))


def _to_numpy(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        if arr.dtype == np.uint8 or arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


def _resize_np(arr, size, interpolation="bilinear"):
    """arr HWC float/uint8 -> resized via jax.image (host small arrays)."""
    import jax
    import jax.numpy as jnp
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            new = (size, int(w * size / h))
        else:
            new = (int(h * size / w), size)
    else:
        new = tuple(size)
    method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}.get(
        interpolation, "linear")
    out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                           new + tuple(arr.shape[2:]), method=method)
    return np.asarray(out)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return _resize_np(_to_numpy(img), self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else \
                [self.padding] * 4
            arr = np.pad(arr, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = int(_rand() * max(h - th, 0))
        j = int(_rand() * max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if _rand() < self.prob:
            return _to_numpy(img)[:, ::-1].copy()
        return _to_numpy(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if _rand() < self.prob:
            return _to_numpy(img)[::-1].copy()
        return _to_numpy(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_numpy(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * (self.scale[0] + _rand() * (self.scale[1] - self.scale[0]))
            logr = np.log(self.ratio[0]) + _rand() * (
                np.log(self.ratio[1]) - np.log(self.ratio[0]))
            ar = np.exp(logr)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = int(_rand() * (h - th + 1))
                j = int(_rand() * (w - tw + 1))
                return _resize_np(arr[i:i + th, j:j + tw], self.size,
                                  self.interpolation)
        return _resize_np(CenterCrop(min(h, w))._apply_image(arr), self.size,
                          self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        f = 1 + (2 * _rand() - 1) * self.value
        return np.clip(arr * f, 0, 255 if arr.max() > 1.5 else 1.0)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        f = 1 + (2 * _rand() - 1) * self.value
        mean = arr.mean()
        return np.clip((arr - mean) * f + mean, 0, 255 if arr.max() > 1.5 else 1.0)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None,
                 fill=0, keys=None):
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) \
            else tuple(degrees)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        deg = self.degrees[0] + _rand() * (self.degrees[1] - self.degrees[0])
        k = int(round(deg / 90.0)) % 4  # coarse rotation (host-side, no scipy)
        return np.rot90(arr, k=k, axes=(0, 1)).copy()


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) else \
            [padding] * 4
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_numpy(img)
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        return np.pad(arr, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2),
                      constant_values=self.fill)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _to_numpy(img)[:, ::-1].copy()


def vflip(img):
    return _to_numpy(img)[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return _to_numpy(img)[top:top + height, left:left + width]
