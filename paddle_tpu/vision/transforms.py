"""Vision transforms (ref: python/paddle/vision/transforms/transforms.py).

Numpy/host-side preprocessing (HWC uint8 images in, CHW float tensors out) —
the device never sees un-batched images.
"""
from __future__ import annotations

import numbers

import numpy as np

from ..tensor_impl import Tensor
from ..framework.random import next_key


def _rand():
    import jax
    return float(jax.random.uniform(next_key(), ()))


def _to_numpy(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        if arr.dtype == np.uint8 or arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


def _resize_np(arr, size, interpolation="bilinear"):
    """arr HWC float/uint8 -> resized via jax.image (host small arrays)."""
    import jax
    import jax.numpy as jnp
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            new = (size, int(w * size / h))
        else:
            new = (int(h * size / w), size)
    else:
        new = tuple(size)
    method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}.get(
        interpolation, "linear")
    out = jax.image.resize(jnp.asarray(arr, jnp.float32),
                           new + tuple(arr.shape[2:]), method=method)
    return np.asarray(out)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return _resize_np(_to_numpy(img), self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else \
                [self.padding] * 4
            arr = np.pad(arr, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = int(_rand() * max(h - th, 0))
        j = int(_rand() * max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if _rand() < self.prob:
            return _to_numpy(img)[:, ::-1].copy()
        return _to_numpy(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if _rand() < self.prob:
            return _to_numpy(img)[::-1].copy()
        return _to_numpy(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, numbers.Number) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_numpy(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * (self.scale[0] + _rand() * (self.scale[1] - self.scale[0]))
            logr = np.log(self.ratio[0]) + _rand() * (
                np.log(self.ratio[1]) - np.log(self.ratio[0]))
            ar = np.exp(logr)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = int(_rand() * (h - th + 1))
                j = int(_rand() * (w - tw + 1))
                return _resize_np(arr[i:i + th, j:j + tw], self.size,
                                  self.interpolation)
        return _resize_np(CenterCrop(min(h, w))._apply_image(arr), self.size,
                          self.interpolation)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        f = 1 + (2 * _rand() - 1) * self.value
        return np.clip(arr * f, 0, 255 if arr.max() > 1.5 else 1.0)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        f = 1 + (2 * _rand() - 1) * self.value
        mean = arr.mean()
        return np.clip((arr - mean) * f + mean, 0, 255 if arr.max() > 1.5 else 1.0)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False, center=None,
                 fill=0, keys=None):
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) \
            else tuple(degrees)

    def _apply_image(self, img):
        arr = _to_numpy(img)
        deg = self.degrees[0] + _rand() * (self.degrees[1] - self.degrees[0])
        return rotate(arr, deg)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) else \
            [padding] * 4
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_numpy(img)
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        return np.pad(arr, ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2),
                      constant_values=self.fill)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _to_numpy(img)[:, ::-1].copy()


def vflip(img):
    return _to_numpy(img)[::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    return _to_numpy(img)[top:top + height, left:left + width]


# -- geometric + photometric functional ops (ref: vision/transforms/
#    functional.py; cv2/PIL backends replaced by a numpy inverse-map
#    bilinear sampler — host-side preprocessing, device never involved) ----

def _inverse_map_sample(arr, inv, out_h=None, out_w=None, interpolation="bilinear",
                        fill=0):
    """Sample arr (H, W[, C]) at positions inv @ [x_out, y_out, 1]."""
    H, W = arr.shape[:2]
    oh, ow = out_h or H, out_w or W
    ys, xs = np.meshgrid(np.arange(oh, dtype=np.float32),
                         np.arange(ow, dtype=np.float32), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1)
    mapped = inv @ coords
    if inv.shape[0] == 3:  # perspective: divide by w
        mapped = mapped[:2] / np.maximum(np.abs(mapped[2:3]), 1e-9) \
            * np.sign(mapped[2:3])
    eps = 1e-4  # tolerate trig round-off at exact-gridpoint mappings
    sx = np.clip(mapped[0].reshape(oh, ow), -1 - eps, W)
    sy = np.clip(mapped[1].reshape(oh, ow), -1 - eps, H)
    valid = (sx >= -eps) & (sx <= W - 1 + eps) & (sy >= -eps) & (sy <= H - 1 + eps)
    sx = np.clip(sx, 0, W - 1)
    sy = np.clip(sy, 0, H - 1)
    if interpolation == "nearest":
        xi = np.clip(np.round(sx), 0, W - 1).astype(np.int64)
        yi = np.clip(np.round(sy), 0, H - 1).astype(np.int64)
        out = arr[yi, xi].astype(np.float32)
    else:
        x0 = np.clip(np.floor(sx), 0, W - 1).astype(np.int64)
        y0 = np.clip(np.floor(sy), 0, H - 1).astype(np.int64)
        x1 = np.clip(x0 + 1, 0, W - 1)
        y1 = np.clip(y0 + 1, 0, H - 1)
        wx = (sx - x0).astype(np.float32)
        wy = (sy - y0).astype(np.float32)
        if arr.ndim == 3:
            wx, wy = wx[..., None], wy[..., None]
        out = (arr[y0, x0] * (1 - wy) * (1 - wx) + arr[y0, x1] * (1 - wy) * wx
               + arr[y1, x0] * wy * (1 - wx) + arr[y1, x1] * wy * wx)
    mask = valid if arr.ndim == 2 else valid[..., None]
    out = np.where(mask, out, np.float32(fill))
    return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


def _affine_matrix(angle, translate, scale, shear, center):
    import math
    rot = math.radians(angle)
    sx, sy = [math.radians(s) for s in shear]
    cx, cy = center
    tx, ty = translate
    # forward matrix M = T(center) R S Sh T(-center) + translate
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    M = np.array([[a * scale, b * scale, 0.0],
                  [c * scale, d * scale, 0.0],
                  [0.0, 0.0, 1.0]], np.float64)
    M[0, 2] = cx + tx - M[0, 0] * cx - M[0, 1] * cy
    M[1, 2] = cy + ty - M[1, 0] * cx - M[1, 1] * cy
    return M


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine-transform an HWC image (ref functional.affine)."""
    arr = _to_numpy(img)
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    M = _affine_matrix(angle, translate, scale, shear, center)
    inv = np.linalg.inv(M)[:2]
    return _inverse_map_sample(arr, inv, interpolation=interpolation, fill=fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate an HWC image by angle degrees counter-clockwise."""
    arr = _to_numpy(img)
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    out_h, out_w = H, W
    if expand:
        import math
        rad = math.radians(angle)
        out_w = int(abs(W * math.cos(rad)) + abs(H * math.sin(rad)) + 0.5)
        out_h = int(abs(W * math.sin(rad)) + abs(H * math.cos(rad)) + 0.5)
    M = _affine_matrix(-angle, (0, 0), 1.0, (0.0, 0.0), center)
    if expand:
        M[0, 2] += (out_w - W) * 0.5
        M[1, 2] += (out_h - H) * 0.5
    inv = np.linalg.inv(M)[:2]
    return _inverse_map_sample(arr, inv, out_h, out_w, interpolation, fill)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Projective warp mapping startpoints -> endpoints (ref functional)."""
    arr = _to_numpy(img)
    A = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
    b = np.array([p for s in startpoints for p in s], np.float64)
    h = np.linalg.solve(np.array(A, np.float64), b)
    inv = np.concatenate([h, [1.0]]).reshape(3, 3)
    return _inverse_map_sample(arr, inv, interpolation=interpolation, fill=fill)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_numpy(img)
    p = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    cfg = ((p[1], p[3]), (p[0], p[2])) + ((0, 0),) * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, cfg, constant_values=fill)
    return np.pad(arr, cfg, mode={"reflect": "reflect", "edge": "edge",
                                  "symmetric": "symmetric"}[padding_mode])


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a region with value v. Works on HWC/CHW numpy or Tensor."""
    if isinstance(img, Tensor):
        arr = np.array(img._data)
        arr[..., i:i + h, j:j + w] = v
        return Tensor(arr)
    arr = _to_numpy(img) if inplace is False else img
    arr = np.array(arr)
    if arr.ndim == 3 and arr.shape[0] in (1, 3):  # CHW
        arr[:, i:i + h, j:j + w] = v
    else:  # HWC
        arr[i:i + h, j:j + w] = v
    return arr


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy(img).astype(np.float32)
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return out.astype(_to_numpy(img).dtype)


def adjust_brightness(img, brightness_factor):
    arr = _to_numpy(img).astype(np.float32)
    hi = 255 if _to_numpy(img).dtype == np.uint8 or arr.max() > 1.5 else 1.0
    return np.clip(arr * brightness_factor, 0, hi).astype(_to_numpy(img).dtype)


def adjust_contrast(img, contrast_factor):
    arr = _to_numpy(img).astype(np.float32)
    hi = 255 if _to_numpy(img).dtype == np.uint8 or arr.max() > 1.5 else 1.0
    mean = to_grayscale(arr)[..., 0].mean()
    return np.clip((arr - mean) * contrast_factor + mean, 0,
                   hi).astype(_to_numpy(img).dtype)


def adjust_saturation(img, saturation_factor):
    arr = _to_numpy(img).astype(np.float32)
    hi = 255 if _to_numpy(img).dtype == np.uint8 or arr.max() > 1.5 else 1.0
    gray = to_grayscale(arr)
    return np.clip(arr * saturation_factor + gray.astype(np.float32)
                   * (1 - saturation_factor), 0, hi).astype(_to_numpy(img).dtype)


def _rgb_to_hsv(arr):
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = np.max(arr, -1)
    minc = np.min(arr, -1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-9), 0)
    rc = (maxc - r) / np.maximum(d, 1e-9)
    gc = (maxc - g) / np.maximum(d, 1e-9)
    bc = (maxc - b) / np.maximum(d, 1e-9)
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(d == 0, 0.0, (h / 6.0) % 1.0)
    return np.stack([h, s, v], -1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int64) % 6
    table = np.stack([
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1)], 0)
    return np.take_along_axis(table, i[None, ..., None], 0)[0]


def adjust_hue(img, hue_factor):
    assert -0.5 <= hue_factor <= 0.5
    src = _to_numpy(img)
    scale = 255.0 if src.dtype == np.uint8 or src.max() > 1.5 else 1.0
    hsv = _rgb_to_hsv(src.astype(np.float32) / scale)
    hsv[..., 0] = (hsv[..., 0] + hue_factor) % 1.0
    out = _hsv_to_rgb(hsv) * scale
    return np.clip(out, 0, scale).astype(src.dtype)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        f = 1 + (2 * _rand() - 1) * self.value
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        assert 0 <= value <= 0.5
        self.value = value

    def _apply_image(self, img):
        f = (2 * _rand() - 1) * self.value
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order
    (ref transforms.ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.tfs = []
        if brightness:
            self.tfs.append(BrightnessTransform(brightness))
        if contrast:
            self.tfs.append(ContrastTransform(contrast))
        if saturation:
            self.tfs.append(SaturationTransform(saturation))
        if hue:
            self.tfs.append(HueTransform(hue))

    def _apply_image(self, img):
        order = np.argsort([_rand() for _ in self.tfs])
        for i in order:
            img = self.tfs[i]._apply_image(img)
        return img


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        self.degrees = (-degrees, degrees) if isinstance(degrees, numbers.Number) \
            else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _to_numpy(img)
        H, W = arr.shape[:2]
        ang = self.degrees[0] + _rand() * (self.degrees[1] - self.degrees[0])
        tx = ty = 0.0
        if self.translate:
            tx = (2 * _rand() - 1) * self.translate[0] * W
            ty = (2 * _rand() - 1) * self.translate[1] * H
        sc = 1.0
        if self.scale:
            sc = self.scale[0] + _rand() * (self.scale[1] - self.scale[0])
        sh = (0.0, 0.0)
        if self.shear:
            s = self.shear if isinstance(self.shear, (list, tuple)) else (-self.shear, self.shear)
            if len(s) == 2:
                sh = (s[0] + _rand() * (s[1] - s[0]), 0.0)
            else:
                sh = (s[0] + _rand() * (s[1] - s[0]),
                      s[2] + _rand() * (s[3] - s[2]))
        return affine(arr, ang, (tx, ty), sc, sh, self.interpolation,
                      self.fill, self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest",
                 fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if _rand() > self.prob:
            return _to_numpy(img)
        arr = _to_numpy(img)
        H, W = arr.shape[:2]
        d = self.distortion_scale
        half_h, half_w = int(H * d / 2), int(W * d / 2)
        tl = (int(_rand() * half_w), int(_rand() * half_h))
        tr = (W - 1 - int(_rand() * half_w), int(_rand() * half_h))
        br = (W - 1 - int(_rand() * half_w), H - 1 - int(_rand() * half_h))
        bl = (int(_rand() * half_w), H - 1 - int(_rand() * half_h))
        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        return perspective(arr, start, [tl, tr, br, bl], self.interpolation,
                           self.fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if _rand() > self.prob:
            return arr
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3)
        H, W = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = H * W
        for _ in range(10):
            target = area * (self.scale[0] + _rand()
                             * (self.scale[1] - self.scale[0]))
            logr = np.log(self.ratio[0]) + _rand() * (np.log(self.ratio[1])
                                                      - np.log(self.ratio[0]))
            r = np.exp(logr)
            h = int(round(np.sqrt(target * r)))
            w = int(round(np.sqrt(target / r)))
            if h < H and w < W:
                i = int(_rand() * (H - h))
                j = int(_rand() * (W - w))
                v = self.value if self.value != "random" else \
                    np.random.rand(*((arr.shape[0], h, w) if chw else (h, w, arr.shape[-1]))).astype(np.float32)
                return erase(arr, i, j, h, w, v)
        return arr
