"""Inference/deploy path (ref: paddle/fluid/inference, python/paddle/inference).

The reference deploys a serialized static Program (`.pdmodel` + `.pdiparams`)
loaded by a C++ predictor. The TPU-native artifact is a *serialized StableHLO
export* (`jax.export`): the traced forward is saved as a compiler-level
program, so loading needs **no Python model code** — exactly the property the
reference's Program gives its C++ predictor — and XLA AOT-compiles it for the
target backend on load.

Artifact layout (``save_inference_model(prefix, layer, input_spec)``):
    ``{prefix}.pdhlo``      serialized StableHLO module (jax.export blob)
    ``{prefix}.pdiparams``  weights + buffers (framework.io pickle)
    ``{prefix}.pdconfig``   json: input specs, export platforms, version

Dynamic batch: an ``InputSpec`` leading dim of ``None``/-1 exports with a
symbolic dimension, so one artifact serves any batch size.
"""
from __future__ import annotations

import json
import os

import jax
import numpy as np
from jax import export as jexport

from ..framework import io as fio
from ..jit.functional import capture_buffers, capture_params, functional_call
from ..static import InputSpec
from ..tensor_impl import Tensor

_HLO_SUFFIX = ".pdhlo"
_PARAMS_SUFFIX = ".pdiparams"
_CONFIG_SUFFIX = ".pdconfig"


def _spec_to_sds(spec, scope):
    """InputSpec -> ShapeDtypeStruct, mapping None/-1 leading dims to a
    symbolic batch dimension (shape polymorphism)."""
    shape = []
    symbolic = False
    for i, d in enumerate(spec.shape):
        if d is None or (isinstance(d, int) and d < 0):
            shape.append("b" if i == 0 else f"d{i}")
            symbolic = True
        else:
            shape.append(int(d))
    dtype = np.dtype(spec.dtype)
    if symbolic:
        dims = jexport.symbolic_shape(
            "(" + ", ".join(str(s) for s in shape) + ")", scope=scope)
        return jax.ShapeDtypeStruct(dims, dtype)
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def save_inference_model(path_prefix, layer, input_spec, platforms=None):
    """Export ``layer``'s eval-mode forward for deployment.

    ``input_spec``: list of InputSpec (or Tensors/arrays used as templates).
    ``platforms``: e.g. ``["cpu", "tpu"]`` for a cross-platform artifact;
    default exports for the current default backend only.
    """
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        params = {k: np.asarray(jax.device_get(v)) for k, v in capture_params(layer).items()}
        buffers = {k: np.asarray(jax.device_get(v)) for k, v in capture_buffers(layer).items()}

        def fn(params, buffers, *inputs):
            outs, _ = functional_call(layer, params, buffers, inputs,
                                      rng_key=jax.random.PRNGKey(0))
            return outs

        specs = []
        for s in input_spec:
            if isinstance(s, InputSpec):
                specs.append(s)
            else:
                arr = s._data if isinstance(s, Tensor) else np.asarray(s)
                specs.append(InputSpec(shape=arr.shape, dtype=str(arr.dtype)))
        scope = jexport.SymbolicScope()
        input_sds = [_spec_to_sds(s, scope) for s in specs]
        params_sds = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
        buffers_sds = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers)

        kwargs = {}
        if platforms is not None:
            kwargs["platforms"] = tuple(platforms)
        exported = jexport.export(jax.jit(fn), **kwargs)(
            params_sds, buffers_sds, *input_sds)

        with open(path_prefix + _HLO_SUFFIX, "wb") as f:
            f.write(exported.serialize())
        fio.save({"params": params, "buffers": buffers}, path_prefix + _PARAMS_SUFFIX)
        with open(path_prefix + _CONFIG_SUFFIX, "w") as f:
            json.dump({
                "version": 1,
                "inputs": [{"shape": [d if isinstance(d, int) else None for d in s.shape],
                            "dtype": str(np.dtype(s.dtype)), "name": s.name} for s in specs],
                "platforms": list(exported.platforms),
            }, f, indent=2)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()


class PrecisionType:
    """ref: paddle.inference.PrecisionType."""
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class Config:
    """Deploy config (ref: paddle.inference.Config, paddle/fluid/inference/
    api/paddle_analysis_config.h).

    The reference's knobs are IR-pass and engine selection; the TPU-native
    analogs are compile-time choices on the XLA executable:
      precision           -> serve-time param/compute dtype cast (bf16 Half)
      enable_memory_optim -> donate input buffers to the executable
      pass control        -> raw XLA compiler options on the jit
                             (set_compiler_option / delete_pass no-op list)
      enable_profile      -> jax profiler trace around run()
    GPU/TensorRT/MKLDNN toggles are accepted no-ops (recorded, with the
    device owned by jax), so reference deploy scripts run unmodified.
    """

    def __init__(self, prog_file=None, params_file=None):
        # accept either a path prefix or explicit file paths
        if prog_file is not None and prog_file.endswith(_HLO_SUFFIX):
            self.path_prefix = prog_file[: -len(_HLO_SUFFIX)]
        else:
            self.path_prefix = prog_file
        self._device = None
        self._precision = PrecisionType.Float32
        self._memory_optim = False
        self._profile = False
        self._compiler_options = {}
        self._deleted_passes = []
        self._num_threads = None

    # -- model location ----------------------------------------------------
    def set_model(self, prog_file, params_file=None):
        if prog_file.endswith(_HLO_SUFFIX):
            prog_file = prog_file[: -len(_HLO_SUFFIX)]
        self.path_prefix = prog_file

    def model_dir(self):
        return self.path_prefix

    # -- device / precision -------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision_mode=None):
        # reference API compat; the device is jax's. precision_mode is the
        # real signal: Half/Bfloat16 serve the model in bf16 on the MXU.
        self._device = "gpu"
        if precision_mode in (PrecisionType.Half, PrecisionType.Bfloat16):
            self._precision = PrecisionType.Bfloat16

    def disable_gpu(self):
        self._device = "cpu"

    def set_precision(self, precision):
        if precision == PrecisionType.Half:
            precision = PrecisionType.Bfloat16  # fp16 serves as bf16 on TPU
        if precision not in (PrecisionType.Float32, PrecisionType.Bfloat16):
            raise NotImplementedError(
                f"serving precision {precision!r} is not supported here; "
                f"int8 inference goes through paddle_tpu.quantization "
                f"(PTQ/QAT) before export")
        self._precision = precision

    def precision(self):
        return self._precision

    # -- executable options --------------------------------------------------
    def enable_memory_optim(self, x=True):
        self._memory_optim = bool(x)

    def set_compiler_option(self, key, value):
        """Pass-control analog: raw XLA compiler option on the compiled
        executable (e.g. 'xla_tpu_enable_latency_hiding_scheduler')."""
        self._compiler_options[key] = value

    def delete_pass(self, name):
        # the reference prunes IR passes by name; XLA's pipeline is not
        # name-addressable — record for introspection, compilation is
        # unaffected
        self._deleted_passes.append(name)

    def pass_builder(self):
        return self._deleted_passes

    def switch_ir_optim(self, x=True):
        pass  # XLA always optimizes; kept for script compat

    def enable_profile(self):
        self._profile = True

    def set_cpu_math_library_num_threads(self, n):
        self._num_threads = int(n)

    def enable_mkldnn(self):
        pass  # host library choice is jax's

    def enable_tensorrt_engine(self, *a, **k):
        raise NotImplementedError(
            "TensorRT does not exist on TPU; the XLA executable IS the "
            "optimized engine (precision via set_precision)")

    def summary(self):
        return {"model": self.path_prefix, "precision": self._precision,
                "memory_optim": self._memory_optim,
                "compiler_options": dict(self._compiler_options),
                "deleted_passes": list(self._deleted_passes)}


class Predictor:
    """Runs a saved inference artifact. No model source code required.
    A `Config` applies serve-time choices: precision cast, input-buffer
    donation (memory optim), raw XLA compiler options, profiling."""

    def __init__(self, path_prefix, config=None):
        if isinstance(path_prefix, Config):
            config = path_prefix
            path_prefix = config.path_prefix
        self.path_prefix = path_prefix
        self._config_obj = config
        with open(path_prefix + _HLO_SUFFIX, "rb") as f:
            self._exported = jexport.deserialize(f.read())
        blob = fio.load(path_prefix + _PARAMS_SUFFIX)
        self._params = blob["params"]
        self._buffers = blob["buffers"]
        with open(path_prefix + _CONFIG_SUFFIX) as f:
            self.config = json.load(f)
        jit_kwargs = {}
        exported_call = self._exported.call
        serve_fn = exported_call
        if config is not None:
            if config._precision == PrecisionType.Bfloat16:
                # the exported HLO's avals are fixed, so precision here is a
                # STORAGE choice: weights live bf16 in HBM (half footprint)
                # and upcast at the jit boundary (XLA fuses the cast).
                # For bf16 COMPUTE, export under amp.decorate(level='O2').
                import jax.numpy as jnp
                pd = jax.tree_util.tree_map(lambda a: a.dtype, self._params)
                bd = jax.tree_util.tree_map(lambda a: a.dtype, self._buffers)
                shrink = lambda a: a.astype(jnp.bfloat16) if hasattr(  # noqa: E731
                    a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                    else a
                self._params = jax.tree_util.tree_map(shrink, self._params)
                self._buffers = jax.tree_util.tree_map(shrink, self._buffers)

                def serve_fn(params, buffers, *arrs):
                    p = jax.tree_util.tree_map(
                        lambda a, d: a.astype(d), params, pd)
                    b = jax.tree_util.tree_map(
                        lambda a, d: a.astype(d), buffers, bd)
                    return exported_call(p, b, *arrs)
            if config._compiler_options:
                jit_kwargs["compiler_options"] = dict(
                    config._compiler_options)
            if config._memory_optim:
                # donate the activations' input slots (params/buffers are
                # reused across calls and must survive)
                jit_kwargs["donate_argnums"] = tuple(
                    2 + i for i in range(len(self.config["inputs"])))
        try:
            self._call = jax.jit(serve_fn, **jit_kwargs)
        except TypeError:
            # older jax without compiler_options on jit
            jit_kwargs.pop("compiler_options", None)
            self._call = jax.jit(serve_fn, **jit_kwargs)
        self._inputs = [None] * len(self.config["inputs"])

    # -- simple API --------------------------------------------------------
    def run(self, *inputs):
        """Predict: numpy/Tensor inputs -> list of numpy outputs."""
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = tuple(inputs[0])
        arrs = [x._data if isinstance(x, Tensor) else np.asarray(x) for x in inputs]
        cfg = self._config_obj
        if cfg is not None and cfg._memory_optim:
            # donation deletes input buffers after the call — donate fresh
            # copies, never the caller's live Tensor storage
            arrs = [jax.numpy.array(a, copy=True)
                    if isinstance(a, jax.Array) else a for a in arrs]
        prof_ctx = None
        if cfg is not None and cfg._profile:
            from .. import profiler as _prof
            prof_ctx = _prof.RecordEvent("inference.run")
            prof_ctx.__enter__()
        try:
            outs = self._call(self._params, self._buffers, *arrs)
            flat = jax.tree_util.tree_leaves(outs)
            # fetch INSIDE the profiled region: execution is async and the
            # trace must cover the device time, not just dispatch
            return [np.asarray(jax.device_get(o)) for o in flat]
        finally:
            if prof_ctx is not None:
                prof_ctx.__exit__(None, None, None)

    # -- reference-style handle API ---------------------------------------
    def get_input_names(self):
        return [i["name"] or f"x{k}" for k, i in enumerate(self.config["inputs"])]

    def get_input_handle(self, name):
        idx = self.get_input_names().index(name)
        pred = self

        class _Handle:
            def copy_from_cpu(self, arr):
                pred._inputs[idx] = np.asarray(arr)

            def copy_to_cpu(self):
                if pred._inputs[idx] is None:
                    raise RuntimeError(
                        f"input handle {name!r} has no data; call "
                        f"copy_from_cpu first")
                return pred._inputs[idx]

        return _Handle()

    def get_output_names(self):
        self._ensure_ran()
        return [f"out{k}" for k in range(len(self._outputs))]

    def get_output_handle(self, name):
        idx = int(name[3:]) if name.startswith("out") else 0
        pred = self

        class _Handle:
            def copy_to_cpu(self):
                return pred._outputs[idx]

        return _Handle()

    def run_handles(self):
        self._outputs = self.run(*self._inputs)
        return True

    def _ensure_ran(self):
        if not hasattr(self, "_outputs"):
            raise RuntimeError("call run()/run_handles() first")

    # -- serving handoff ----------------------------------------------------
    def serve(self, gpt_config, **engine_kwargs):
        """Hand this artifact's weights to the continuous-batching engine
        (`paddle_tpu.serving.Engine`). The exported StableHLO is a
        whole-sequence forward — the wrong program for token-at-a-time
        serving — so `serve()` rebuilds the functional GPT param tree from
        the artifact's weight dict instead (artifact must be a
        GPTForCausalLM export; `gpt_config` is its GPTConfig).

        Engine kwargs pass through — including the tensor-parallel ones
        (``mp=``, ``mesh=``, ``comm_backend=``): ``serve(cfg, mp=4)``
        shards the rebuilt tree and the paged KV pool over a 4-chip mp
        mesh at construction — and ``quant=`` (a ``serving.QuantSpec``,
        e.g. from ``serving.quant.calibrate``, or a dtype string):
        ``serve(cfg, quant=spec)`` deploys the artifact int8/fp8
        weight-only with a quantized paged KV pool. A spec whose
        calibrated scale shapes don't match the artifact's params tree
        is rejected up front with a ``QuantSpecError`` naming the
        offending leaf — before any device placement happens."""
        params = _gpt_functional_params(self._params, gpt_config)
        from ..serving import Engine
        return Engine(params=params, config=gpt_config, **engine_kwargs)


def _gpt_functional_params(named, config):
    """Predictor weight dict (capture_params qualified names) -> the
    functional layout generation/serving consume (init_gpt_params)."""
    import jax.numpy as jnp
    need = ["gpt.wte.weight", "gpt.wpe.weight",
            "gpt.ln_f.weight", "gpt.ln_f.bias"]
    if any(k not in named for k in need):
        raise ValueError(
            "artifact is not a GPTForCausalLM export (missing gpt.* "
            "weights); serve() only maps the GPT family")
    from ..models.gpt import BLOCK_PARAM_PATHS
    L = config.num_layers
    blocks = {k: jnp.stack([jnp.asarray(named[f"gpt.h.{i}.{suffix}"])
                            for i in range(L)])
              for k, suffix in BLOCK_PARAM_PATHS.items()}
    head = (jnp.asarray(named["lm_head.weight"])
            if "lm_head.weight" in named
            else jnp.asarray(named["gpt.wte.weight"]).T)
    return {
        "wte": jnp.asarray(named["gpt.wte.weight"]),
        "wpe": jnp.asarray(named["gpt.wpe.weight"]),
        "lnf_g": jnp.asarray(named["gpt.ln_f.weight"]),
        "lnf_b": jnp.asarray(named["gpt.ln_f.bias"]),
        "head_w": head,
        "blocks": blocks,
    }


def serve(model=None, *, params=None, config=None, **engine_kwargs):
    """Build a continuous-batching serving engine
    (`paddle_tpu.serving.Engine`) from a GPTForCausalLM Layer or a
    functional param tree — the deploy entry point once a model graduates
    from single-shot `Predictor.run` to request traffic. An mp-trained
    ``HybridTrainStep`` tree serves directly (``serve(params=step.params,
    config=step.config, mp=4)``): head-major sharded weights are
    device_put straight to the serving layout, no host round trip.
    ``quant=`` accepts a ``serving.QuantSpec`` (PTQ-calibrated via
    ``serving.quant.calibrate``) or a dtype string for int8/fp8
    weight-only serving over a quantized paged KV pool; a spec that
    doesn't match the params tree raises ``QuantSpecError`` naming the
    leaf, up front."""
    from ..serving import Engine
    return Engine(model, params=params, config=config, **engine_kwargs)


def load_inference_model(path_prefix):
    return Predictor(path_prefix)


def create_predictor(config):
    return Predictor(config.path_prefix, config=config)
