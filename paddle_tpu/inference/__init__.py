"""Inference/deploy path (ref: paddle/fluid/inference, python/paddle/inference).

The reference deploys a serialized static Program (`.pdmodel` + `.pdiparams`)
loaded by a C++ predictor. The TPU-native artifact is a *serialized StableHLO
export* (`jax.export`): the traced forward is saved as a compiler-level
program, so loading needs **no Python model code** — exactly the property the
reference's Program gives its C++ predictor — and XLA AOT-compiles it for the
target backend on load.

Artifact layout (``save_inference_model(prefix, layer, input_spec)``):
    ``{prefix}.pdhlo``      serialized StableHLO module (jax.export blob)
    ``{prefix}.pdiparams``  weights + buffers (framework.io pickle)
    ``{prefix}.pdconfig``   json: input specs, export platforms, version

Dynamic batch: an ``InputSpec`` leading dim of ``None``/-1 exports with a
symbolic dimension, so one artifact serves any batch size.
"""
from __future__ import annotations

import json
import os

import jax
import numpy as np
from jax import export as jexport

from ..framework import io as fio
from ..jit.functional import capture_buffers, capture_params, functional_call
from ..static import InputSpec
from ..tensor_impl import Tensor

_HLO_SUFFIX = ".pdhlo"
_PARAMS_SUFFIX = ".pdiparams"
_CONFIG_SUFFIX = ".pdconfig"


def _spec_to_sds(spec, scope):
    """InputSpec -> ShapeDtypeStruct, mapping None/-1 leading dims to a
    symbolic batch dimension (shape polymorphism)."""
    shape = []
    symbolic = False
    for i, d in enumerate(spec.shape):
        if d is None or (isinstance(d, int) and d < 0):
            shape.append("b" if i == 0 else f"d{i}")
            symbolic = True
        else:
            shape.append(int(d))
    dtype = np.dtype(spec.dtype)
    if symbolic:
        dims = jexport.symbolic_shape(
            "(" + ", ".join(str(s) for s in shape) + ")", scope=scope)
        return jax.ShapeDtypeStruct(dims, dtype)
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def save_inference_model(path_prefix, layer, input_spec, platforms=None):
    """Export ``layer``'s eval-mode forward for deployment.

    ``input_spec``: list of InputSpec (or Tensors/arrays used as templates).
    ``platforms``: e.g. ``["cpu", "tpu"]`` for a cross-platform artifact;
    default exports for the current default backend only.
    """
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        params = {k: np.asarray(jax.device_get(v)) for k, v in capture_params(layer).items()}
        buffers = {k: np.asarray(jax.device_get(v)) for k, v in capture_buffers(layer).items()}

        def fn(params, buffers, *inputs):
            outs, _ = functional_call(layer, params, buffers, inputs,
                                      rng_key=jax.random.PRNGKey(0))
            return outs

        specs = []
        for s in input_spec:
            if isinstance(s, InputSpec):
                specs.append(s)
            else:
                arr = s._data if isinstance(s, Tensor) else np.asarray(s)
                specs.append(InputSpec(shape=arr.shape, dtype=str(arr.dtype)))
        scope = jexport.SymbolicScope()
        input_sds = [_spec_to_sds(s, scope) for s in specs]
        params_sds = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
        buffers_sds = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), buffers)

        kwargs = {}
        if platforms is not None:
            kwargs["platforms"] = tuple(platforms)
        exported = jexport.export(jax.jit(fn), **kwargs)(
            params_sds, buffers_sds, *input_sds)

        with open(path_prefix + _HLO_SUFFIX, "wb") as f:
            f.write(exported.serialize())
        fio.save({"params": params, "buffers": buffers}, path_prefix + _PARAMS_SUFFIX)
        with open(path_prefix + _CONFIG_SUFFIX, "w") as f:
            json.dump({
                "version": 1,
                "inputs": [{"shape": [d if isinstance(d, int) else None for d in s.shape],
                            "dtype": str(np.dtype(s.dtype)), "name": s.name} for s in specs],
                "platforms": list(exported.platforms),
            }, f, indent=2)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()


class Config:
    """Deploy config (parity shim for paddle.inference.Config)."""

    def __init__(self, prog_file=None, params_file=None):
        # accept either a path prefix or explicit file paths
        if prog_file is not None and prog_file.endswith(_HLO_SUFFIX):
            self.path_prefix = prog_file[: -len(_HLO_SUFFIX)]
        else:
            self.path_prefix = prog_file
        self._device = None

    def enable_use_gpu(self, *a, **k):  # reference API compat; device is jax's
        self._device = "gpu"

    def disable_gpu(self):
        self._device = "cpu"


class Predictor:
    """Runs a saved inference artifact. No model source code required."""

    def __init__(self, path_prefix):
        self.path_prefix = path_prefix
        with open(path_prefix + _HLO_SUFFIX, "rb") as f:
            self._exported = jexport.deserialize(f.read())
        blob = fio.load(path_prefix + _PARAMS_SUFFIX)
        self._params = blob["params"]
        self._buffers = blob["buffers"]
        with open(path_prefix + _CONFIG_SUFFIX) as f:
            self.config = json.load(f)
        self._call = jax.jit(self._exported.call)
        self._inputs = [None] * len(self.config["inputs"])

    # -- simple API --------------------------------------------------------
    def run(self, *inputs):
        """Predict: numpy/Tensor inputs -> list of numpy outputs."""
        if len(inputs) == 1 and isinstance(inputs[0], (list, tuple)):
            inputs = tuple(inputs[0])
        arrs = [x._data if isinstance(x, Tensor) else np.asarray(x) for x in inputs]
        outs = self._call(self._params, self._buffers, *arrs)
        flat = jax.tree_util.tree_leaves(outs)
        return [np.asarray(jax.device_get(o)) for o in flat]

    # -- reference-style handle API ---------------------------------------
    def get_input_names(self):
        return [i["name"] or f"x{k}" for k, i in enumerate(self.config["inputs"])]

    def get_input_handle(self, name):
        idx = self.get_input_names().index(name)
        pred = self

        class _Handle:
            def copy_from_cpu(self, arr):
                pred._inputs[idx] = np.asarray(arr)

            def copy_to_cpu(self):
                if pred._inputs[idx] is None:
                    raise RuntimeError(
                        f"input handle {name!r} has no data; call "
                        f"copy_from_cpu first")
                return pred._inputs[idx]

        return _Handle()

    def get_output_names(self):
        self._ensure_ran()
        return [f"out{k}" for k in range(len(self._outputs))]

    def get_output_handle(self, name):
        idx = int(name[3:]) if name.startswith("out") else 0
        pred = self

        class _Handle:
            def copy_to_cpu(self):
                return pred._outputs[idx]

        return _Handle()

    def run_handles(self):
        self._outputs = self.run(*self._inputs)
        return True

    def _ensure_ran(self):
        if not hasattr(self, "_outputs"):
            raise RuntimeError("call run()/run_handles() first")


def load_inference_model(path_prefix):
    return Predictor(path_prefix)


def create_predictor(config):
    return Predictor(config.path_prefix)
