"""Topology-elastic serving fleet support: per-chip failure detection and
mesh re-forming for tensor-parallel replica GROUPS.

PR 12 made a supervisor replica an mp *group* — one lost chip takes a
whole multi-chip replica with it, and a respawn pinned to the original
devices would strand the fleet on a real chip failure. This module closes
the gap the way PR 11's ``ElasticMeshSupervisor`` did for training:

  * **per-chip detection** — ``FleetTopology`` watches every chip of the
    fleet individually: the deterministic injected schedule
    (``fault_injection.lost_serving_chips`` — serving-scoped
    ``serving_chip_loss_at``/``serving_chip_return_at`` with a sticky
    watermark) plus, with a heartbeat dir configured, per-CHIP heartbeat
    files (``distributed.elastic.Heartbeat`` at chip granularity) whose
    staleness marks the chip down. Any lost chip marks its whole group
    down deterministically.
  * **mesh re-forming** — ``plan()`` recomputes a group's mesh over its
    SURVIVING chips (non-contiguous survivors included) at the LARGEST
    viable mp degree: the largest divisor of the configured mp that the
    survivors can host (a divisor of mp always divides hidden/heads/ffn,
    because mp itself does). The supervisor respawns the replica on that
    mesh through the PR 12 mp-portable snapshot path — pool geometry is
    global and the gather-only schedule is bitwise at every degree, so
    an mp=4 snapshot resumes bitwise on mp=2 or a single chip.
  * **grow-back** — when chips return (``serving_chip_return_at`` fires /
    heartbeats recover), ``plan()`` reports the restored degree and the
    supervisor re-forms UP from a live snapshot; engine builders are
    memoized per (cfg, mesh, rung), so growing back to a topology seen
    before reuses its compiled executables (zero new traces).

Every event lands in the observability registry's "elastic" family
(``group_reforms``/``grow_backs``/``degraded_groups``/
``serving_chips_lost``/``reform_latency_*`` plus per-replica
``active_mp_replica{i}`` gauges) → the Prometheus endpoint.
"""
from __future__ import annotations

import os
import time

from ..distributed.elastic import (
    Heartbeat, HeartbeatMonitor, _ecount, _egauge,
)
from ..utils import fault_injection as _fi


def mp_replica_meshes(num_replicas, mp, devices=None):
    """Partition ``devices`` (default: all) into ``num_replicas`` DISJOINT
    1-D ('mp',) meshes of ``mp`` chips each — under tensor-parallel
    serving a replica is an mp GROUP, not a chip. The device set may be
    arbitrary and non-contiguous (the survivors of a chip loss partition
    exactly like a fresh fleet). ``num_replicas=None`` derives the count
    from the device set, which must then divide evenly::

        meshes = serving.mp_replica_meshes(2, mp=4)      # 8 chips
        sup = ServingSupervisor(
            lambda i: serving.Engine(params=p, config=cfg,
                                     mesh=meshes[i]),
            num_replicas=2)

    Validates the n/mp/device combination up front with the offending
    numbers named (a bad combination used to surface as a deep
    mesh-construction error)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh
    devices = list(jax.devices() if devices is None else devices)
    mp = int(mp)
    if mp < 1:
        raise ValueError(f"mp_replica_meshes needs mp >= 1, got mp={mp}")
    if num_replicas is None:
        if len(devices) % mp:
            raise ValueError(
                f"cannot partition {len(devices)} devices into mp={mp} "
                f"groups: {len(devices)} % {mp} != 0 (pass num_replicas "
                f"explicitly to leave spares)")
        num_replicas = len(devices) // mp
    num_replicas = int(num_replicas)
    if num_replicas < 1:
        raise ValueError(
            f"mp_replica_meshes needs num_replicas >= 1, got "
            f"num_replicas={num_replicas}")
    need = num_replicas * mp
    if need > len(devices):
        raise ValueError(
            f"{num_replicas} mp={mp} replicas need {need} devices, only "
            f"{len(devices)} available")
    return [Mesh(np.array(devices[i * mp:(i + 1) * mp]), ("mp",))
            for i in range(num_replicas)]


def viable_mp(mp, available):
    """Largest viable mp degree ``m`` for a group with ``available``
    surviving chips: the largest divisor of the configured ``mp`` that
    the survivors can host. A divisor of mp always divides
    hidden/heads/ffn (the configured mp does — the original engine
    exists), so every degree this returns builds a valid sharded engine.
    Returns 0 when no chip survives."""
    mp, available = int(mp), int(available)
    for m in range(min(mp, available), 0, -1):
        if mp % m == 0:
            return m
    return 0


class FleetTopology:
    """Chip-level view of a serving fleet: ``num_replicas`` home groups of
    ``mp`` chips each over ``devices`` (global chip rank = index into
    ``devices``). Owns per-chip liveness (injected loss schedule + per-chip
    heartbeats) and the reform plan for each group.

    Single-process notes: ``beat()`` writes a heartbeat for EVERY fleet
    chip each boundary — the single-controller simulation of per-host
    heartbeat daemons (``FaultPlan.stale_heartbeat_ranks`` freezes
    individual chips, so their files age and ``lost_chips`` reports
    them). On a real pod each host beats for its own chips; only the
    monitoring half applies."""

    def __init__(self, devices, mp, num_replicas, heartbeat_dir=None,
                 heartbeat_timeout=None):
        import jax
        self.mp = int(mp)
        devices = list(jax.devices() if devices is None else devices)
        need = int(num_replicas) * self.mp
        # validate through the same path users hit (names n/mp/devices)
        mp_replica_meshes(num_replicas, self.mp, devices)
        self.devices = devices[:need]
        self.num_replicas = int(num_replicas)
        self.monitor = None
        self._beats = {}
        self._beat_interval = 0.0
        self._last_beat = None
        self._last_poll = None
        self._stale = set()
        if heartbeat_dir is not None:
            from ..flags import get_flags
            chips_dir = os.path.join(os.fspath(heartbeat_dir), "chips")
            timeout = (get_flags().get("FLAGS_serving_heartbeat_timeout",
                                       10.0)
                       if heartbeat_timeout is None else heartbeat_timeout)
            self.monitor = HeartbeatMonitor(chips_dir, len(self.devices),
                                            timeout=float(timeout))
            self._beats = {r: Heartbeat(chips_dir, rank=r)
                           for r in range(len(self.devices))}
            # freshness only has to beat the staleness timeout, not the
            # boundary rate: a boundary is roughly one decoded token, so
            # an unthrottled beat would json+rename every chip's file
            # ~1000x more often than detection needs
            self._beat_interval = float(timeout) / 3.0
        _egauge("serving_chips_lost", 0)

    def home(self, i):
        """Replica ``i``'s home chip ranks (global indices)."""
        return tuple(range(i * self.mp, (i + 1) * self.mp))

    def beat(self, step):
        """Heartbeat every fleet chip (the fault plan silently drops
        frozen chips' writes, so their files go stale). Throttled to a
        third of the staleness timeout: detection is time-based, so
        rewriting every file at every boundary buys nothing."""
        now = time.monotonic()
        if self._last_beat is not None \
                and now - self._last_beat < self._beat_interval:
            return
        self._last_beat = now
        for hb in self._beats.values():
            try:
                hb.beat(step=step)
            except OSError:
                # transient heartbeat-file IO is NOT chip death (same
                # policy as the supervisor's per-replica beat): the file
                # just ages, and only the staleness timeout may fail the
                # chip — one flaky write must not crash the supervising
                # loop or starve the other chips' beats
                pass

    def lost_chips(self, step):
        """Global ranks of chips lost as of supervisor step ``step``:
        the injected serving-scoped schedule (sticky watermark) plus
        chips whose heartbeat is stale. The file sweep is throttled to
        the same timeout/3 cadence as ``beat()`` (staleness is
        time-based — N opens + JSON parses per decoded token buy no
        detection latency); the injected schedule stays per-step, so
        tests remain deterministic."""
        lost = set(_fi.lost_serving_chips(step))
        lost &= set(range(len(self.devices)))
        if self.monitor is not None:
            now = time.monotonic()
            if self._last_poll is None \
                    or now - self._last_poll >= self._beat_interval:
                self._stale = set(self.monitor.failed_ranks(
                    list(range(len(self.devices)))))
                self._last_poll = now
            lost |= self._stale
        _egauge("serving_chips_lost", len(lost))
        return frozenset(lost)

    def plan(self, i, lost):
        """(mp_degree, chip ranks) replica ``i`` should run on given the
        ``lost`` chip set: its surviving home chips (home order, so the
        plan — and therefore the mesh the builders memoize on — is
        deterministic) at the largest viable degree. None when no home
        chip survives. Pure arithmetic — cheap enough for every boundary;
        the mesh is built by ``mesh_for`` only when a reform actually
        runs."""
        alive = [c for c in self.home(i) if c not in lost]
        m = viable_mp(self.mp, len(alive))
        if m < 1:
            return None
        return m, tuple(alive[:m])

    def mesh_for(self, ranks):
        """The 1-D ('mp',) mesh over ``ranks`` (global chip indices) — a
        re-created mesh over the same devices hashes equal, so the
        memoized engine builders hit on a grow-back."""
        return mp_replica_meshes(1, len(ranks),
                                 [self.devices[c] for c in ranks])[0]


def record_reform(kind, latency_s):
    """Ledger one group reform into the "elastic" family: ``kind`` is
    "loss" (chip-loss shrink / degraded respawn) or "grow" (grow-back to
    a higher degree)."""
    _ecount("group_reforms")
    if kind == "grow":
        _ecount("grow_backs")
    _egauge("reform_latency_s_last", latency_s)
    _ecount("reform_latency_s_total", latency_s)


def degraded_count(replicas, configured_mp):
    """Groups running below their configured degree — down/reforming
    groups count too (zero capacity is as degraded as it gets). Retired
    ones don't, and neither do draining ones: a rolling restart takes a
    replica out of rotation on purpose with its chips healthy — an
    operator alerting on this gauge must not get paged by routine
    upgrades. THE shared definition: the elastic-family gauge and the
    supervisor's telemetry() both read it, so they can never diverge."""
    n = 0
    for rep in replicas:
        if rep.state in ("retired", "draining"):
            continue
        mp = int(getattr(rep, "mp", 0) or 0) if rep.state == "up" else 0
        if mp < int(configured_mp):
            n += 1
    return n


def set_group_gauges(replicas, configured_mp):
    """Refresh the live fleet-shape gauges: per-replica active mp, the
    degraded-group count (``degraded_count``) and — for disaggregated
    fleets — each replica's serving role (0=both, 1=prefill, 2=decode;
    an operator watching a chip-loss rebalance sees the flip here)."""
    role_code = {"both": 0, "prefill": 1, "decode": 2}
    for rep in replicas:
        mp = int(getattr(rep, "mp", 0) or 0)
        if rep.state != "up":
            mp = 0
        _egauge(f"active_mp_replica{rep.idx}", mp)
        role = getattr(rep, "role", "both")
        if role != "both" or getattr(rep, "configured_role", "both") != "both":
            _egauge(f"serving_role_replica{rep.idx}",
                    role_code.get(role, 0))
    _egauge("degraded_groups", degraded_count(replicas, configured_mp))
