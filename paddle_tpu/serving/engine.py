"""Iteration-level continuous-batching engine (Orca-style) for the GPT
family, built on the fixed-shape / cached-executable discipline of the
eager+jit runtime.

Design
------
The engine owns a fixed batch of B decode SLOTS backed by one pooled KV
cache ``[L, B, Smax, nh, d]`` and exactly TWO steady-state executables:

* **prefill** — ONE sequence, prompt right-padded to a length bucket,
  forwarded with the slot's cache rows sliced out of the pool
  (`dynamic_slice`), KV written back via `dynamic_update_slice`, logits read
  at the true last prompt token. One executable per configured bucket; the
  bucket ladder is static so steady state never sees a new shape.
* **decode** — one token for ALL B slots at once. Every per-slot quantity
  that varies across requests — absolute position, active mask, do_sample
  mask, temperature, top_p, PRNG key — is a TRACED operand, so admission,
  eviction, slot recycling and sampling-config changes are pure data
  changes: the executable is reused, never re-traced (`top_k` stays static,
  it shapes the top_k kernel).

Requests join and leave at step boundaries (continuous batching): a finished
request's slot is recycled into a prefill for the next queued request while
the other slots' decode continues undisturbed — each slot's token stream is
bitwise identical to running that request alone through
`models.generation.generate_from_params` (greedy; tested).

The host loop fetches each step's B next-tokens (serving must stream tokens
out anyway) and keeps all scheduling state in numpy; only the KV pool stays
device-resident (donated back into the next step's executable off-CPU).
"""
from __future__ import annotations

import time
from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp

from ..flags import get_flags
from ..models.generation import (
    _cfg_key, _cfg_view, _collect_params, _forward_cached,
    _forward_decode_slots, _logical_qkv, _mask_logits,
)
from . import metrics
from .request import (
    CANCELLED, EXPIRED, FINISHED, LENGTH, QUEUED, RUNNING, STOP,
    GenerationResult, Request,
)
from .scheduler import QueueFullError, Scheduler


# Both builders are memoized on (cfg, top_k, donate): every Engine with the
# same model config shares ONE jit wrapper, so a rebuilt/second engine reuses
# the already-compiled executables instead of re-tracing (fast restart). The
# trace counters are correspondingly GLOBAL — a new engine over warm shapes
# adds zero traces.
@lru_cache(maxsize=None)
def _make_prefill(cfg, top_k, donate):
    """Build the bucketed single-sequence prefill executable. Distinct
    bucket lengths arrive as distinct ids shapes -> one trace per bucket."""
    config = _cfg_view(cfg)

    def fn(params, kc, vc, ids, plen, slot, key_data, do_sample,
           temperature, top_p):
        metrics.bump("prefill_traces")  # body runs only when traced
        kcs = jax.lax.dynamic_slice_in_dim(kc, slot, 1, axis=1)
        vcs = jax.lax.dynamic_slice_in_dim(vc, slot, 1, axis=1)
        logits, kcs, vcs = _forward_cached(params, config, ids[None],
                                           kcs, vcs, 0, last_index=plen - 1)
        kc = jax.lax.dynamic_update_slice_in_dim(kc, kcs, slot, axis=1)
        vc = jax.lax.dynamic_update_slice_in_dim(vc, vcs, slot, axis=1)
        key, sub = jax.random.split(jax.random.wrap_key_data(key_data))
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        sampled = jax.random.categorical(
            sub, _mask_logits(logits, temperature, top_k, top_p)
        ).astype(jnp.int32)
        tok = jnp.where(do_sample, sampled, greedy)[0]
        return kc, vc, tok, jax.random.key_data(key)

    return jax.jit(fn, donate_argnums=donate)


@lru_cache(maxsize=None)
def _make_decode(cfg, top_k, donate):
    """Build the one-token decode executable over all B slots."""
    config = _cfg_view(cfg)

    def fn(params, kc, vc, tok, pos, active, do_sample, temperature, top_p,
           key_data):
        metrics.bump("decode_traces")  # body runs only when traced
        logits, kc, vc = _forward_decode_slots(params, config, tok, kc, vc,
                                               pos)
        keys = jax.random.wrap_key_data(key_data)           # [B] keys
        pair = jax.vmap(jax.random.split)(keys)             # [B, 2] keys
        subs = pair[:, 1]
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        sampled = jax.vmap(jax.random.categorical)(
            subs, _mask_logits(logits, temperature, top_k, top_p)
        ).astype(jnp.int32)
        nxt = jnp.where(do_sample & active, sampled, greedy)
        return kc, vc, nxt, jax.random.key_data(pair[:, 0])

    return jax.jit(fn, donate_argnums=donate)


class Engine:
    """Continuous-batching serving engine.

    Accepts a ``GPTForCausalLM`` Layer or the functional param tree
    (``init_gpt_params`` layout, the thing ``HybridTrainStep`` trains), so
    trained params serve directly::

        eng = serving.Engine(model, num_slots=8)              # from a Layer
        eng = serving.Engine(params=step.params, config=cfg)  # from params

        eng.submit(serving.Request([1, 2, 3], max_new_tokens=32,
                                   eos_token_id=50256, on_token=stream_cb))
        results = eng.run()        # drain queue + slots

    Defaults come from FLAGS_serving_* (flags.py); kwargs override.
    """

    def __init__(self, model=None, *, params=None, config=None,
                 num_slots=None, max_seq_len=None, prefill_buckets=None,
                 max_queue=None, top_k=None):
        if model is not None:
            params = _collect_params(model)
            config = model.config
        if params is None or config is None:
            raise ValueError("Engine needs a GPTForCausalLM model, or "
                             "params= (init_gpt_params layout) + config=")
        self.config = config
        # undo head-major qkv storage (sequence-parallel HybridTrainStep)
        # once at construction — decode splits qkv logically
        params = _logical_qkv(params, config)
        self.params = jax.tree_util.tree_map(jnp.asarray, params)

        flags = get_flags()
        self.num_slots = int(num_slots or flags.get("FLAGS_serving_slots", 8))
        self.max_seq_len = int(max_seq_len or
                               flags.get("FLAGS_serving_max_seq_len", 0) or
                               config.max_seq_len)
        if self.max_seq_len > config.max_seq_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's wpe "
                f"table ({config.max_seq_len})")
        buckets = prefill_buckets or flags.get(
            "FLAGS_serving_prefill_buckets", (64, 256, 1024))
        buckets = sorted({min(int(b), self.max_seq_len) for b in buckets})
        self.scheduler = Scheduler(
            buckets,
            max_queue=int(max_queue or
                          flags.get("FLAGS_serving_max_queue", 256)))
        self.top_k = (None if top_k in (None, 0)
                      else min(int(top_k), config.vocab_size))

        cfg = _cfg_key(config)
        donate_ok = jax.default_backend() != "cpu"  # cpu: donation unimplemented
        self._prefill = _make_prefill(cfg, self.top_k,
                                      (1, 2) if donate_ok else ())
        self._decode = _make_decode(cfg, self.top_k,
                                    (1, 2) if donate_ok else ())

        B = self.num_slots
        nh = config.num_heads
        d = config.hidden_size // nh
        compute = jnp.dtype(config.compute_dtype or "float32")
        shape = (config.num_layers, B, self.max_seq_len, nh, d)
        self._kc = jnp.zeros(shape, compute)
        self._vc = jnp.zeros(shape, compute)

        # host-authoritative per-slot state (numpy; re-uploaded every step —
        # tiny arrays, and exactly why joins/evicts can never retrace)
        self._slots = [None] * B          # Request or None
        self._pos = np.zeros(B, np.int32)       # write position of next token
        self._tok = np.zeros(B, np.int32)       # last emitted token
        self._keys = np.zeros((B, 2), np.uint32)
        self._temp = np.ones(B, np.float32)
        self._top_p = np.ones(B, np.float32)
        self._do_sample = np.zeros(B, bool)
        self._results = {}                # request_id -> GenerationResult

    # -- submission ----------------------------------------------------------
    def submit(self, request):
        """Queue a request (FCFS). Raises QueueFullError past max_queue,
        ValueError for requests the pool can never hold."""
        if not isinstance(request, Request):
            request = Request(request)
        if request.state != QUEUED:
            # single-use: the max_new_tokens==0 fast path below must not
            # re-resolve (and re-ledger) an already-finished request
            raise ValueError(f"request {request.request_id} already "
                             f"{request.state}; requests are single-use")
        metrics.bump("submitted")
        plen = request.prompt_len
        if plen + request.max_new_tokens > self.max_seq_len:
            metrics.bump("rejected")
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds the KV pool's "
                f"max_seq_len ({self.max_seq_len})")
        if plen > self.scheduler.buckets[-1]:
            metrics.bump("rejected")
            raise ValueError(
                f"prompt length {plen} exceeds the largest prefill bucket "
                f"{self.scheduler.buckets[-1]}")
        if request.top_k not in (None, self.top_k):
            metrics.bump("rejected")
            raise ValueError(
                f"request top_k={request.top_k} differs from the engine's "
                f"static top_k={self.top_k}; per-value top_k would recompile "
                f"the shared executables (construct the Engine with that "
                f"top_k instead)")
        if request.do_sample and request.top_k is None \
                and self.top_k is not None:
            # greedy is top-k-invariant (argmax survives the mask), but a
            # sampled request would silently draw from top-k-truncated
            # logits, diverging from generate_from_params(top_k=None)
            metrics.bump("rejected")
            raise ValueError(
                f"sampled request with top_k=None on an engine compiled "
                f"with static top_k={self.top_k}; pass top_k={self.top_k} "
                f"to accept the engine's truncation, or serve it from an "
                f"Engine built with top_k=None")
        if request.max_new_tokens == 0:
            # parity with generate(max_new_tokens=0): prompt unchanged
            request.submit_t = time.perf_counter()
            self._resolve(request, LENGTH)
            return request
        try:
            self.scheduler.submit(request)
        except QueueFullError:
            metrics.bump("rejected")
            raise
        return request

    def cancel(self, request):
        """Abort a queued or running request; its slot (if any) is recycled
        at the next step boundary."""
        if request.state == QUEUED and self.scheduler.cancel(request):
            self._resolve(request, CANCELLED, count="cancelled")
        elif request.state == RUNNING:
            self._free_slot(request.slot)
            self._resolve(request, CANCELLED, count="cancelled")

    # -- one engine iteration ------------------------------------------------
    def step(self):
        """One scheduling boundary + one decode iteration: evict expired,
        admit (prefill) into free slots, decode one token for every active
        slot. Returns True while any work remains."""
        now = time.perf_counter()

        # 1) evict running requests whose deadline passed
        for b, req in enumerate(self._slots):
            if req is not None and req.deadline is not None \
                    and now > req.deadline:
                self._free_slot(b)
                self._resolve(req, EXPIRED, count="expired")

        # 2) reap deadline-expired queued requests (even with zero free
        #    slots — they must not count toward backpressure), then FCFS
        #    admission into free slots at the boundary
        expired = self.scheduler.expire(now)
        free = [b for b, r in enumerate(self._slots) if r is None]
        admitted, admit_expired = self.scheduler.admit(len(free), now)
        for req in expired + admit_expired:
            self._results[req.request_id] = req.result()
            metrics.bump("expired")
        for req, b in zip(admitted, free):
            self._admit(req, b)

        # 3) one decode iteration over all slots
        active = np.array([r is not None for r in self._slots])
        metrics.observe_boundary(self.scheduler.qsize(), int(active.sum()),
                                 self.num_slots)
        if active.any():
            t0 = time.perf_counter()
            self._kc, self._vc, nxt, keys = self._decode(
                self.params, self._kc, self._vc,
                jnp.asarray(self._tok), jnp.asarray(self._pos),
                jnp.asarray(active), jnp.asarray(self._do_sample),
                jnp.asarray(self._temp), jnp.asarray(self._top_p),
                jnp.asarray(self._keys))
            nxt = np.asarray(nxt)
            # copy: device_get views are read-only and _admit writes rows
            self._keys = np.array(keys)
            dt = time.perf_counter() - t0
            metrics.bump("decode_steps")
            metrics.add_time("decode_time_s", dt)
            metrics.observe_token_latency(dt, 1)
            for b, req in enumerate(self._slots):
                if req is None:
                    continue
                tok = int(nxt[b])
                req._emit(tok)
                metrics.bump("tokens_out")
                self._tok[b] = tok
                self._pos[b] += 1
                if req.stop_token_ids and tok in req.stop_token_ids:
                    self._free_slot(b)
                    self._resolve(req, STOP)
                elif len(req.tokens) >= req.max_new_tokens:
                    self._free_slot(b)
                    self._resolve(req, LENGTH)

        return self.scheduler.qsize() > 0 or \
            any(r is not None for r in self._slots)

    def _admit(self, req, b):
        """Prefill req's prompt into slot b (prompt padded to its bucket);
        the prefill emits the request's FIRST token (TTFT stops here)."""
        plen = req.prompt_len
        bucket = self.scheduler.bucket_for(plen)
        ids = np.zeros(bucket, np.int32)
        ids[:plen] = req.prompt
        key0 = jax.random.key_data(jax.random.key(req.seed))
        t0 = time.perf_counter()
        self._kc, self._vc, tok, key = self._prefill(
            self.params, self._kc, self._vc, jnp.asarray(ids),
            jnp.int32(plen), jnp.int32(b), jnp.asarray(key0),
            jnp.asarray(bool(req.do_sample)),
            jnp.float32(req.temperature),
            jnp.float32(1.0 if req.top_p is None else req.top_p))
        tok = int(np.asarray(tok))
        metrics.bump("prefill_calls")
        metrics.add_time("prefill_time_s", time.perf_counter() - t0)
        metrics.bump("admitted")

        req.state = RUNNING
        req.slot = b
        req._emit(tok)
        metrics.bump("tokens_out")
        metrics.observe_ttft(req.first_token_t - req.submit_t)
        if req.stop_token_ids and tok in req.stop_token_ids:
            self._resolve(req, STOP)
            return
        if req.max_new_tokens == 1:
            self._resolve(req, LENGTH)
            return
        self._slots[b] = req
        self._keys[b] = np.asarray(key)
        self._tok[b] = tok
        self._pos[b] = plen            # first decode writes token's KV here
        self._do_sample[b] = bool(req.do_sample)
        self._temp[b] = float(req.temperature)
        self._top_p[b] = 1.0 if req.top_p is None else float(req.top_p)

    def _free_slot(self, b):
        self._slots[b] = None
        self._pos[b] = 0
        self._tok[b] = 0

    def _resolve(self, req, reason, count="completed"):
        if req.state != FINISHED:
            req._finish(reason)
        req.slot = None
        self._results[req.request_id] = req.result()
        metrics.bump(count)
        if reason in (STOP, LENGTH):
            metrics.bump(f"finished_{reason}")

    # -- draining ------------------------------------------------------------
    def pop_results(self):
        """Drain resolved requests: returns {request_id: GenerationResult}
        for everything resolved since the last drain and forgets them.
        Call this from a ``step()`` loop — results are held until popped,
        so an undrained long-running engine grows without bound."""
        out, self._results = self._results, {}
        return out

    def run(self, requests=None):
        """Submit ``requests`` (optional) and step until queue and slots are
        empty. Returns {request_id: GenerationResult} for everything that
        resolved during this call (including earlier submissions)."""
        if requests is not None:
            for r in requests:
                self.submit(r)
        while self.step():
            pass
        return self.pop_results()

    def generate(self, prompts, **kw):
        """Batch convenience: one Request per prompt (shared kwargs),
        results returned in submission order."""
        reqs = [Request(p, **kw) for p in prompts]
        results = self.run(reqs)
        return [results[r.request_id] for r in reqs]

    # -- introspection -------------------------------------------------------
    @property
    def active_slots(self):
        return sum(r is not None for r in self._slots)

    @property
    def queue_depth(self):
        return self.scheduler.qsize()
