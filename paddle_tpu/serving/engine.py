"""Iteration-level continuous-batching engine (Orca-style) for the GPT
family, built on the fixed-shape / cached-executable discipline of the
eager+jit runtime.

Two KV layouts (``kv_layout`` / FLAGS_serving_kv_layout):

* **paged** (default) — block-paged pool ``[L, P, page_size, nh, d]``
  plus a slot->page table (vLLM-style PagedAttention): admission is
  bounded by physical PAGES, not worst-case-length slots, so effective
  batch tracks ACTUAL sequence lengths; prompts with a cached prefix map
  the same physical pages copy-on-write (serving/paged_kv.py); and long
  prompts prefill in fixed-size CHUNKS fused into the regular decode step
  (Sarathi-style), so admitting a 1024-token prompt no longer stalls all
  B decode streams for a monolithic prefill. Steady state uses a small
  static executable set — ONE fused step dispatched at its two shapes
  ([B, 1] decode over all slots, [1, chunk] prefill chunk), plus the CoW
  page copy — all trace-counter gated, and
  every per-request quantity (chunk offset, is-prefill/emit, page table,
  sampling params, PRNG keys) is a traced operand. Token streams stay
  bitwise identical to single-request ``generate_from_params`` for any
  admission order, greedy and sampled, with sharing and chunking on.
* **pooled** — the PR 5 contiguous ``[L, B, Smax, nh, d]`` layout, kept
  as the bitwise parity baseline.

Pooled design
-------------
The engine owns a fixed batch of B decode SLOTS backed by one pooled KV
cache ``[L, B, Smax, nh, d]`` and exactly TWO steady-state executables:

* **prefill** — ONE sequence, prompt right-padded to a length bucket,
  forwarded with the slot's cache rows sliced out of the pool
  (`dynamic_slice`), KV written back via `dynamic_update_slice`, logits read
  at the true last prompt token. One executable per configured bucket; the
  bucket ladder is static so steady state never sees a new shape.
* **decode** — one token for ALL B slots at once. Every per-slot quantity
  that varies across requests — absolute position, active mask, do_sample
  mask, temperature, top_p, PRNG key — is a TRACED operand, so admission,
  eviction, slot recycling and sampling-config changes are pure data
  changes: the executable is reused, never re-traced (`top_k` stays static,
  it shapes the top_k kernel).

Requests join and leave at step boundaries (continuous batching): a finished
request's slot is recycled into a prefill for the next queued request while
the other slots' decode continues undisturbed — each slot's token stream is
bitwise identical to running that request alone through
`models.generation.generate_from_params` (greedy; tested).

The host loop fetches each step's B next-tokens (serving must stream tokens
out anyway) and keeps all scheduling state in numpy; only the KV pool stays
device-resident (donated back into the next step's executable off-CPU).
"""
from __future__ import annotations

import threading
import time
from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp

from ..flags import get_flags
from ..observability import tracing as obs_tracing
from ..utils import fault_injection as _fi
from ..models.generation import (
    _cfg_key, _cfg_view, _collect_params, _forward_cached,
    _forward_decode_slots, _logical_qkv, _mask_logits, _verify_accept,
)
from . import metrics
from . import quant as _squant
from .adapters import AdapterRegistry, AdapterSpec, UnknownAdapterError
from .kv_transfer import KVTransfer, PagePayload
from .paged_attention import (
    paged_draft_forward, paged_forward, paged_kernel_supported,
    paged_kv_rewind, paged_verify_forward,
)
from .paged_kv import PagedKVPool, pages_for
from .request import (
    CANCELLED, ERROR, EXPIRED, FINISHED, LENGTH, QUEUED, RUNNING, SHED,
    STOP, GenerationResult, Request,
)
from .scheduler import QueueFullError, Scheduler, ShedError
from .slo import ShedPolicy, resolve_tenant_adapters


class EngineStoppedError(RuntimeError):
    """submit() on a drained/stopped engine. Carries the work the drain
    handed back so a router can act instead of guessing: ``queue_depth``
    (requests the drain requeued and still unclaimed) and ``requeued``
    (their request ids — resubmit them, or this new request, to a live
    replica or to an engine restored from this one's last snapshot).

    ``reforming=True`` means the stop is TEMPORARY: the replica's mp
    group is mid-reform after a chip loss/return and will come back (on
    fewer or more chips) momentarily — back off for ``retry_after``
    seconds and retry, rather than declaring the replica dead. The
    supervisor router treats reforming replicas as temporarily
    unroutable and spills elsewhere; only an all-reforming fleet
    surfaces this error to the caller, retry_after attached."""

    def __init__(self, message, queue_depth=0, requeued=(),
                 reforming=False, retry_after=None):
        super().__init__(message)
        self.queue_depth = int(queue_depth)
        self.requeued = tuple(requeued)
        self.reforming = bool(reforming)
        self.retry_after = retry_after


# Both builders are memoized on (cfg, top_k, donate): every Engine with the
# same model config shares ONE jit wrapper, so a rebuilt/second engine reuses
# the already-compiled executables instead of re-tracing (fast restart). The
# trace counters are correspondingly GLOBAL — a new engine over warm shapes
# adds zero traces.
@lru_cache(maxsize=None)
def _make_prefill(cfg, top_k, donate):
    """Build the bucketed single-sequence prefill executable. Distinct
    bucket lengths arrive as distinct ids shapes -> one trace per bucket."""
    config = _cfg_view(cfg)

    def fn(params, kc, vc, ids, plen, slot, key_data, do_sample,
           temperature, top_p):
        metrics.bump("prefill_traces")  # body runs only when traced
        kcs = jax.lax.dynamic_slice_in_dim(kc, slot, 1, axis=1)
        vcs = jax.lax.dynamic_slice_in_dim(vc, slot, 1, axis=1)
        logits, kcs, vcs = _forward_cached(params, config, ids[None],
                                           kcs, vcs, 0, last_index=plen - 1)
        kc = jax.lax.dynamic_update_slice_in_dim(kc, kcs, slot, axis=1)
        vc = jax.lax.dynamic_update_slice_in_dim(vc, vcs, slot, axis=1)
        key, sub = jax.random.split(jax.random.wrap_key_data(key_data))
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        sampled = jax.random.categorical(
            sub, _mask_logits(logits, temperature, top_k, top_p)
        ).astype(jnp.int32)
        tok = jnp.where(do_sample, sampled, greedy)[0]
        return kc, vc, tok, jax.random.key_data(key)

    return jax.jit(fn, donate_argnums=donate)


@lru_cache(maxsize=None)
def _make_decode(cfg, top_k, donate):
    """Build the one-token decode executable over all B slots."""
    config = _cfg_view(cfg)

    def fn(params, kc, vc, tok, pos, active, do_sample, temperature, top_p,
           key_data):
        metrics.bump("decode_traces")  # body runs only when traced
        logits, kc, vc = _forward_decode_slots(params, config, tok, kc, vc,
                                               pos)
        keys = jax.random.wrap_key_data(key_data)           # [B] keys
        pair = jax.vmap(jax.random.split)(keys)             # [B, 2] keys
        subs = pair[:, 1]
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        sampled = jax.vmap(jax.random.categorical)(
            subs, _mask_logits(logits, temperature, top_k, top_p)
        ).astype(jnp.int32)
        nxt = jnp.where(do_sample & active, sampled, greedy)
        return kc, vc, nxt, jax.random.key_data(pair[:, 0])

    return jax.jit(fn, donate_argnums=donate)


@lru_cache(maxsize=None)
def _make_paged_step(cfg, top_k, page_size, use_kernel, donate,
                     mp_key=None, anomaly=False, quant=None,
                     qkernel=False, adapters=None):
    """Build the FUSED chunk/decode executable over the paged pool: every
    batch row is a slot processing a T-token window (ids' second dim) at
    its own offset. The engine dispatches it at exactly two steady-state
    shapes — [B, 1] (one-token decode over all slots) and [1, chunk] (one
    prefill chunk, Sarathi-interleaved between decodes). start/valid/emit
    and the page table are traced per-slot operands, so admission, chunk
    progress, CoW remaps and sampling changes never retrace; distinct
    shapes -> exactly one trace per rung of the chunk ladder.

    A slot's PRNG key splits ONLY on steps where it emits a token
    (emit[b]), replicating generate's split-per-emitted-token stream even
    though prefill now spans several steps.

    ``mp_key`` = (mesh, ServingMPConfig) routes the forward through the
    mp-sharded schedule (serving/mp_forward.py) — same signature, same
    traced operands, bitwise-identical logits — so the host loop, trace
    gates and snapshot machinery are mp-blind.

    ``anomaly=True`` (FLAGS_serving_anomaly_policy != "off") additionally
    returns a per-slot all-finite verdict over the logits ([B] bool,
    fused into the step — no extra dispatch or host sync beyond the
    fetch the host loop already does): the serving anomaly guard. The
    healthy-path math is untouched (one extra reduction output), and
    with the flag off this builder key is byte-identical to the PR 12
    executable.

    ``quant`` = (weight_dtype, kv_dtype) (serving/quant.py) keys the
    quantized variants: quantized weights ride scale leaves inside the
    params tree (same signature), a quantized KV pool appends the
    per-page ``ksc``/``vsc`` [L, P] traced scale operands AFTER
    ``key_data`` (donate indices untouched). quant=None is byte-identical
    to the PR 13 builder.

    ``adapters`` = ``AdapterSpec.key()`` (serving/adapters.py) keys the
    per-slot LoRA-delta variants: the per-slot adapter row id [B] and the
    stacked delta slabs {target: (A, B)} arrive as traced operands AFTER
    the kv scales. The id is DATA — a mixed-adapter batch (base rows
    included) shares this one executable at its two steady-state shapes,
    and adapter load/evict/swap (content-only slab rewrites) never
    retrace. adapters=None is byte-identical to the adapter-less
    builder."""
    config = _cfg_view(cfg)
    kvq = quant is not None and quant[1] != "bf16"

    def fn(params, kc, vc, ids, start, valid, emit, table, do_sample,
           temperature, top_p, key_data, *extra):
        metrics.bump("paged_traces")  # body runs only when traced
        rest = list(extra)
        scales = None
        if kvq:
            scales = (rest[0], rest[1])
            rest = rest[2:]
        ad = (rest[0], rest[1]) if adapters is not None else None
        if mp_key is None:
            logits, kc, vc = paged_forward(params, config, ids, kc, vc,
                                           start, valid, table, page_size,
                                           use_kernel, kv_scales=scales,
                                           wq_kernel=qkernel, adapters=ad)
        else:
            from .mp_forward import mp_paged_forward
            logits, kc, vc = mp_paged_forward(params, config, ids, kc, vc,
                                              start, valid, table,
                                              page_size, use_kernel,
                                              mp_key[0], mp_key[1],
                                              kv_scales=scales,
                                              adapters=ad)
        keys = jax.random.wrap_key_data(key_data)           # [B] keys
        pair = jax.vmap(jax.random.split)(keys)             # [B, 2] keys
        subs = pair[:, 1]
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        sampled = jax.vmap(jax.random.categorical)(
            subs, _mask_logits(logits, temperature, top_k, top_p)
        ).astype(jnp.int32)
        nxt = jnp.where(do_sample & emit, sampled, greedy)
        new_keys = jnp.where(emit[:, None], jax.random.key_data(pair[:, 0]),
                             key_data)
        if anomaly:
            ok = jnp.all(jnp.isfinite(logits), axis=-1)     # [B] per-slot
            return kc, vc, nxt, new_keys, ok
        return kc, vc, nxt, new_keys

    return jax.jit(fn, donate_argnums=donate)


@lru_cache(maxsize=None)
def _make_page_copy(donate):
    """Physical page copy (the CoW split): one executable, src/dst traced
    scalars, reused for every copy-on-write divergence."""

    def fn(kc, vc, src, dst):
        metrics.bump("copy_traces")  # body runs only when traced
        kc = kc.at[:, dst].set(kc[:, src])
        vc = vc.at[:, dst].set(vc[:, src])
        return kc, vc

    return jax.jit(fn, donate_argnums=donate)


@lru_cache(maxsize=None)
def _make_page_read():
    """Read one physical page out of the pool (the prefill worker's
    transfer-out path): src is a traced scalar, one executable for every
    page hauled to the host at the pool's storage dtype."""

    def fn(kc, vc, src):
        metrics.bump("read_traces")  # body runs only when traced
        return kc[:, src], vc[:, src]

    return jax.jit(fn)


@lru_cache(maxsize=None)
def _make_page_write(donate):
    """Write one page payload into the pool (the decode worker's
    transfer-in path): dst is a traced scalar, so installing any page of
    any transfer reuses ONE executable."""

    def fn(kc, vc, kpage, vpage, dst):
        metrics.bump("write_traces")  # body runs only when traced
        kc = kc.at[:, dst].set(kpage)
        vc = vc.at[:, dst].set(vpage)
        return kc, vc

    return jax.jit(fn, donate_argnums=donate)


@lru_cache(maxsize=None)
def _make_spec_draft(cfg, page_size, k, quant=None):
    """Build the speculative DRAFT executable: greedily roll the draft
    params ``k`` tokens ahead of every slot, reading the shared paged
    pool (strictly below each slot's write position) and carrying the
    in-window KV in a [L, B, k, nh, d] sidecar — the pool is NEVER
    written, so a rejected proposal needs zero draft-side rewind.
    nprop gating is the verify pass's job (its accept scan stops at
    nprop[b]); the draft always rolls the full static k so one
    executable serves every per-slot proposal depth. Memoized per
    (config, page_size, k, quant) — both draft sources share this one
    wrapper; their distinct param TREES (int8 scale leaves vs sliced
    shallow blocks) key distinct traces under it, exactly like the
    quantized vs bf16 fused step."""
    config = _cfg_view(cfg)
    kvq = quant is not None and quant[1] != "bf16"

    def fn(draft_params, kc, vc, tok, pos, table, *kv_scales):
        metrics.bump("spec_draft_traces")  # body runs only when traced
        scales = tuple(kv_scales) if kvq else None
        return paged_draft_forward(draft_params, config, tok, kc, vc, pos,
                                   table, page_size, k, kv_scales=scales)

    # NO donation: kc/vc must survive — the verify dispatch reads them next
    return jax.jit(fn)


@lru_cache(maxsize=None)
def _make_spec_verify(cfg, top_k, page_size, donate, anomaly=False,
                      quant=None, qkernel=False):
    """Build the fused speculative VERIFY executable: score ALL slots'
    [B, k+1] windows (lane 0 = the last emitted token, lanes 1..k = the
    draft's proposals) with the SERVED weights, run the accept scan
    (per-slot nprop/emit/sampling params as traced operands — the
    chunk-ladder trick, so mixed speculative/plain/greedy/sampled
    traffic shares this one executable), then rewind every KV byte
    written past each slot's accepted length back to its pre-dispatch
    value. PRNG keys split once per EMITTED token inside the scan, so
    sampled streams replay ``generate_from_params`` exactly.

    ``anomaly=True`` mirrors the fused step's guard: a slot is flagged
    only if a NON-finite logit occurs on a lane it actually emitted
    from — rejected lanes' logits are dead values."""
    config = _cfg_view(cfg)
    kvq = quant is not None and quant[1] != "bf16"

    def fn(params, kc, vc, ids, start, valid, emit, table, nprop,
           do_sample, temperature, top_p, key_data, *kv_scales):
        metrics.bump("spec_verify_traces")  # body runs only when traced
        scales = tuple(kv_scales) if kvq else None
        logits, kc, vc, saved_k, saved_v = paged_verify_forward(
            params, config, ids, kc, vc, start, valid, table, page_size,
            False, kv_scales=scales, wq_kernel=qkernel)
        # lane i's logits score the token AFTER window position i: the
        # proposal to check against is ids[:, i+1] (last lane has none)
        ids_next = jnp.concatenate(
            [ids[:, 1:], jnp.zeros_like(ids[:, :1])], axis=1)
        toks, n_emit, new_keys = _verify_accept(
            logits, ids_next, nprop, emit, do_sample, temperature, top_p,
            key_data, top_k)
        kc, vc = paged_kv_rewind(kc, vc, saved_k, saved_v, table, start,
                                 valid, n_emit, page_size)
        if anomaly:
            T = ids.shape[1]
            lane = jnp.arange(T)[None, :]
            fin = jnp.all(jnp.isfinite(logits), axis=-1)        # [B, T]
            ok = jnp.all((lane >= n_emit[:, None]) | fin, axis=-1)
            return kc, vc, toks, n_emit, new_keys, ok
        return kc, vc, toks, n_emit, new_keys

    return jax.jit(fn, donate_argnums=donate)


class Engine:
    """Continuous-batching serving engine.

    Accepts a ``GPTForCausalLM`` Layer or the functional param tree
    (``init_gpt_params`` layout, the thing ``HybridTrainStep`` trains), so
    trained params serve directly::

        eng = serving.Engine(model, num_slots=8)              # from a Layer
        eng = serving.Engine(params=step.params, config=cfg)  # from params

        eng.submit(serving.Request([1, 2, 3], max_new_tokens=32,
                                   eos_token_id=50256, on_token=stream_cb))
        results = eng.run()        # drain queue + slots

    Defaults come from FLAGS_serving_* (flags.py); kwargs override.
    """

    def __init__(self, model=None, *, params=None, config=None,
                 num_slots=None, max_seq_len=None, prefill_buckets=None,
                 max_queue=None, top_k=None, kv_layout=None, page_size=None,
                 num_pages=None, prefill_chunk=None, prefix_cache=None,
                 tag=None, trace=None, priority=None, tenant_weights=None,
                 shed=None, params_version=0, mesh=None, mp=None,
                 comm_backend=None, anomaly=None, quant=None, role=None,
                 speculate_k=None, draft_source=None, draft_layers=None,
                 adapter_slots=None, adapter_rank=None,
                 tenant_adapters=None):
        if model is not None:
            params = _collect_params(model)
            config = model.config
        if params is None or config is None:
            raise ValueError("Engine needs a GPTForCausalLM model, or "
                             "params= (init_gpt_params layout) + config=")
        self.config = config
        flags = get_flags()

        # -- quantized serving (serving/quant.py): resolve the dtype
        # config FIRST — it decides the stored weight leaves, the KV
        # pool's storage dtype and the per-page scale tables. quant=None
        # + bf16 flags resolves to None and every quantized code path
        # below is skipped: the engine is byte-identical to the
        # unquantized one (the flags-off parity contract).
        self._quant = _squant.resolve(quant, flags)
        if self._quant is not None:
            _squant.validate(self._quant, params, config)
            # fill missing KV clip ranges by the automatic one-forward
            # calibration over the deterministic token sample — the
            # flags-only path where no PTQ artifact exists
            self._quant = _squant.ensure_kv_clips(self._quant, params,
                                                  config)

        # -- tensor-parallel serving (serving/mp_forward.py): resolve the
        # mp mesh FIRST — it decides the param layout (head-major sharded
        # vs logical replicated). mp > 1 shards the GPT weights column-
        # parallel and the paged KV pool's head axis over a 1-D 'mp' mesh;
        # the schedule is gather-only, so engine output stays BITWISE
        # identical to the single-chip engine on every collective rung.
        if mesh is None and mp is None:
            mp = int(flags.get("FLAGS_serving_mp", 0) or 0)
        if mesh is None and mp is not None and int(mp) > 1:
            from .mp_forward import replica_mesh
            mesh = replica_mesh(int(mp))
        self._mesh = None
        self._mp_cfg = None
        self._kv_sharding = None
        if mesh is not None:
            from ..distributed import tp_overlap as _tpov
            self._mp_cfg = _tpov.resolve_serving(config, mesh,
                                                 backend=comm_backend)
            if self._mp_cfg is not None:
                self._mesh = mesh
        self.mp = 1 if self._mp_cfg is None else self._mp_cfg.n
        self._mp_records = {}        # dispatch shape -> static comm record
        if self.mp > 1:
            # head-major + column-sharded placement; an already-mp-sharded
            # HybridTrainStep tree (config.qkv_head_major) is device_put
            # straight to the serving shardings — no host round trip.
            # A quant spec quantizes BEFORE placement (per-channel
            # quantization is column-independent, so the shards are
            # bitwise the single-chip engine's column slices).
            from .mp_forward import shard_serving_params
            self.params = shard_serving_params(params, config, self._mesh,
                                               self._mp_cfg,
                                               quant_spec=self._quant)
            metrics.set_mp_info(self.mp, self._mp_cfg.backend)
        else:
            # undo head-major qkv storage (sequence-parallel
            # HybridTrainStep) once at construction — single-chip decode
            # splits qkv logically
            params = _logical_qkv(params, config)
            if self._quant is not None and self._quant.quantizes_weights:
                params = _squant.quantize_params(params, config,
                                                 self._quant)
            self.params = jax.tree_util.tree_map(jnp.asarray, params)
        # per-request span tracing (observability/tracing.py): host-side
        # only — recording sites are gated on `req.trace is not None`, so
        # disabled tracing costs one attribute check and the executables /
        # trace counters are identical either way
        self.trace_enabled = (bool(flags.get("FLAGS_serving_trace", False))
                              if trace is None else bool(trace))
        # FLAGS_metrics_port: bring the Prometheus endpoint up with the
        # serving runtime (no-op at the default 0; idempotent otherwise)
        from ..observability import prometheus as _prom
        _prom.start_from_flags()
        self.kv_layout = (kv_layout or
                          flags.get("FLAGS_serving_kv_layout", "paged"))
        if self.kv_layout not in ("paged", "pooled"):
            raise ValueError(f"kv_layout must be 'paged' or 'pooled', got "
                             f"{self.kv_layout!r}")
        if self.mp > 1 and self.kv_layout != "paged":
            raise ValueError(
                "tensor-parallel serving shards the PAGED pool (the "
                "pooled layout is the single-chip parity baseline); use "
                "kv_layout='paged' with mp > 1")
        if self._quant is not None and self.kv_layout != "paged":
            raise ValueError(
                "quantized serving rides the paged layout (pages are the "
                "KV quantization block; the pooled layout is the "
                "full-precision parity baseline); use kv_layout='paged' "
                "with FLAGS_serving_weight_dtype/kv_dtype != 'bf16'")
        self.num_slots = int(num_slots or flags.get("FLAGS_serving_slots", 8))
        self.max_seq_len = int(max_seq_len or
                               flags.get("FLAGS_serving_max_seq_len", 0) or
                               config.max_seq_len)
        if self.max_seq_len > config.max_seq_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's wpe "
                f"table ({config.max_seq_len})")
        buckets = prefill_buckets or flags.get(
            "FLAGS_serving_prefill_buckets", (64, 256, 1024))
        buckets = sorted({min(int(b), self.max_seq_len) for b in buckets})
        # SLO traffic management (serving/slo.py) — ALL policy, no traced
        # operand or executable changes: with both knobs off, admission is
        # the strict FCFS the parity suites gate, byte-identical to the
        # pre-SLO engine.
        self.priority_mode = (
            bool(flags.get("FLAGS_serving_priority_classes", False))
            if priority is None else bool(priority))
        self._class_deadlines = {
            "interactive": float(
                flags.get("FLAGS_serving_class_deadline_interactive", 0.0)),
            "batch": float(
                flags.get("FLAGS_serving_class_deadline_batch", 0.0)),
            "best_effort": float(
                flags.get("FLAGS_serving_class_deadline_best_effort", 0.0)),
        }
        self._preempt_margin_s = float(
            flags.get("FLAGS_serving_preempt_margin_s", 0.0))
        # -- per-slot LoRA-class adapters (serving/adapters.py): resolve
        # the CAPACITY spec before the scheduler — WFQ lanes rotate across
        # ADAPTERS when adapters are on (the many-model fairness axis),
        # across tenants otherwise. Off (the default
        # FLAGS_serving_adapter_slots=0) resolves to None and every
        # adapter code path below is skipped: executables, dispatch
        # signatures and trace counters are byte-identical to the
        # adapter-less engine (the flags-off parity contract).
        self._adapter_spec = AdapterSpec.resolve(
            flags.get("FLAGS_serving_adapter_slots", 0)
            if adapter_slots is None else adapter_slots,
            flags.get("FLAGS_serving_adapter_rank", 8)
            if adapter_rank is None else adapter_rank)
        self.adapters = None            # AdapterRegistry once constructed
        self._tenant_adapters = {}
        if self._adapter_spec is not None and self.kv_layout != "paged":
            raise ValueError(
                "adapter serving rides the paged layout (per-slot adapter "
                "ids are traced operands of the fused paged step; the "
                "pooled layout is the parity baseline); use "
                "kv_layout='paged' with FLAGS_serving_adapter_slots > 0")
        lane_key = (None if self._adapter_spec is None
                    else (lambda r: r.adapter or 0))
        self.scheduler = Scheduler(
            buckets,
            max_queue=int(max_queue or
                          flags.get("FLAGS_serving_max_queue", 256)),
            priority=self.priority_mode, tenant_weights=tenant_weights,
            lane_key=lane_key)
        shed_on = (bool(flags.get("FLAGS_serving_shed", False))
                   if shed is None else bool(shed))
        self._shed = None
        if shed_on:
            self._shed = ShedPolicy(
                self.scheduler.max_queue,
                high=float(flags.get("FLAGS_serving_shed_high", 0.75)),
                low=float(flags.get("FLAGS_serving_shed_low", 0.5)),
                window=int(flags.get("FLAGS_serving_shed_window", 4)))
        # weight-swap audit trail: every admitted request is stamped with
        # the version its tokens are produced under
        self.params_version = int(params_version)
        self._resolved_total = 0          # feeds the shed drain-rate EWMA
        # serving anomaly guard (FLAGS_serving_anomaly_policy): "off"
        # (default — the fused step and its trajectory are byte-identical
        # to the unguarded engine) or "quarantine" (a per-slot all-finite
        # check on the logits rides the fused step; a poisoned slot is
        # resolved finish_reason="error" at the boundary — freed WITHOUT
        # publishing its prompt pages to the prefix cache — while its
        # neighbors stay bitwise-stable, so a NaN from bad weights or a
        # flaky chip never poisons the shared batch or a snapshot)
        policy = (flags.get("FLAGS_serving_anomaly_policy", "off")
                  if anomaly is None else anomaly)
        if policy not in ("off", "quarantine"):
            raise ValueError(
                f"FLAGS_serving_anomaly_policy must be 'off' or "
                f"'quarantine', got {policy!r}")
        if policy != "off" and self.kv_layout != "paged":
            raise ValueError(
                "the serving anomaly guard rides the fused paged step; "
                "use kv_layout='paged' (the pooled layout is the "
                "unguarded parity baseline)")
        self.anomaly_policy = policy
        self._anomaly = policy != "off"
        self.top_k = (None if top_k in (None, 0)
                      else min(int(top_k), config.vocab_size))

        # speculative decoding (FLAGS_serving_speculate_k): resolves to
        # None at the default 0 and every speculative code path below is
        # skipped — the engine's executables, dispatch sequence and trace
        # counters are byte-identical to the plain engine (the flags-off
        # parity contract every serving PR carries).
        self._spec = _squant.resolve_draft(speculate_k, draft_source,
                                           draft_layers, flags)
        self.speculate_k = 0 if self._spec is None else self._spec.k
        self._draft_params = None
        self._spec_draft = None
        self._spec_verify = None
        self._draft_params_version = None
        if self._spec is not None and self.kv_layout != "paged":
            raise ValueError(
                "speculative decoding rides the paged layout (the draft "
                "shares the paged pool and rejected writes rewind "
                "per-page; the pooled layout is the parity baseline); "
                "use kv_layout='paged' with FLAGS_serving_speculate_k > 0")
        if self._spec is not None and self.mp > 1:
            raise ValueError(
                "speculative decoding is single-chip for now (the draft/"
                "verify pair would double the mp collective schedule); "
                "use mp=1 with FLAGS_serving_speculate_k > 0")
        if self._adapter_spec is not None:
            if self._spec is not None:
                raise ValueError(
                    "adapter serving is mutually exclusive with "
                    "speculative decoding for now (the draft would need "
                    "its own per-slot delta routing to keep accept rates "
                    "honest); use FLAGS_serving_speculate_k=0 with "
                    "FLAGS_serving_adapter_slots > 0")
            self.adapters = AdapterRegistry(config, self._adapter_spec,
                                            mesh=self._mesh)
            self._tenant_adapters = (
                resolve_tenant_adapters(flags) if tenant_adapters is None
                else {str(k): int(v)
                      for k, v in dict(tenant_adapters).items()})
            for t, a in self._tenant_adapters.items():
                if not 0 <= int(a) <= self._adapter_spec.slots:
                    raise UnknownAdapterError(
                        a, f"tenant {t!r} maps to adapter id {a} outside "
                           f"capacity 0..{self._adapter_spec.slots}")
            metrics.set_adapter_info(self._adapter_spec.slots,
                                     self._adapter_spec.rank,
                                     self.adapters.row_bytes())
            metrics.set_adapter_residency(0, 0)

        cfg = _cfg_key(config)
        donate_ok = jax.default_backend() != "cpu"  # cpu: donation unimplemented
        B = self.num_slots
        nh = config.num_heads
        d = config.hidden_size // nh
        compute = jnp.dtype(config.compute_dtype or "float32")
        self._kv_quant = False

        if self.kv_layout == "pooled":
            self._prefill = _make_prefill(cfg, self.top_k,
                                          (1, 2) if donate_ok else ())
            self._decode = _make_decode(cfg, self.top_k,
                                        (1, 2) if donate_ok else ())
            shape = (config.num_layers, B, self.max_seq_len, nh, d)
        else:
            self.page_size = int(page_size or
                                 flags.get("FLAGS_serving_page_size", 16))
            self.prefill_chunk = int(
                prefill_chunk or flags.get("FLAGS_serving_prefill_chunk", 16))
            if self.prefill_chunk < self.page_size:
                raise ValueError(
                    f"prefill_chunk ({self.prefill_chunk}) must be >= "
                    f"page_size ({self.page_size})")
            # the chunk LADDER: power-of-two multiples of page_size up to
            # prefill_chunk. Bulk prefill rides the largest rung; the tail
            # drops down the ladder so the final chunk's padding is always
            # < page_size. One executable per rung, all trace-gated.
            self._chunk_ladder = [self.page_size]
            while self._chunk_ladder[-1] * 2 <= self.prefill_chunk:
                self._chunk_ladder.append(self._chunk_ladder[-1] * 2)
            if prefix_cache is None:
                prefix_cache = bool(
                    flags.get("FLAGS_serving_prefix_cache", True))
            kv_dtype = (self._quant.kv_dtype if self._quant is not None
                        else "bf16")
            pool_kw = {}
            if kv_dtype != "bf16":
                pool_kw = dict(kv_dtype=kv_dtype,
                               num_layers=config.num_layers,
                               k_clip=self._quant.kv_k_clip,
                               v_clip=self._quant.kv_v_clip,
                               qmax=_squant.QMAX[kv_dtype])
            self.pool = PagedKVPool(
                B, self.max_seq_len, self.page_size,
                num_pages=int(num_pages or
                              flags.get("FLAGS_serving_num_pages", 0) or 0),
                prefix_cache=prefix_cache, **pool_kw)
            self._kv_quant = kv_dtype != "bf16"
            use_kernel = bool(flags.get("FLAGS_serving_paged_kernel", True)
                              ) and paged_kernel_supported(
                                  nh // self.mp, d, self.page_size,
                                  why="serving engine")
            quant_key = None if self._quant is None else self._quant.key()
            qkernel = (self._quant is not None
                       and self._quant.quantizes_weights
                       and self.mp == 1
                       and bool(flags.get("FLAGS_serving_quant_kernel",
                                          True))
                       and jax.default_backend() == "tpu")
            adapter_key = (None if self._adapter_spec is None
                           else self._adapter_spec.key())
            if self.mp > 1:
                self._paged_step = _make_paged_step(
                    cfg, self.top_k, self.page_size, use_kernel,
                    (1, 2) if donate_ok else (),
                    mp_key=(self._mesh, self._mp_cfg),
                    anomaly=self._anomaly, quant=quant_key,
                    qkernel=qkernel, adapters=adapter_key)
            else:
                self._paged_step = _make_paged_step(
                    cfg, self.top_k, self.page_size, use_kernel,
                    (1, 2) if donate_ok else (), anomaly=self._anomaly,
                    quant=quant_key, qkernel=qkernel,
                    adapters=adapter_key)
            self._page_copy = _make_page_copy((0, 1) if donate_ok else ())
            if self._spec is not None:
                # one draft + one verify builder, memoized per config like
                # every other serving executable: a second spec engine
                # over warm shapes adds zero traces
                self._spec_verify = _make_spec_verify(
                    cfg, self.top_k, self.page_size,
                    (1, 2) if donate_ok else (), anomaly=self._anomaly,
                    quant=quant_key, qkernel=qkernel)
                self._spec_draft = _make_spec_draft(
                    cfg, self.page_size, self._spec.k, quant=quant_key)
                self._build_draft_params()
            shape = (config.num_layers, self.pool.num_pages, self.page_size,
                     nh, d)
            if self._kv_quant:
                compute = _squant.STORE_DTYPES[kv_dtype]
        self._kc = jnp.zeros(shape, compute)
        self._vc = jnp.zeros(shape, compute)
        if self._quant is not None:
            metrics.set_quant_info(
                self._quant.weight_dtype, self._quant.kv_dtype,
                scale_bytes=_squant.scale_bytes(self.params)
                + (0 if not self._kv_quant
                   else int(self.pool.k_scale.nbytes
                            + self.pool.v_scale.nbytes)),
                kv_bytes_per_token=self.kv_bytes_per_token())
        if self.mp > 1:
            # the pool's GLOBAL geometry is mp-independent (the page table
            # addresses it identically at every mp); only the HEAD axis is
            # laid out across chips — per-chip KV bytes are 1/mp
            from jax.sharding import NamedSharding
            from .mp_forward import KV_SPEC
            self._kv_sharding = NamedSharding(self._mesh, KV_SPEC)
            self._kc = jax.device_put(self._kc, self._kv_sharding)
            self._vc = jax.device_put(self._vc, self._kv_sharding)

        # host-authoritative per-slot state (numpy; re-uploaded every step —
        # tiny arrays, and exactly why joins/evicts can never retrace)
        self._slots = [None] * B          # Request or None
        self._pos = np.zeros(B, np.int32)       # write position of next token
        self._tok = np.zeros(B, np.int32)       # last emitted token
        self._keys = np.zeros((B, 2), np.uint32)
        self._temp = np.ones(B, np.float32)
        self._top_p = np.ones(B, np.float32)
        self._do_sample = np.zeros(B, bool)
        self._aid = np.zeros(B, np.int32)       # per-slot adapter row id
        # paged: next prompt index to prefill for slot b (== prompt_len once
        # prefill is done and the slot is decoding), plus the admission
        # sequence number that keeps chunked prefill FCFS across slots
        self._chunk_off = np.zeros(B, np.int32)
        self._admit_seq = np.zeros(B, np.int64)
        self._admit_count = 0
        self._results = {}                # request_id -> GenerationResult

        # disaggregated serving (serving/kv_transfer.py): role is
        # host-side SCHEDULING policy over the same executables — a
        # prefill worker never dispatches the [B,1] decode shape, a
        # decode worker seats streamed pages as if the prompt were an
        # exact prefix-cache hit — which is what keeps disaggregated
        # output bitwise identical to a single-engine run.
        self.role = "both"
        self._outbound = {}            # rid -> KVTransfer (prefill side)
        self._fresh_outbound = []      # transfers not yet taken by the sup
        self._transfers_in = []        # KVTransfers offered to this decoder
        self._install_progress = {}    # rid -> pages installed so far
        self._transfer_budget = int(
            flags.get("FLAGS_serving_transfer_pages_per_boundary", 4))
        # end-to-end KV wire integrity: stamp outbound page payloads with
        # CRC32 at creation, re-verify at install (kv_transfer.py)
        self._kv_crc = bool(flags.get("FLAGS_kv_transfer_crc", False))
        self._page_read = None
        self._page_write = None
        # per-role trace gates (host counters beside the global
        # paged_traces gate): decode dispatches and chunk rungs actually
        # used BY THIS ENGINE — the per-role acceptance criteria
        self._decode_dispatches = 0
        self._chunk_rungs = set()
        self.set_role(role if role is not None
                      else flags.get("FLAGS_serving_role", "both"))

        # self-healing state: step counter (snapshot cadence + chaos
        # hooks), attached snapshot manager, drain/stop latch
        self.tag = "engine" if tag is None else str(tag)
        self._step_count = 0
        self._stopped = False
        self._reforming = False           # stop_for_reform: temporary stop
        self._reform_retry_after = None
        self._ckpt = None
        self._snapshot_every = 0
        self._drained = []                # requests the last drain() handed back

    # -- submission ----------------------------------------------------------
    def _check_stopped(self):
        if self._stopped:
            pending = [r for r in self._drained
                       if r.state not in (FINISHED,)]
            if self._reforming:
                hint = self._reform_retry_after
                raise EngineStoppedError(
                    f"engine {self.tag!r} is mid-reform (its mp group is "
                    f"being re-formed after a chip loss/return); the "
                    f"replica comes back momentarily — retry"
                    f"{f' in ~{hint:.2f}s' if hint is not None else ''}",
                    queue_depth=len(pending),
                    requeued=[r.request_id for r in pending],
                    reforming=True, retry_after=hint)
            raise EngineStoppedError(
                f"engine {self.tag!r} is stopped (drained"
                f"{' after preemption' if self._ckpt is not None and self._ckpt.preempted else ''}); "
                f"resubmit to a live replica or to an engine restored from "
                f"its last snapshot ({len(pending)} drained requests are "
                f"waiting to be requeued)",
                queue_depth=len(pending),
                requeued=[r.request_id for r in pending])

    def stop_for_reform(self, retry_after=None):
        """Mark this engine TEMPORARILY stopped for a group reform: the
        supervisor is rebuilding the replica on a different chip set and
        every piece of state moves with it (live snapshot or disk
        restore), so — unlike ``drain()`` — nothing is requeued or
        mutated here. ``submit()`` raises ``EngineStoppedError`` with
        ``reforming=True`` and the ``retry_after`` hint; the router
        treats the replica as temporarily unroutable, not dead."""
        # publish the reform markers BEFORE the stop (same ordering
        # discipline as rep.state vs rep.engine in the supervisor): a
        # concurrent submit that sees stopped must never read a
        # not-yet-reforming engine and write the replica off as dead
        self._reform_retry_after = (None if retry_after is None
                                    else float(retry_after))
        self._reforming = True
        self._stopped = True

    # -- disaggregated roles -------------------------------------------------
    def set_role(self, role):
        """Assign this engine's serving role ("both" | "prefill" |
        "decode") — host-side policy only, settable while the engine is
        IDLE (no slots, no queue, no in-flight transfers): a mid-stream
        flip would strand half-prefilled slots with no decoder. The
        supervisor flips roles only through a drain (``_set_replica_role``).
        Non-"both" roles require the paged layout (the handoff is a page
        copy + a table splice)."""
        role = str(role)
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', got {role!r}")
        if role != "both" and self.kv_layout != "paged":
            raise ValueError(
                "disaggregated roles ride the paged layout (KV pages are "
                "the transfer unit); use kv_layout='paged'")
        if role != "both" and getattr(self, "adapters", None) is not None:
            raise ValueError(
                "adapter serving is single-role for now (a prefill/decode "
                "handoff would have to carry the adapter-residency "
                "contract across workers); use role='both' with "
                "FLAGS_serving_adapter_slots > 0")
        if (any(r is not None for r in self._slots)
                or self.scheduler.qsize() > 0
                or self._outbound or self._transfers_in):
            raise RuntimeError(
                "set_role on a non-idle engine: drain() first")
        self.role = role
        if role != "both":
            donate_ok = jax.default_backend() != "cpu"
            self._page_read = _make_page_read()
            self._page_write = _make_page_write((0, 1) if donate_ok else ())
        return self

    def take_outbound(self):
        """Pop the transfers opened since the last call (the supervisor
        polls this on a prefill worker every boundary and routes them)."""
        out, self._fresh_outbound = self._fresh_outbound, []
        return out

    def prefill_backlog(self):
        """Prompt tokens this engine still has to prefill: the remaining
        chunk tokens of every mid-prefill slot plus every queued prompt.
        The supervisor folds this into its load probe — queue depth alone
        makes a replica mid-giant-prefill look idle."""
        if self.kv_layout != "paged":
            return sum(r.prompt_len for r in self.scheduler._q
                       if r.state != FINISHED)
        backlog = 0
        for b, req in enumerate(self._slots):
            if req is not None:
                backlog += max(0, req.prompt_len - int(self._chunk_off[b]))
        backlog += sum(r.prompt_len for r in self.scheduler._q
                       if r.state != FINISHED)
        return backlog

    def prefix_page_hashes(self, prompt):
        """Stable routing key for prefix-affinity: ``(page_hashes,
        exact_key)`` where ``page_hashes[j]`` digests the cumulative
        full-page prefix ``prompt[:(j+1)*page_size]`` and ``exact_key``
        digests the whole prompt — the same keys (hashed) the prefix
        cache indexes by, so the router and tests never reach into cache
        internals. Paged layout only."""
        import hashlib
        if self.kv_layout != "paged":
            raise ValueError("prefix_page_hashes needs the paged layout")
        prompt = np.ascontiguousarray(np.asarray(prompt, np.int32))
        ps = self.page_size
        hashes = tuple(
            hashlib.blake2b(prompt[:j * ps].tobytes(),
                            digest_size=16).hexdigest()
            for j in range(1, len(prompt) // ps + 1))
        exact = hashlib.blake2b(prompt.tobytes(),
                                digest_size=16).hexdigest()
        return hashes, exact

    def prefix_coverage(self, prompt):
        """Tokens of ``prompt`` this engine's prefix cache already holds
        (longest cached prefix, LRU-neutral probe). 0 for pooled engines."""
        if self.kv_layout != "paged":
            return 0
        prompt = np.ascontiguousarray(np.asarray(prompt, np.int32))
        return self.pool.peek_coverage(prompt)

    def submit(self, request):
        """Queue a request (FCFS). Raises QueueFullError past max_queue,
        EngineStoppedError after drain()/preemption, ValueError for
        requests the pool can never hold."""
        if not isinstance(request, Request):
            request = Request(request)
        self._check_stopped()
        if request.state != QUEUED:
            # single-use: the max_new_tokens==0 fast path below must not
            # re-resolve (and re-ledger) an already-finished request
            raise ValueError(f"request {request.request_id} already "
                             f"{request.state}; requests are single-use")
        if self.trace_enabled and request.trace is None:
            request.trace = obs_tracing.RequestTrace(request.request_id)
        metrics.bump("submitted")
        plen = request.prompt_len
        if plen + request.max_new_tokens > self.max_seq_len:
            metrics.bump("rejected")
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds the KV "
                f"{'table capacity' if self.kv_layout == 'paged' else 'pool'}"
                f" max_seq_len ({self.max_seq_len})")
        if self.kv_layout == "pooled":
            # the pooled layout additionally caps prompts at the largest
            # prefill bucket; paged prompts prefill in chunks of any count
            if plen > self.scheduler.buckets[-1]:
                metrics.bump("rejected")
                raise ValueError(
                    f"prompt length {plen} exceeds the largest prefill "
                    f"bucket {self.scheduler.buckets[-1]}")
        else:
            # worst-case demand is exactly the lifetime page count: a CoW
            # spare is reserved only when >= 1 page is prefix-shared, and
            # every shared page reduces the fresh-page need by one. A
            # request that can NEVER fit must fail fast instead of
            # deadlocking the FCFS queue head.
            worst = pages_for(plen + request.max_new_tokens, self.page_size)
            if worst > self.pool.num_pages - 1:
                metrics.bump("rejected")
                raise ValueError(
                    f"request needs up to {worst} KV pages but the pool "
                    f"only has {self.pool.num_pages - 1}")
        if request.top_k not in (None, self.top_k):
            metrics.bump("rejected")
            raise ValueError(
                f"request top_k={request.top_k} differs from the engine's "
                f"static top_k={self.top_k}; per-value top_k would recompile "
                f"the shared executables (construct the Engine with that "
                f"top_k instead)")
        if request.do_sample and request.top_k is None \
                and self.top_k is not None:
            # greedy is top-k-invariant (argmax survives the mask), but a
            # sampled request would silently draw from top-k-truncated
            # logits, diverging from generate_from_params(top_k=None)
            metrics.bump("rejected")
            raise ValueError(
                f"sampled request with top_k=None on an engine compiled "
                f"with static top_k={self.top_k}; pass top_k={self.top_k} "
                f"to accept the engine's truncation, or serve it from an "
                f"Engine built with top_k=None")
        if request.adapter is None:
            # tenant default mapping (FLAGS_serving_tenant_adapters):
            # unmapped tenants serve the base model
            request.adapter = int(
                self._tenant_adapters.get(request.tenant, 0))
        if request.adapter != 0:
            # typed refusal UP FRONT for ids the engine can never serve
            # (disabled adapters / outside capacity). A merely
            # NON-RESIDENT id is NOT an error: the request queues and
            # admission blocks until load_adapter makes it resident.
            if self.adapters is None:
                metrics.bump("rejected")
                raise UnknownAdapterError(
                    request.adapter,
                    f"request names adapter {request.adapter} but this "
                    f"engine serves no adapters "
                    f"(FLAGS_serving_adapter_slots=0)")
            try:
                self.adapters._check_id(request.adapter)
            except UnknownAdapterError:
                metrics.bump("rejected")
                raise
        if request.max_new_tokens == 0:
            # parity with generate(max_new_tokens=0): prompt unchanged
            request.submit_t = time.perf_counter()
            self._resolve(request, LENGTH)
            return request
        if self.priority_mode and request.deadline_s is None:
            # per-class default deadline (0 = none): the SLO contract a
            # class carries when the caller didn't set one explicitly
            dflt = self._class_deadlines.get(request.priority, 0.0)
            if dflt > 0:
                request.deadline_s = dflt
        if self._shed is not None and self._shed.shedding \
                and request.class_rank >= 2:
            # sustained overload, shedding latched: refuse new best-effort
            # work UP FRONT with the drain-rate hint instead of queueing it
            # only to shed it a boundary later
            qsize = self.scheduler.qsize()
            hint = self._shed.retry_after(qsize)
            metrics.bump("shed")
            raise ShedError(
                f"shedding {request.priority} traffic under sustained "
                f"overload ({qsize} waiting); retry in ~{hint:.2f}s",
                qsize=qsize, max_queue=self.scheduler.max_queue,
                retry_after=hint)
        try:
            self.scheduler.submit(request)
        except QueueFullError:
            metrics.bump("rejected")
            raise
        return request

    def requeue(self, request):
        """Re-admit a drained/preempted request (the replay path): unlike
        ``submit`` it bypasses the ``max_queue`` bound (the request was
        already accepted once — dropping it now would break the zero-drop
        drain guarantee), inserts at the request's ORIGINAL arrival
        position (global FCFS survives a drain) and keeps its original
        ``submit_t``/deadline. Returns True unless the request was
        cancelled while in flight between drain and requeue.

        (Not counted in the ``requeued`` ledger — that counter means
        "in-flight requests reset to queue state by a drain", bumped
        exactly once in ``drain()``; cross-replica re-insertion is the
        supervisor's ``replayed``.)"""
        self._check_stopped()
        return self.scheduler.requeue(request)

    def cancel(self, request, *, count="cancelled"):
        """Abort a queued or running request; its slot (if any) is recycled
        at the next step boundary. Race-safe against a concurrent drain: a
        request cancelled while it sits BETWEEN drain() and a requeue (in
        neither the wait queue nor a slot) resolves as cancelled here, and
        ``Scheduler.requeue``/``admit`` skip already-resolved requests.

        ``count=None`` skips the ledger bump — for internal hygiene
        cancels (a supervisor pruning a stale snapshot's duplicates) that
        are not user cancellations and must not skew the SLO counters."""
        if request.state == QUEUED:
            in_queue = self.scheduler.cancel(request)
            if in_queue or request in self._drained:
                self._resolve(request, CANCELLED, count=count)
        elif request.state == RUNNING:
            b = request.slot
            if b is not None and 0 <= b < self.num_slots \
                    and self._slots[b] is request:
                self._free_slot(b)
                self._resolve(request, CANCELLED, count=count)
            elif request.request_id in self._install_progress:
                # cancelled MID-TRANSFER on the decode side (RUNNING, no
                # slot anywhere): abort the stream, return staged pages
                rid = request.request_id
                for tr in self._transfers_in:
                    if tr.request_id == rid:
                        tr.aborted = True
                self.pool.release_staged(rid)
                self._install_progress.pop(rid, None)
                self._transfers_in = [t for t in self._transfers_in
                                      if t.request_id != rid]
                self._resolve(request, CANCELLED, count=count)
            # else: a RUNNING handle this engine does not host (e.g. a
            # stale snapshot copy whose live twin moved to another
            # replica) — freeing request.slot here would evict whatever
            # unrelated request occupies that slot. Not ours: no-op.

    # -- one engine iteration ------------------------------------------------
    def step(self):
        """One scheduling boundary + one decode iteration: evict expired,
        admit (prefill) into free slots, decode one token for every active
        slot. Returns True while any work remains."""
        if self._stopped:
            return False
        # chaos hook: simulated ABRUPT engine death (no flush) — recovery
        # must come from the last periodic snapshot or request replay
        _fi.maybe_kill_serving(self.tag, self._step_count)
        # chaos hook: FINITE silent corruption of the live KV pool — the
        # all-finite anomaly guard cannot see it; only the shadow audit can
        if _fi._plan is not None and _fi._plan.kv_bitflip_at:
            self._maybe_kv_bitflip()
        now = time.perf_counter()

        # 1) evict running requests whose deadline passed (same boundary
        #    predicate — Request.expired — as every queue-expiry site)
        for b, req in enumerate(self._slots):
            if req is not None and req.expired(now):
                self._free_slot(b)
                self._resolve(req, EXPIRED, count="expired")

        # 2) reap deadline-expired queued requests (even with zero free
        #    slots — they must not count toward backpressure); their queue
        #    wait goes to the ledger so refused traffic stays visible
        expired = self.scheduler.expire(now)

        # 2b) graceful load shedding: after `window` consecutive over-high
        #     boundaries, shed lowest-class queued work down to the low
        #     watermark with a retry-after hint from the live drain rate
        if self._shed is not None:
            qsize = self.scheduler.qsize()
            target = self._shed.observe(qsize, self._resolved_total, now)
            if target is not None:
                hint = self._shed.retry_after(qsize)
                for req in self.scheduler.shed(target):
                    req.retry_after = hint
                    metrics.observe_queue_wait(
                        now - req.submit_t if req.submit_t else 0.0, "shed")
                    self._resolve(req, SHED, count="shed")

        # 2c) preemptive admission (priority mode): when an interactive
        #     request would miss its deadline waiting for capacity, evict
        #     the youngest lowest-class running slot — requeued through
        #     the PR 7 drain machinery (ORIGINAL submit_t/deadline kept,
        #     replay bitwise), so preemption costs the victim latency,
        #     never correctness
        if self.priority_mode:
            self._preempt_for_deadline(now)

        # 2d) inbound KV transfers (disaggregated serving): install up to
        #     the per-boundary page budget and seat fully-landed requests
        #     BEFORE admission, so a handed-off request (older by FCFS —
        #     it was admitted on the prefill worker already) takes a free
        #     slot ahead of fresh queue arrivals
        if self.kv_layout == "paged" and self._transfers_in:
            self._pump_transfers(now)

        #    then admission into free slots at the boundary, FCFS or
        #    class-aware WFQ (page-aware for the paged layout: a candidate
        #    is admitted when PAGES suffice for its whole lifetime, not
        #    when a whole-Smax slot does)
        free = [b for b, r in enumerate(self._slots) if r is None]
        fits = self._try_reserve if self.kv_layout == "paged" else None
        admitted, admit_expired = self.scheduler.admit(len(free), now,
                                                       fits=fits)
        for req in expired + admit_expired:
            # already _finish(EXPIRED)ed by the scheduler; _resolve stores
            # the result, bumps the ledger and closes the trace
            metrics.observe_queue_wait(
                now - req.submit_t if req.submit_t else 0.0, "expired")
            self._resolve(req, EXPIRED, count="expired")
        for req, b in zip(admitted, free):
            self._admit(req, b)

        # 3) one iteration over all slots
        active = np.array([r is not None for r in self._slots])
        metrics.observe_boundary(self.scheduler.qsize(), int(active.sum()),
                                 self.num_slots)
        if self.kv_layout == "paged":
            metrics.observe_pages(self.pool.pages_in_use,
                                  self.pool.num_pages - 1)
            if active.any():
                self._iterate_paged()
        elif active.any():
            self._iterate_pooled(active)

        self._step_count += 1
        if self._ckpt is not None and self._snapshot_every > 0 \
                and self._step_count % self._snapshot_every == 0 \
                and any(r is not None for r in self._slots):
            self.save_snapshot()

        return self.scheduler.qsize() > 0 or \
            any(r is not None for r in self._slots) or \
            bool(self._transfers_in) or bool(self._outbound)

    def _iterate_pooled(self, active):
        """One pooled-layout decode iteration: one token for every active
        slot through the [L, B, Smax, nh, d] cache."""
        t0 = time.perf_counter()
        self._kc, self._vc, nxt, keys = self._decode(
            self.params, self._kc, self._vc,
            jnp.asarray(self._tok), jnp.asarray(self._pos),
            jnp.asarray(active), jnp.asarray(self._do_sample),
            jnp.asarray(self._temp), jnp.asarray(self._top_p),
            jnp.asarray(self._keys))
        nxt = np.asarray(nxt)
        # copy: device_get views are read-only and _admit writes rows
        self._keys = np.array(keys)
        t1 = time.perf_counter()
        dt = t1 - t0
        metrics.bump("decode_steps")
        metrics.add_time("decode_time_s", dt)
        metrics.observe_token_latency(dt, 1)
        for b, req in enumerate(self._slots):
            if req is None:
                continue
            if req.trace is not None:
                req.trace.span("decode_step", t0, t1, pos=int(self._pos[b]))
            tok = int(nxt[b])
            req._emit(tok)
            metrics.bump("tokens_out")
            self._tok[b] = tok
            self._pos[b] += 1
            if req.stop_token_ids and tok in req.stop_token_ids:
                self._free_slot(b)
                self._resolve(req, STOP)
            elif len(req.tokens) >= req.max_new_tokens:
                self._free_slot(b)
                self._resolve(req, LENGTH)

    def _record_mp_comm(self, B, T, t0, t1, reqs=()):
        """mp-rung observability per fused-step dispatch: the STATIC
        collective schedule of this dispatch shape is recorded into the
        training-shared ``profiler.mp_comm_counters()`` ledger (PR 3
        plumbing) and the serving ledger (wire bytes / collectives /
        fused dispatches), and every traced request on board gets a
        per-boundary ``mp_comm`` span carrying wire bytes + backend
        label (PR 9 tracing). Zero-cost at mp == 1."""
        if self.mp <= 1:
            return
        from ..distributed import tp_overlap as _tpov
        rec = self._mp_records.get((B, T))
        if rec is None:
            rec = _tpov.serving_step_record(self.config, self._mp_cfg, B, T)
            self._mp_records[(B, T)] = rec
        _tpov.record_step(rec)
        wire = rec.rs_bytes + rec.ag_bytes
        metrics.bump("mp_steps")
        metrics.bump("mp_collectives", rec.collectives)
        metrics.bump("mp_wire_bytes", wire)
        metrics.bump("mp_fused_dispatches", rec.fused_dispatches)
        for req in reqs:
            if req is not None and req.trace is not None:
                req.trace.span("mp_comm", t0, t1, bytes=wire,
                               backend=self._mp_cfg.backend, mp=self.mp)

    def _kv_scale_args(self):
        """Per-page dequant scale operands of a quantized pool: host-
        authoritative like the page table, uploaded with every dispatch
        ([L, P] fp32 — tiny). Empty for a full-precision pool, so the
        unquantized dispatch signature is untouched."""
        if not self._kv_quant:
            return ()
        return (jnp.asarray(self.pool.k_scale),
                jnp.asarray(self.pool.v_scale))

    def _adapter_args(self, sl=None):
        """Traced adapter operands of the fused step (AFTER the kv
        scales): the per-slot adapter row ids (host-authoritative,
        re-uploaded every dispatch exactly like the page table) and the
        stacked delta slabs (device-resident; re-placed only by
        load/evict/swap). Empty when adapters are off, so the
        adapter-less dispatch signature is untouched. ``sl`` slices the
        id row for the [1, chunk] prefill dispatch."""
        if self.adapters is None:
            return ()
        aid = self._aid if sl is None else self._aid[sl]
        return (jnp.asarray(aid), self.adapters.device_slabs())

    def _cow(self, b, start, end):
        """Copy-on-write guard: a slot may only WRITE pages it exclusively
        owns — split any shared page in [start, end) to a fresh physical
        page before the dispatch that writes the range."""
        copied = 0
        for src, dst in self.pool.make_writable(b, start, end):
            self._kc, self._vc = self._page_copy(
                self._kc, self._vc, jnp.int32(src), jnp.int32(dst))
            metrics.bump("cow_copies")
            copied += 1
        if copied:
            req = self._slots[b]
            if req is not None and req.trace is not None:
                req.trace.instant("cow_copy", pages=copied)

    def _iterate_paged(self):
        """One paged iteration (Sarathi-style interleave): the FCFS-oldest
        slot still consuming its prompt advances by ONE prefill chunk
        ([1, chunk] dispatch of the fused step), and every decode-ready
        slot emits one token ([B, 1] dispatch of the SAME fused step).
        Decode streams therefore advance at every boundary — a 1024-token
        admission costs each inter-token gap one chunk, never a monolithic
        prefill — and decode slots never pay for the chunk window. The two
        dispatch shapes ARE the steady-state executable set (the chunk
        ladder), trace-counter gated."""
        B = self.num_slots
        t_boundary = time.perf_counter()    # chunks + CoW + decode: the
        prefilling = sorted(                # whole inter-token gap
            (b for b in range(B) if self._slots[b] is not None
             and self._chunk_off[b] < self._slots[b].prompt_len),
            key=lambda x: self._admit_seq[x])
        n_dec = sum(1 for b in range(B) if self._slots[b] is not None
                    and self._chunk_off[b] >= self._slots[b].prompt_len)

        if prefilling:
            # prefill budget scales with IDLE decode capacity (Sarathi's
            # principle): while the batch ramps up, several prompts chunk
            # per boundary; once half the slots decode, only one chunk
            # rides along, so the inter-token gap stays one-chunk-bounded.
            # A dedicated PREFILL worker has no decode streams to protect:
            # every prefilling slot advances each boundary.
            budget = (len(prefilling) if self.role == "prefill"
                      else max(1, B // 2 - n_dec))
            for b in prefilling[:budget]:
                self._prefill_chunk(b)

        decoding = [b for b in range(B) if self._slots[b] is not None
                    and self._chunk_off[b] >= self._slots[b].prompt_len]
        if not decoding:
            return
        if self._spec is not None:
            self._iterate_spec(decoding, t_boundary)
            return
        # mid-prefill slots ride along inert: valid=0 routes their writes
        # to the trash page, emit=False parks their PRNG keys
        valid = np.zeros(B, np.int32)
        emit = np.zeros(B, bool)
        valid[decoding] = 1
        emit[decoding] = True
        for b in decoding:
            self._cow(b, int(self._pos[b]), int(self._pos[b]) + 1)
        t0 = time.perf_counter()
        self._decode_dispatches += 1     # per-role gate: prefill workers
        out = self._paged_step(          # must never reach this dispatch
            self.params, self._kc, self._vc,
            jnp.asarray(self._tok[:, None]), jnp.asarray(self._pos),
            jnp.asarray(valid), jnp.asarray(emit),
            jnp.asarray(self.pool.table), jnp.asarray(self._do_sample),
            jnp.asarray(self._temp), jnp.asarray(self._top_p),
            jnp.asarray(self._keys), *self._kv_scale_args(),
            *self._adapter_args())
        if self._anomaly:
            self._kc, self._vc, nxt, keys, ok = out
            ok = np.asarray(ok)
        else:
            self._kc, self._vc, nxt, keys = out
            ok = None
        nxt = np.asarray(nxt)
        self._keys = np.array(keys)
        now = time.perf_counter()
        self._record_mp_comm(B, 1, t0, now,
                             [self._slots[b] for b in decoding])
        metrics.bump("paged_steps")
        metrics.add_time("decode_time_s", now - t0)
        # the latency a decode stream OBSERVES spans the whole boundary —
        # interleaved prefill chunks and CoW copies included — which is
        # exactly the gap chunked prefill is supposed to bound
        metrics.observe_token_latency(now - t_boundary, 1)
        for b in decoding:
            req = self._slots[b]
            if ok is not None and not ok[b]:
                self._quarantine(req, b)
                continue
            if req.trace is not None:
                # the span covers the whole boundary (chunks + CoW + the
                # fused dispatch): that IS this stream's inter-token gap
                req.trace.span("decode_step", t_boundary, now,
                               pos=int(self._pos[b]))
            self._pos[b] += 1
            self._emit_token(req, b, int(nxt[b]), first=False)

    def _build_draft_params(self):
        """(Re)derive the draft params from the SERVED weights — at
        construction and after every ``swap_params`` — so the draft always
        proposes against the live version (``_draft_params_version``, the
        snapshot's audit stamp, records which). Source "quant": the PR 14
        int8 self-draft — on an engine already serving quantized weights
        the served tree IS the draft (degenerate self-draft, 100% greedy
        agreement); on a bf16 engine the served tree is quantized fresh.
        Source "shallow": the first ``draft_layers`` transformer blocks
        of the served tree (embeddings/LN/head shared, zero copies)."""
        if self._spec.source == "quant":
            if self._quant is not None and self._quant.quantizes_weights:
                self._draft_params = self.params
            else:
                self._draft_params = _squant.quantize_params(
                    self.params, self.config,
                    _squant.QuantSpec(weight_dtype="int8"))
        else:
            self._draft_params = _squant.shallow_draft_params(
                self.params,
                self._spec.num_layers(self.config.num_layers))
        self._draft_params_version = self.params_version

    def _iterate_spec(self, decoding, t_boundary):
        """Speculative decode boundary (FLAGS_serving_speculate_k > 0):
        the draft rolls every decode-ready slot up to k tokens ahead of
        its last emitted token (sidecar KV — the shared pool is never
        written), then ONE fused verify dispatch scores all slots at
        [B, k+1] under the SERVED weights, accepts per slot, and rewinds
        every KV byte written past an accepted length. Per-slot
        nprop/emit/sampling params are traced operands — the chunk-ladder
        trick — so mixed speculative/plain/greedy/sampled traffic shares
        this one executable: a slot with nprop=0 (``speculate="off"``, or
        one token remaining) IS plain decode inside the same dispatch,
        and a spec engine never dispatches the [B, 1] plain-decode shape.
        Emitted token streams are bitwise the plain engine's (greedy) and
        replay ``generate_from_params`` exactly (sampled): the verify key
        splits once per EMITTED token only."""
        B = self.num_slots
        k = self._spec.k
        nprop = np.zeros(B, np.int32)
        valid = np.zeros(B, np.int32)
        emit = np.zeros(B, bool)
        for b in decoding:
            req = self._slots[b]
            remaining = req.max_new_tokens - len(req.tokens)
            if req.speculate != "off":
                # the window's last lane must stay a real (non-proposed)
                # emission so LENGTH fires exactly at max_new_tokens
                nprop[b] = min(k, max(0, remaining - 1))
            valid[b] = nprop[b] + 1
            emit[b] = True
        ids = np.zeros((B, k + 1), np.int32)
        ids[:, 0] = self._tok                 # lane 0: last emitted token
        t0 = time.perf_counter()
        if int(nprop.max()) > 0:
            props = self._spec_draft(
                self._draft_params, self._kc, self._vc,
                jnp.asarray(self._tok), jnp.asarray(self._pos),
                jnp.asarray(self.pool.table), *self._kv_scale_args())
            ids[:, 1:] = np.asarray(props)
            metrics.bump("draft_dispatches")
        for b in decoding:
            self._cow(b, int(self._pos[b]),
                      int(self._pos[b]) + int(valid[b]))
        self._decode_dispatches += 1     # per-role gate: prefill workers
        out = self._spec_verify(         # must never reach this dispatch
            self.params, self._kc, self._vc, jnp.asarray(ids),
            jnp.asarray(self._pos), jnp.asarray(valid), jnp.asarray(emit),
            jnp.asarray(self.pool.table), jnp.asarray(nprop),
            jnp.asarray(self._do_sample), jnp.asarray(self._temp),
            jnp.asarray(self._top_p), jnp.asarray(self._keys),
            *self._kv_scale_args())
        if self._anomaly:
            self._kc, self._vc, toks, n_emit, keys, ok = out
            ok = np.asarray(ok)
        else:
            self._kc, self._vc, toks, n_emit, keys = out
            ok = None
        toks = np.asarray(toks)
        n_emit = np.asarray(n_emit)
        self._keys = np.array(keys)
        now = time.perf_counter()
        metrics.bump("paged_steps")
        metrics.bump("verify_dispatches")
        metrics.add_time("decode_time_s", now - t0)
        total_emitted = 0
        for b in decoding:
            req = self._slots[b]
            if ok is not None and not ok[b]:
                self._quarantine(req, b)
                continue
            # a stop token cuts the window mid-run: the tail of the
            # accepted run is dropped (freed pages only), so the emission
            # count is known BEFORE emitting — which is what lets the
            # span land before the final token's emission delivers the
            # request and archives its trace
            n = int(n_emit[b])
            stops = req.stop_token_ids or ()
            plan = next((j + 1 for j in range(n)
                         if int(toks[b, j]) in stops), n)
            accepted = max(0, plan - 1)      # lane 0 is never speculative
            metrics.bump("spec_proposed", int(nprop[b]))
            metrics.bump("spec_accepted", accepted)
            metrics.bump("spec_tokens_out", plan)
            if req.trace is not None:
                # reconciles with the emitted-token ledger: sum(emitted)
                # over a request's speculate spans == len(result.tokens)-1
                # (the first token comes from the prefill chunk)
                req.trace.span("speculate", t_boundary, now,
                               proposed=int(nprop[b]), accepted=accepted,
                               emitted=plan)
            for j in range(plan):
                if self._slots[b] is not req:
                    break                    # safety net; plan already
                self._pos[b] += 1            # accounts for the stop cut
                self._emit_token(req, b, int(toks[b, j]), first=False)
            total_emitted += plan
        # the whole boundary gap bought total_emitted tokens — the
        # speculative payoff the latency histogram should see
        metrics.observe_token_latency(now - t_boundary,
                                      max(1, total_emitted))

    def _prefill_chunk(self, b):
        """Advance slot b's prefill by one chunk ([1, rung] dispatch of
        the fused step); the final chunk emits the request's first token."""
        req = self._slots[b]
        plen = req.prompt_len
        off = int(self._chunk_off[b])
        remaining = plen - off
        # largest ladder rung <= the page-rounded remainder: bulk prefill
        # uses the big rung, the tail steps down so the final chunk's
        # padding stays < page_size
        target = min(-(-remaining // self.page_size) * self.page_size,
                     self._chunk_ladder[-1])
        C = max(c for c in self._chunk_ladder if c <= target)
        v = min(C, remaining)
        last = off + v >= plen                # final chunk emits token #1
        # a PREFILL worker never emits: its final chunk dispatches with
        # emit=False, so the slot's PRNG key PARKS exactly as it does for
        # every non-final chunk — the decode worker re-derives the stream
        # from the request seed and makes the FIRST split itself, which is
        # what keeps the handoff bitwise-identical to a single engine
        emit = last and self.role != "prefill"
        self._chunk_rungs.add(C)              # per-role rung gate
        ids = np.zeros((1, C), np.int32)
        ids[0, :v] = req.prompt[off:off + v]
        self._cow(b, off, off + v)
        t0 = time.perf_counter()
        out = self._paged_step(
            self.params, self._kc, self._vc, jnp.asarray(ids),
            jnp.asarray([off], np.int32), jnp.asarray([v], np.int32),
            jnp.asarray([emit]), jnp.asarray(self.pool.table[b:b + 1]),
            jnp.asarray(self._do_sample[b:b + 1]),
            jnp.asarray(self._temp[b:b + 1]),
            jnp.asarray(self._top_p[b:b + 1]),
            jnp.asarray(self._keys[b:b + 1]), *self._kv_scale_args(),
            *self._adapter_args(slice(b, b + 1)))
        if self._anomaly:
            # the verdict is only consulted on the emitting (final) chunk
            # — fetch it there, not per chunk (no extra host sync on the
            # interleaved bulk-prefill path)
            self._kc, self._vc, nxt, keys, ok_dev = out
        else:
            self._kc, self._vc, nxt, keys = out
            ok_dev = None
        t1 = time.perf_counter()
        self._record_mp_comm(1, C, t0, t1, [req])
        metrics.bump("paged_steps")
        metrics.bump("chunk_steps")
        metrics.bump("prefill_chunks")
        metrics.add_time("prefill_time_s", t1 - t0)
        if req.trace is not None:
            req.trace.span("prefill_chunk", t0, t1, offset=off, tokens=v,
                           chunk=C)
        self._keys[b] = np.asarray(keys)[0]
        if last:
            self._chunk_off[b] = plen
            self._pos[b] = plen               # next decode writes here
            # only the final chunk is padded: waste < chunk per request
            metrics.observe_prefill_waste(C - v)
            ok = True if ok_dev is None else bool(np.asarray(ok_dev)[0])
            if not ok:
                # poisoned already at first-token time (bad weights or a
                # corrupted prompt page): quarantine before anything is
                # emitted or published
                self._quarantine(req, b)
                return
            if self.role == "prefill":
                # the prompt KV is complete: stream the remaining pages,
                # close the transfer and free the slot for the next
                # prompt — the assigned decode worker emits token #1
                self._finish_handoff(b)
                return
            tok = int(np.asarray(nxt)[0])
            self._emit_token(req, b, tok, first=True)
        else:
            self._chunk_off[b] = off + v
            if self.role == "prefill":
                # pages the chunk boundary just passed are FINAL (KV of a
                # token depends only on its prefix) — stream them now so
                # the transfer overlaps the rest of the prefill
                tr = self._outbound.get(req.request_id)
                if tr is not None:
                    self._stream_pages(b, tr)

    # -- KV-page streaming (disaggregated prefill/decode) --------------------
    def _stream_pages(self, b, tr, final=False):
        """Haul slot b's FINAL pages to the host and append them to the
        outbound transfer: everything the chunk boundary has passed (a
        token's KV depends only on its prefix, so a fully-written page
        never changes again), or all ``total_pages`` when ``final``."""
        complete = (tr.total_pages if final
                    else int(self._chunk_off[b]) // self.page_size)
        while len(tr.pages) < complete:
            li = len(tr.pages)
            phys = int(self.pool.table[b, li])
            kpage, vpage = self._page_read(self._kc, self._vc,
                                           jnp.int32(phys))
            ks = vs = None
            if self._kv_quant:
                ks = self.pool.k_scale[:, phys].copy()
                vs = self.pool.v_scale[:, phys].copy()
            payload = PagePayload(li, np.asarray(jax.device_get(kpage)),
                                  np.asarray(jax.device_get(vpage)),
                                  ks, vs)
            if self._kv_crc:
                payload.stamp()
            tr.append(payload)

    def _finish_handoff(self, b):
        """Prefill complete on a PREFILL worker: stream the remaining
        pages, close the transfer and free the slot — the request stays
        RUNNING (slot None) while the supervisor routes its pages to a
        decode worker, which emits token #1."""
        req = self._slots[b]
        tr = self._outbound[req.request_id]
        self._stream_pages(b, tr, final=True)
        tr.finish()
        if req.trace is not None:
            req.trace.instant("handoff", pages=tr.total_pages,
                              bytes=tr.bytes_total)
        metrics.bump("prefill_handoffs")
        # frees pages AND publishes the prompt to this worker's prefix
        # cache (chunk_off == plen) — the next shared-prefix prompt routed
        # here streams its covered pages without recompute
        self._free_slot(b)
        req.slot = None

    def offer_transfer(self, tr):
        """Hand an inbound KV transfer to this (decode-capable) engine:
        pages install between decode boundaries and the request seats in
        a free slot once all pages landed. Re-offering a transfer already
        in flight (a supervisor retry) restarts its install cleanly."""
        if self.kv_layout != "paged":
            raise ValueError("KV transfers ride the paged layout")
        if self.role == "prefill":
            raise ValueError(f"engine {self.tag!r} is a prefill worker; "
                             f"offer transfers to a decode-capable engine")
        if tr.page_size != self.page_size \
                or tr.kv_dtype != self.pool.kv_dtype:
            raise ValueError(
                f"transfer geometry (page_size={tr.page_size}, "
                f"kv_dtype={tr.kv_dtype!r}) does not match this engine "
                f"(page_size={self.page_size}, "
                f"kv_dtype={self.pool.kv_dtype!r})")
        rid = tr.request_id
        if rid in self._install_progress:
            self.pool.release_staged(rid)
            self._transfers_in = [t for t in self._transfers_in
                                  if t.request_id != rid]
        if self._page_write is None:
            donate_ok = jax.default_backend() != "cpu"
            self._page_write = _make_page_write((0, 1) if donate_ok else ())
        self._transfers_in.append(tr)
        self._install_progress[rid] = 0
        return tr

    def has_transfer(self, rid):
        """Is a transfer for request ``rid`` currently installing here?"""
        return rid in self._install_progress

    def _maybe_kv_bitflip(self):
        """Chaos hook body (``FaultPlan.kv_bitflip_at``): flip scheduled
        bits in the live K cache via a host round-trip. A mantissa flip
        stays FINITE — exactly the corruption class the all-finite guard
        is blind to and the sampled shadow audit exists for."""
        flips = _fi.maybe_kv_bitflip(self.tag, self._step_count)
        if not flips or self._kc is None:
            return
        host = np.asarray(jax.device_get(self._kc)).copy()
        for page, layer, bit in flips:
            view = host[int(layer) % host.shape[0], int(page) % host.shape[1]]
            flat = view.view(np.uint8).reshape(-1)
            byte, off = divmod(int(bit), 8)
            flat[byte] ^= np.uint8(1 << off)
        self._kc = jax.device_put(host, self._kc.sharding)

    def _install_page(self, payload, dst):
        """Write one page payload into physical page ``dst`` (ONE traced
        executable for every page of every transfer)."""
        kpage = jnp.asarray(payload.k, self._kc.dtype)
        vpage = jnp.asarray(payload.v, self._vc.dtype)
        self._kc, self._vc = self._page_write(self._kc, self._vc,
                                              kpage, vpage, jnp.int32(dst))
        if self._kv_quant:
            self.pool.k_scale[:, dst] = payload.k_scale
            self.pool.v_scale[:, dst] = payload.v_scale
        metrics.bump("transfer_installs")

    def _pump_transfers(self, now):
        """Advance inbound transfers at a decode boundary, T3-style: at
        most ``FLAGS_serving_transfer_pages_per_boundary`` page installs
        ride this boundary (the copies hide behind the batch's decode
        compute — decoding slots never stall on a transfer), then any
        fully-landed transfer seats its request in a free slot."""
        budget = self._transfer_budget
        keep = []
        for tr in self._transfers_in:
            rid = tr.request_id
            req = tr.request
            if tr.aborted or tr.failed or req.state == FINISHED:
                # handled elsewhere (cancel / supervisor abort): return
                # the staged pages, forget the stream
                self.pool.release_staged(rid)
                self._install_progress.pop(rid, None)
                continue
            if req.expired(now):
                self.pool.release_staged(rid)
                self._install_progress.pop(rid, None)
                tr.aborted = True
                self._resolve(req, EXPIRED, count="expired")
                continue
            installed = self._install_progress[rid]
            refused = False
            while budget > 0 and installed < len(tr.pages):
                dst = self.pool.stage(rid, 1)
                if dst is None:
                    break                  # page pressure: retry next boundary
                payload = _fi.maybe_corrupt_kv_payload(tr.pages[installed])
                if payload.crc is not None:
                    from ..distributed import integrity as _integrity
                    from .kv_transfer import KVIntegrityError
                    _integrity._count("crc_checks")
                    try:
                        payload.verify()
                    except KVIntegrityError:
                        # typed refusal: corrupt bytes never reach the
                        # pool. Drop the whole inbound stream — the
                        # supervisor sees has_transfer() go False and
                        # re-offers the RETAINED (still clean) payloads
                        _integrity._count("crc_refusals")
                        metrics.bump("transfer_crc_refusals")
                        self.pool.release_staged(rid)
                        self._install_progress.pop(rid, None)
                        refused = True
                        break
                self._install_page(payload, dst[0])
                installed += 1
                budget -= 1
            if refused:
                continue
            self._install_progress[rid] = installed
            if tr.done and installed == tr.total_pages \
                    and self._seat_transfer(tr, now):
                self._install_progress.pop(rid, None)
                continue
            keep.append(tr)
        self._transfers_in = keep

    def _seat_transfer(self, tr, now):
        """All pages landed: adopt them into a free slot and resume the
        request EXACTLY as a single engine resumes an exact-prefix-cache
        hit — ``chunk_off = plen - 1`` re-forwards the last prompt token
        (into exclusively-owned pages: no CoW) and the fresh per-request
        threefry key makes its FIRST split on the emitting chunk, so the
        token stream is bitwise the single-engine stream. Returns True
        when the transfer is terminal (seated or failed), False to retry
        at the next boundary (no slot / no tail pages yet)."""
        req = tr.request
        rid = tr.request_id
        b = next((i for i, r in enumerate(self._slots) if r is None), None)
        if b is None:
            return False
        if req.params_version is not None \
                and req.params_version != self.params_version:
            # the prompt KV was computed under different weights than this
            # engine serves — seating it would mix versions mid-stream.
            # Surface it to the supervisor for a single-version replay.
            tr.failed = True
            self.pool.release_staged(rid)
            return True
        plen = req.prompt_len
        extra = pages_for(plen + req.max_new_tokens,
                          self.page_size) - tr.total_pages
        tail = []
        if extra > 0:
            tail = self.pool.try_alloc(extra)
            if tail is None:
                return False               # page pressure: retry later
        pages = self.pool.adopt_staged(rid)
        self._trace_queue_span(req, b)
        self.pool.map_slot(b, pages + tail, None)
        req.slot = b
        self._slots[b] = req
        self._chunk_off[b] = plen - 1      # re-forward the last prompt token
        self._admit_count += 1
        self._admit_seq[b] = self._admit_count
        self._pos[b] = 0
        self._tok[b] = 0
        self._keys[b] = np.asarray(
            jax.random.key_data(jax.random.key(req.seed)))
        self._do_sample[b] = bool(req.do_sample)
        self._temp[b] = float(req.temperature)
        self._top_p[b] = 1.0 if req.top_p is None else float(req.top_p)
        tr.seated = True
        metrics.bump("transfers")
        metrics.bump("transfer_pages", tr.total_pages)
        metrics.bump("transfer_bytes", tr.bytes_total)
        metrics.add_time("transfer_time_s", now - tr.t_open)
        if req.trace is not None:
            # the transfer span covers open (prefill admission on the
            # source worker) to seat — TTFT = queue + transfer + the final
            # chunk's boundary, reconciling on the request's own timeline
            req.trace.span("transfer", tr.t_open, now,
                           bytes=tr.bytes_total, pages=tr.total_pages,
                           dtype=tr.kv_dtype, src=tr.src_tag)
        return True

    def _emit_token(self, req, b, tok, first):
        # a requeued/replayed request keeps its original first_token_t (the
        # user already saw a token) — only a genuinely-first emission may
        # contribute a TTFT sample, or every recovery round trip would
        # duplicate its entry in the histogram
        fresh_first = req.first_token_t is None
        req._emit(tok)
        metrics.bump("tokens_out")
        self._tok[b] = tok
        if first and fresh_first:
            metrics.observe_ttft(req.first_token_t - req.submit_t,
                                 priority=req.priority)
            if req.trace is not None:
                # the exact timestamp the TTFT sample uses — the exported
                # trace reconciles with the ledger to the float
                req.trace.instant("first_token", req.first_token_t)
        if req.stop_token_ids and tok in req.stop_token_ids:
            self._free_slot(b)
            self._resolve(req, STOP)
        elif len(req.tokens) >= req.max_new_tokens:
            self._free_slot(b)
            self._resolve(req, LENGTH)

    # -- preemptive admission (priority mode) --------------------------------
    def _preempt_margin(self, now=None):
        """Slack under which a queued deadline counts as at-risk: the flag
        when set, else 2x the ledger's recent TTFT p50 (what admission
        actually costs right now), floor 50ms."""
        if self._preempt_margin_s > 0:
            return self._preempt_margin_s
        p50 = metrics.recent_ttft_p50()
        return max(0.05, 2.0 * p50) if p50 is not None else 0.05

    def _capacity_for(self, req):
        """Could ``req`` be admitted right now without preempting? Exact:
        the paged check runs the real reservation as a side-effect-free
        probe (pages allocated then immediately released, no ledger/plan
        writes)."""
        if not any(r is None for r in self._slots):
            return False
        if self.kv_layout != "paged":
            return True
        return self._try_reserve(req, probe=True)

    def _preempt_slot(self, than_rank):
        """Victim slot for a class-``than_rank`` preemption: a RUNNING
        request of strictly worse class; worst class first, youngest
        admission first within it (the least sunk work is thrown away).
        None when every running slot is same-or-better class."""
        best = None
        for b, req in enumerate(self._slots):
            if req is None or req.class_rank <= than_rank:
                continue
            key = (req.class_rank, int(self._admit_seq[b]))
            if best is None or key > best[0]:
                best = (key, b)
        return None if best is None else best[1]

    def _preempt_for_deadline(self, now):
        """Evict lower-class running slots until the most at-risk queued
        request (slack within the preempt margin) has capacity, then seat
        it DIRECTLY: the regular admission order (class + WFQ tenant
        rotation) is deadline-blind, so leaving the freed slot to
        ``Scheduler.admit`` could hand it to a different request and the
        eviction would have been for nothing. Victims requeue at their
        ORIGINAL arrival — the PR 7 machinery — and their replay is
        bitwise, so this trades best-effort latency for the deadline.
        Bounded by the slot count per boundary."""
        margin = None
        for _ in range(self.num_slots):
            if margin is None:
                margin = self._preempt_margin(now)
            risk = self.scheduler.deadline_risk(now, margin)
            if risk is None:
                return
            if self.adapters is not None \
                    and not self.adapters.resident(risk.adapter or 0):
                # evicting running slots cannot make a non-resident
                # adapter appear — preemption would burn a victim for
                # nothing; the request waits for load_adapter instead
                return
            if not self._capacity_for(risk):
                b = self._preempt_slot(risk.class_rank)
                if b is None:
                    return
                victim = self._slots[b]
                self._free_slot(b)
                victim._requeue()
                self.scheduler.requeue(victim)
                metrics.bump("preempted")
                if not self._capacity_for(risk):
                    continue          # free more slots/pages for it
            if not self.scheduler.cancel(risk):
                return                # resolved concurrently: nothing owed
            if self.kv_layout == "paged" and not self._try_reserve(risk):
                # pages raced away between probe and reserve: restore the
                # queue entry at its arrival position, retry next boundary
                self.scheduler.requeue(risk)
                return
            free_b = next(b for b, r in enumerate(self._slots) if r is None)
            self._admit(risk, free_b)

    def _prefix_salt(self, req, version=None):
        """Prefix-cache key salt for ``req``. Base traffic (adapter id 0,
        or an adapter-less engine) gets b"" — base-model prompt pages are
        keyed by tokens alone and stay shared across every tenant AND
        across adapter load/evict/swap. Adapted requests get their
        (adapter id, content version): the adapted out/up/down projections
        feed the residual stream the NEXT layer's K/V is computed from, so
        a prompt page prefilled under one set of delta bits is only
        bitwise-reusable under those SAME bits. Versioned keys are what
        makes ``swap_adapter`` flush-free — the old version's entries just
        become unreachable and age out of the LRU."""
        if self.adapters is None:
            return b""
        aid = int(req.adapter or 0)
        if aid == 0:
            return b""
        if version is None:
            version = self.adapters.version(aid)
        return b"a%d:%d|" % (aid, int(version))

    def _try_reserve(self, req, probe=False):
        """Page-aware admission predicate (the scheduler's ``fits``): pin
        the longest cached prompt prefix, then allocate every page the
        request can touch over its WHOLE lifetime (prompt + max_new_tokens,
        plus a copy-on-write spare when sharing overlaps the write range).
        Returns False — pool untouched — when pages don't suffice yet; the
        head then waits for running requests to release pages (strict
        FCFS, no starvation). A request bound to a NON-RESIDENT adapter
        never fits — admission blocks (strict in-order: the scheduler
        stops at the first non-fitting head) until ``load_adapter`` makes
        the id resident; pages are untouched."""
        if self.adapters is not None \
                and not self.adapters.resident(req.adapter or 0):
            if not probe:
                metrics.bump("adapter_admit_blocked")
            return False
        pool = self.pool
        ps = self.page_size
        plen = req.prompt_len
        # a PREFILL worker computes (and ships) only the PROMPT's pages —
        # the decode worker reserves the generation tail when it seats the
        # transfer, so prefill admission never holds decode capacity
        total = pages_for(
            plen + (0 if self.role == "prefill" else req.max_new_tokens),
            ps)
        m, shared, exact = pool.lookup(req.prompt,
                                       salt=self._prefix_salt(req))
        # at least the last prompt token must be (re-)forwarded so the
        # first emitted token has logits — even on an exact-prompt hit
        chunk_start = min(m, plen - 1)
        n_shared = len(shared)
        pool.incref(shared)       # pin before eviction can drop the entries
        # CoW spare: needed only when a shared page overlaps this
        # request's write range (an exact-prompt hit sharing the partial
        # last page) — prefix registration happens on slot RELEASE, so a
        # request never CoWs against its own registration
        spare_needed = n_shared > 0 and n_shared - 1 >= chunk_start // ps
        need = (total - n_shared) + (1 if spare_needed else 0)
        if probe:
            # capacity question only (preemption policy): answered without
            # allocating — pool.try_alloc would EVICT cache entries to
            # satisfy a transient probe, churning the very prefix pages
            # (possibly this request's own) the reservation depends on
            ok = pool.can_alloc(need)
            pool.decref(shared)
            return ok
        got = pool.try_alloc(need)
        if got is None:
            pool.decref(shared)
            return False
        spare = got.pop() if spare_needed else None
        req._page_plan = (chunk_start, shared, got, spare)
        # ledger per successful ADMISSION (fits may poll a waiting head
        # many times; that must not dilute the hit rate)
        if pool.prefix_cache_enabled:
            metrics.bump("prefix_lookups")
        if n_shared:
            metrics.bump("prefix_hits")
            metrics.bump("prefix_tokens_reused", chunk_start)
            if req.trace is not None:
                req.trace.instant("prefix_hit", tokens=chunk_start,
                                  pages=n_shared)
        return True

    def _admit(self, req, b):
        if self.kv_layout == "paged":
            return self._admit_paged(req, b)
        return self._admit_pooled(req, b)

    def _admit_paged(self, req, b):
        """Bind slot b to the request's page plan (reserved by
        _try_reserve): cached prefix pages map logical 0..n_shared-1, fresh
        pages cover the rest of prompt + max_new_tokens. No forward pass
        happens here — the prompt prefills chunk-by-chunk inside the fused
        step, interleaved with every other slot's decode."""
        chunk_start, shared, private, spare = req._page_plan
        del req._page_plan
        self._trace_queue_span(req, b)
        self.pool.map_slot(b, list(shared) + list(private), spare)
        req.state = RUNNING
        req.slot = b
        req.params_version = self.params_version
        if self.adapters is not None:
            aid = int(req.adapter or 0)
            self._aid[b] = aid
            # the adapter analogue of params_version: which delta bits
            # produced this request's tokens (rides snapshots + results)
            req.adapter_version = self.adapters.version(aid)
            if req.trace is not None:
                req.trace.instant("adapter", adapter_id=aid,
                                  adapter_version=req.adapter_version)
        self._slots[b] = req
        self._chunk_off[b] = chunk_start
        self._admit_count += 1
        self._admit_seq[b] = self._admit_count
        self._pos[b] = 0
        self._tok[b] = 0
        self._keys[b] = np.asarray(
            jax.random.key_data(jax.random.key(req.seed)))
        self._do_sample[b] = bool(req.do_sample)
        self._temp[b] = float(req.temperature)
        self._top_p[b] = 1.0 if req.top_p is None else float(req.top_p)
        metrics.bump("admitted")
        if self.role == "prefill":
            # open the request's KV stream; pages a cached prefix already
            # covers (logical 0 .. chunk_start//ps - 1) are final right
            # now and stream before the first chunk even runs — the
            # prefix-affinity payoff on the prefill side
            tr = KVTransfer(req, self.page_size, self.pool.kv_dtype,
                            self.tag)
            self._outbound[req.request_id] = tr
            self._fresh_outbound.append(tr)
            self._stream_pages(b, tr)

    def _admit_pooled(self, req, b):
        """Prefill req's prompt into slot b (prompt padded to its bucket);
        the prefill emits the request's FIRST token (TTFT stops here)."""
        plen = req.prompt_len
        self._trace_queue_span(req, b)
        req.params_version = self.params_version
        bucket = self.scheduler.bucket_for(plen)
        metrics.observe_prefill_waste(bucket - plen)
        ids = np.zeros(bucket, np.int32)
        ids[:plen] = req.prompt
        key0 = jax.random.key_data(jax.random.key(req.seed))
        t0 = time.perf_counter()
        self._kc, self._vc, tok, key = self._prefill(
            self.params, self._kc, self._vc, jnp.asarray(ids),
            jnp.int32(plen), jnp.int32(b), jnp.asarray(key0),
            jnp.asarray(bool(req.do_sample)),
            jnp.float32(req.temperature),
            jnp.float32(1.0 if req.top_p is None else req.top_p))
        tok = int(np.asarray(tok))
        t1 = time.perf_counter()
        metrics.bump("prefill_calls")
        metrics.add_time("prefill_time_s", t1 - t0)
        metrics.bump("admitted")
        if req.trace is not None:
            req.trace.span("prefill", t0, t1, bucket=bucket, tokens=plen)

        req.state = RUNNING
        req.slot = b
        fresh_first = req.first_token_t is None  # replays don't re-observe
        req._emit(tok)
        metrics.bump("tokens_out")
        if fresh_first:
            metrics.observe_ttft(req.first_token_t - req.submit_t,
                                 priority=req.priority)
            if req.trace is not None:
                req.trace.instant("first_token", req.first_token_t)
        if req.stop_token_ids and tok in req.stop_token_ids:
            self._resolve(req, STOP)
            return
        if req.max_new_tokens == 1:
            self._resolve(req, LENGTH)
            return
        self._slots[b] = req
        self._keys[b] = np.asarray(key)
        self._tok[b] = tok
        self._pos[b] = plen            # first decode writes token's KV here
        self._do_sample[b] = bool(req.do_sample)
        self._temp[b] = float(req.temperature)
        self._top_p[b] = 1.0 if req.top_p is None else float(req.top_p)

    def _quarantine(self, req, b):
        """Anomaly-guard resolution (``FLAGS_serving_anomaly_policy=
        quarantine``): the fused step's per-slot all-finite check flagged
        this slot's logits — a NaN/Inf from bad weights, a corrupted KV
        page or a flaky chip. The token is NOT emitted (it would be
        garbage), the slot is freed WITHOUT publishing its prompt pages
        to the prefix cache (poisoned KV must never be reused), and the
        request resolves ``finish_reason="error"`` at this boundary.
        Neighbors are bitwise-stable — batch rows never interact — and
        the freed slot/pages are re-written before any future read, so
        neither the shared batch nor the next snapshot carries the
        poison forward."""
        pos = int(self._pos[b])
        self._free_slot(b, register=False)
        if req.trace is not None:
            req.trace.instant("anomaly", pos=pos)
        self._resolve(req, ERROR, count="anomalies_quarantined")

    def _free_slot(self, b, register=True):
        req = self._slots[b]
        if self.role == "prefill" and req is not None:
            # a prefill slot freed before its transfer completed (cancel /
            # expiry / quarantine / drain) aborts the stream — the normal
            # resolution path owns the request, the supervisor must not
            # replay it off a half-dead transfer
            tr = self._outbound.pop(req.request_id, None)
            if tr is not None and not tr.done:
                tr.aborted = True
        if self.kv_layout == "paged" and req is not None and register \
                and int(self._chunk_off[b]) >= req.prompt_len:
            # publish the prompt's pages for prefix reuse ON RELEASE
            # (vLLM-style cache-on-free): the slot never decodes into a
            # cache-pinned page, so registration costs zero CoW splits.
            # Generated-token KV beyond the prompt in the partial last
            # page is harmless — a consumer always CoW-copies that page
            # before its first write, and never unmasks a position it has
            # not itself written.
            # salt with the version STAMPED at admission (a bound adapter
            # cannot be mutated, but the stamped value is the truth of
            # which bits produced these pages)
            self.pool.register(
                req.prompt, b,
                salt=self._prefix_salt(req, version=req.adapter_version))
        self._slots[b] = None
        self._pos[b] = 0
        self._tok[b] = 0
        self._chunk_off[b] = 0
        # reset the sampling state too: a recycled slot must not carry its
        # predecessor's temp/top_p/do_sample/PRNG key — stale values made
        # slot-state debug dumps lie, and (worse) an admission that forgot
        # to overwrite one of these would silently couple the new
        # occupant's stream to the previous one's
        self._keys[b] = 0
        self._temp[b] = 1.0
        self._top_p[b] = 1.0
        self._do_sample[b] = False
        if self.adapters is not None and req is not None and req.tokens:
            # per-adapter token share (base id 0 included): the fairness
            # gauge the WFQ-across-adapters policy is audited against
            metrics.observe_adapter_tokens(int(self._aid[b]),
                                           len(req.tokens))
        self._aid[b] = 0
        if self.kv_layout == "paged":
            self.pool.release_slot(b)

    def _trace_queue_span(self, req, b):
        """Admission closes the request's queue-wait span: from arrival
        (``submit_t`` — the exact float the TTFT/latency ledger uses) or,
        after a requeue/restore hop, from the last recorded span, to now."""
        if req.trace is None:
            return
        tail = req.trace.tail()
        t0 = req.submit_t if tail is None else max(tail, req.submit_t)
        req.trace.span("queue", t0, time.perf_counter(), slot=b)

    def _resolve(self, req, reason, count="completed"):
        if req.state != FINISHED:
            req._finish(reason)
        req.slot = None
        if reason != SHED:
            # feeds the shed drain-rate EWMA: shedding itself must not
            # count as "drained" or a mass shed would spike the rate and
            # shrink the very retry hints it is about to hand out
            self._resolved_total += 1
        self._results[req.request_id] = req.result()
        if count is not None:
            metrics.bump(count)
        if reason in (STOP, LENGTH):
            metrics.bump(f"finished_{reason}")
        if req.trace is not None and not getattr(req, "_trace_done", False):
            # "deliver" lands at finish_t, the float the latency ledger
            # records — span timeline and SLO numbers reconcile exactly
            req._trace_done = True
            req.trace.instant("deliver", req.finish_t, reason=reason)
            obs_tracing.collect(req, engine_tag=self.tag)

    # -- hot weight swap -----------------------------------------------------
    def swap_params(self, params, version=None, count=True):
        """Replace the served weights in place with a SAME-SHAPE tree
        (``init_gpt_params`` layout, the thing ``HybridTrainStep`` trains):
        the executables are memoized per config and params are ordinary
        traced operands, so a same-shape swap re-dispatches the already
        compiled fused step — zero retraces (gated in tests). Bumps
        ``params_version`` (or sets it to ``version``); requests admitted
        AFTER the swap are stamped with the new version, requests already
        in a slot keep decoding against the swapped weights — which is why
        the supervisor's ``rolling_restart(new_params=)`` swaps only
        DRAINED replicas: in-flight work is requeued and recomputed from
        scratch on exactly one version, never a mid-stream mix.

        ``count=False`` skips the ``weight_swaps`` ledger bump — for
        RE-applications of already-live weights (a supervisor respawning a
        crashed replica after an upgrade), which are not new swaps and
        would make the upgrade audit trail useless for correlating
        regressions with actual weight changes."""
        if params is None:
            raise ValueError("swap_params needs a params tree")
        if any(r is not None for r in self._slots) \
                or self.scheduler.qsize() > 0:
            # KV already computed (and tokens already streamed) under the
            # old weights would continue under the new ones — a mid-stream
            # version mix. The supervisor always swaps freshly-spawned
            # (empty) engines; direct callers must drain first.
            raise RuntimeError(
                "swap_params on a non-idle engine: drain() first (the "
                "drained requests requeue and recompute single-version)")
        swap_spec = None
        if self._quant is not None and self._quant.quantizes_weights:
            # re-quantize ON DEVICE with FRESH per-channel scales (the
            # incoming weights' own absmax — a calibration pinned to the
            # OLD weights would clip channels that grew since); the KV
            # clip ranges stay the engine's (pool scales are untouched).
            # Same leaf dtypes/shapes as the served tree -> the shape
            # gate below passes and the swap stays zero-retrace.
            from dataclasses import replace as _dc_replace
            swap_spec = _dc_replace(self._quant, weight_scales=None)
        if self.mp > 1:
            # same prep as construction: head-major + column-sharded
            # placement (an already-sharded tree reshards on device)
            from .mp_forward import shard_serving_params
            new = shard_serving_params(params, self.config, self._mesh,
                                       self._mp_cfg, quant_spec=swap_spec)
        else:
            params = _logical_qkv(params, self.config)
            if swap_spec is not None:
                params = _squant.quantize_params(params, self.config,
                                                 swap_spec)
            new = jax.tree_util.tree_map(jnp.asarray, params)
        old_leaves, old_def = jax.tree_util.tree_flatten(self.params)
        new_leaves, new_def = jax.tree_util.tree_flatten(new)
        if old_def != new_def:
            raise ValueError(
                f"swap_params tree structure differs from the served "
                f"params ({new_def} vs {old_def}); a different "
                f"architecture needs a new Engine, not a swap")
        for o, n in zip(old_leaves, new_leaves):
            if o.shape != n.shape or o.dtype != n.dtype:
                raise ValueError(
                    f"swap_params leaf mismatch {n.shape}/{n.dtype} vs "
                    f"served {o.shape}/{o.dtype}; same-shape swaps only "
                    f"(anything else would retrace the fused step)")
        self.params = new
        self.params_version = (int(version) if version is not None
                               else self.params_version + 1)
        if self.kv_layout == "paged":
            # the prefix cache holds KV pages COMPUTED UNDER THE OLD
            # WEIGHTS — a post-swap prompt that prefix-hit them would
            # decode against stale KV (caught by the parity gate). Version
            # bump invalidates the whole cache. This full flush is scoped
            # to BASE-weight swaps only: adapter load/evict/swap
            # (load_adapter & co.) never touch attention, so their pages
            # stay valid and those ops deliberately skip this.
            self.pool.clear_cache()
        if self._spec is not None:
            # the draft must propose against the NEW weights (a stale
            # draft would only cost accept rate, never correctness — the
            # verify pass serves the swapped tree — but the whole point
            # of the self-draft is tracking the served version for free)
            self._build_draft_params()
        if count:
            metrics.bump("weight_swaps")
        return self

    # -- adapter hot-load / evict / swap -------------------------------------
    def _require_adapters(self):
        if self.adapters is None:
            raise RuntimeError(
                "this engine serves no adapters; construct it with "
                "adapter_slots > 0 (or FLAGS_serving_adapter_slots)")
        return self.adapters

    def _check_adapter_unbound(self, adapter_id, verb):
        """Refuse to mutate an adapter some RUNNING slot is decoding
        against: its stream would silently switch delta bits mid-request
        — the adapter analogue of the mid-stream version mix swap_params
        drains against. Queued requests are fine (admission re-checks
        residency and stamps the version at seat time)."""
        busy = [b for b, r in enumerate(self._slots)
                if r is not None and int(self._aid[b]) == int(adapter_id)]
        if busy:
            raise RuntimeError(
                f"cannot {verb} adapter {adapter_id}: bound to running "
                f"slot(s) {busy}; wait for them to finish (or cancel)")

    def _adapter_gauges(self):
        metrics.set_adapter_residency(len(self.adapters.resident_ids()),
                                      self.adapters.delta_bytes())

    def load_adapter(self, adapter_id, tree, alpha=None, count=True):
        """Make ``adapter_id`` resident (hot — while serving): a
        content-only rewrite of the fixed-shape delta slabs, so like
        ``swap_params`` it re-dispatches the already-compiled fused step
        with ZERO retraces (gated in tests). Queued requests blocked on
        this id admit at the next boundary.

        Unlike ``swap_params``, loading an adapter does NOT flush the
        prefix-page cache: attention projections are never adapted
        (serving/adapters.py rejects ``qkv_w``), so every KV page is
        computed under the BASE weights only and stays valid for every
        adapter — shared-base prefix reuse across adapters is the point.

        ``count=False`` skips the ``adapter_loads`` ledger bump (the
        supervisor RE-applying a live adapter set onto a respawned
        replica — not a new load)."""
        reg = self._require_adapters()
        self._check_stopped()
        self._check_adapter_unbound(adapter_id, "load over")
        version = reg.load(adapter_id, tree, alpha=alpha)
        if count:
            metrics.bump("adapter_loads")
        self._adapter_gauges()
        return version

    def evict_adapter(self, adapter_id, count=True):
        """Drop a resident adapter (hot): its slab rows zero and its id
        becomes loadable again. Queued requests bound to it WAIT at
        admission (strict in-order) until a reload. No prefix-cache
        flush — see ``load_adapter``. Zero retraces."""
        reg = self._require_adapters()
        self._check_stopped()
        self._check_adapter_unbound(adapter_id, "evict")
        reg.evict(adapter_id)
        if count:
            metrics.bump("adapter_evicts")
        self._adapter_gauges()

    def swap_adapter(self, adapter_id, tree, alpha=None, count=True):
        """Replace a RESIDENT adapter's delta in place (hot): bumps the
        per-adapter version — requests admitted after the swap are
        stamped with it — and, like every adapter op, costs zero retraces
        and no prefix-cache flush. ``count=False`` is the supervisor
        applying one fleet-level swap across its replicas (counted
        once)."""
        reg = self._require_adapters()
        self._check_stopped()
        self._check_adapter_unbound(adapter_id, "swap")
        version = reg.load(adapter_id, tree, alpha=alpha, replace=True)
        if count:
            metrics.bump("adapter_swaps")
        self._adapter_gauges()
        return version

    # -- self-healing: snapshot / restore / drain ----------------------------
    def attach_checkpoint(self, mgr, every=None):
        """Attach a hardened ``CheckpointManager`` as this engine's
        snapshot sink: every ``every`` step boundaries (default
        ``FLAGS_serving_snapshot_every``; 0 disables the cadence) the full
        engine state is saved through the CRC/rename-aside/retry path, and
        ``run()`` installs the manager's SIGTERM hook in ``defer`` mode —
        on preemption the loop finishes the in-flight fused step, flushes
        a consistent snapshot at the boundary, requeues in-flight requests
        and unwinds with ``Preempted``. Returns self."""
        self._ckpt = mgr
        if every is None:
            every = get_flags().get("FLAGS_serving_snapshot_every", 32) or 0
        self._snapshot_every = max(0, int(every))
        # keep snapshot step ids MONOTONIC per manager: a fresh engine
        # reattached to a directory with history (e.g. a supervisor respawn
        # after a drain) must not write snapshots that sort BELOW the stale
        # ones — _prune would delete the new snapshot immediately and
        # restore(None) would keep resurrecting pre-restart state. (A
        # subsequent load_state_dict overwrites _step_count with the
        # restored snapshot's own step, which is >= every step it leaves
        # on disk.)
        latest = mgr.latest_step()
        if latest is not None:
            self._step_count = max(self._step_count, int(latest))
        return self

    def save_snapshot(self, blocking=None):
        """Checkpoint the full engine state at the current step count
        through the attached manager (satellite of the PR 4 hardened path:
        per-array CRC manifest, rename-aside publish, OSError retry /
        quarantine). Returns the snapshot's step id."""
        if self._ckpt is None:
            raise RuntimeError(
                "no CheckpointManager attached; call attach_checkpoint()")
        self._ckpt.save(self._step_count, self.state_dict(),
                        blocking=blocking)
        metrics.bump("snapshots")
        return self._step_count

    def _snapshot_meta(self):
        # params_version is part of the compatibility contract: a snapshot
        # holds KV computed under ONE weight version, and restoring it
        # onto an engine serving another version would resume mid-stream
        # on mixed weights. The mismatch raises in load_state_dict; the
        # supervisor then falls back to replay-from-scratch on the new
        # version — zero drops either way, single-version results always.
        meta = {"kv_layout": self.kv_layout, "num_slots": self.num_slots,
                "max_seq_len": self.max_seq_len, "top_k": self.top_k,
                "params_version": int(self.params_version),
                "cfg": _cfg_key(self.config),
                # dtype config: part of the restore contract — quantized
                # KV bytes do not reinterpret across dtypes, so a
                # mismatched restore is REFUSED (typed) up front
                "weight_dtype": (self._quant.weight_dtype
                                 if self._quant is not None else "bf16"),
                "kv_dtype": (self._quant.kv_dtype
                             if self._quant is not None else "bf16"),
                # adapter CAPACITY is a compatibility axis (slab shapes);
                # the resident SET is data and rides state["adapters"]
                "adapters": (None if self._adapter_spec is None
                             else self._adapter_spec.key())}
        if self.kv_layout == "paged":
            meta.update(page_size=self.page_size,
                        prefill_chunk=self.prefill_chunk,
                        num_pages=self.pool.num_pages)
        else:
            meta["buckets"] = tuple(self.scheduler.buckets)
        return meta

    @staticmethod
    def _result_state(res):
        return {"request_id": int(res.request_id),
                "prompt": np.asarray(res.prompt).copy(),
                "tokens": list(res.tokens),
                "finish_reason": res.finish_reason,
                "ttft": res.ttft, "latency": res.latency,
                "priority": res.priority, "tenant": res.tenant,
                "params_version": res.params_version,
                "adapter": res.adapter,
                "adapter_version": res.adapter_version,
                "retry_after": res.retry_after,
                # exceptions may not pickle; the repr is enough postmortem
                "callback_error": (None if res.callback_error is None
                                   else repr(res.callback_error))}

    def state_dict(self):
        """Snapshot the FULL engine as host numpy / plain python: device
        KV (both layouts — for paged including the slot->page table,
        refcounted allocator and prefix-cache entries via
        ``PagedKVPool.state_dict``), the host slot table (last token,
        write position, per-slot threefry streams, sampling params, chunk
        progress, admission sequence), every in-flight and queued request
        (``Request.to_state``; ``on_token`` callbacks are not captured),
        unpopped results, and the serving metrics ledger. Safe for
        ``CheckpointManager``/``framework.io`` round trips; pair with
        ``load_state_dict`` for bitwise mid-decode resume."""
        kc_np = np.asarray(jax.device_get(self._kc))
        vc_np = np.asarray(jax.device_get(self._vc))
        if kc_np.dtype not in (np.int8, np.float32, np.float64, np.float16):
            # fp8/bf16 pools: numpy IO paths don't all speak ml_dtypes —
            # snapshot the raw bytes; meta's kv dtype restores the view
            kc_np = kc_np.view(np.uint8)
            vc_np = vc_np.view(np.uint8)
        state = {
            "meta": self._snapshot_meta(),
            "kc": kc_np,
            "vc": vc_np,
            "pos": self._pos.copy(), "tok": self._tok.copy(),
            "keys": self._keys.copy(), "temp": self._temp.copy(),
            "top_p": self._top_p.copy(),
            "do_sample": self._do_sample.copy(),
            "chunk_off": self._chunk_off.copy(),
            "aid": self._aid.copy(),
            "admit_seq": self._admit_seq.copy(),
            "admit_count": int(self._admit_count),
            "step_count": int(self._step_count),
            "slots": [None if r is None else r.to_state()
                      for r in self._slots],
            "queue": self.scheduler.queue_state(),
            "results": [self._result_state(r)
                        for r in self._results.values()],
            "metrics": metrics.export_state(),
            # both clocks: perf_counter anchors the request timestamps
            # (same-boot restores compare directly), wall time measures
            # the outage when the perf origin changed (other host/boot)
            "snapshot_t": time.perf_counter(),
            "snapshot_wall": time.time(),
        }
        if self.kv_layout == "paged":
            state["pool"] = self.pool.state_dict()
        if self.adapters is not None:
            # the resident adapter SET rides every snapshot: a restored
            # (or supervisor-respawned) engine serves the same many-model
            # surface without re-issuing load_adapter calls
            state["adapters"] = self.adapters.state_dict()
        if self._spec is not None:
            # draft/speculation state. Drafts are BOUNDARY-ATOMIC — a
            # draft+verify pair completes inside one step boundary and
            # every rejected byte is rewound before the host regains
            # control — so there is never pending-draft progress to
            # drain: the snapshot is always the plain-equivalent state,
            # which is what lets spec <-> plain restores stay bitwise.
            # (Deliberately NOT in _snapshot_meta: spec config is an
            # ENGINE property, not a snapshot-compatibility axis.)
            state["spec"] = {
                "speculate_k": int(self._spec.k),
                "draft_source": self._spec.source,
                "draft_layers": int(self._spec.layers),
                "draft_params_version": (
                    None if self._draft_params_version is None
                    else int(self._draft_params_version)),
            }
        return state

    def load_state_dict(self, state, restore_metrics=False):
        """Restore a ``state_dict()`` snapshot into this (compatibly
        configured) engine and resume exactly: mid-decode slots continue
        token-for-token bitwise identically to an uninterrupted run,
        greedy and sampled, on both layouts. No retracing happens — the
        executable builders are memoized per config, so a restored engine
        over warm shapes re-dispatches the already-compiled fused step
        (trace counters do not move; gated in tests).

        ``restore_metrics=True`` additionally replaces the process-global
        serving ledger with the snapshot's (for a cold cross-process
        restart); leave it False when other engines share the process.

        Timestamps: ``submit_t``/deadlines are ``perf_counter`` values
        whose origin is per-boot-arbitrary, so they are re-anchored onto
        the local clock using the snapshot's WALL-clock companion: the
        outage is measured as wall time elapsed since the save (NTP-level
        accuracy is plenty for second-scale deadlines), and every request
        timestamp shifts so the snapshot instant maps to ``now - outage``.
        Deadlines therefore keep ticking through the outage on any host;
        a same-process restore shifts by ~0."""
        meta = dict(state["meta"])
        # pre-quant snapshots carry no dtype fields: they are bf16/bf16
        meta.setdefault("weight_dtype", "bf16")
        meta.setdefault("kv_dtype", "bf16")
        # pre-adapter snapshots carry no capacity field: adapter-less.
        # Normalize the key's tuple-of-tuples (JSON round trips lists)
        meta.setdefault("adapters", None)
        if meta["adapters"] is not None:
            s, r, t = meta["adapters"]
            meta["adapters"] = (int(s), int(r), tuple(t))
        mine = self._snapshot_meta()
        snap_q = (meta["weight_dtype"], meta["kv_dtype"])
        mine_q = (mine["weight_dtype"], mine["kv_dtype"])
        if snap_q != mine_q:
            # typed refusal BEFORE any state is touched: quantized KV
            # bytes (and the scale tables) do not reinterpret across
            # dtype configs — deserializing them would be garbage
            raise _squant.QuantDtypeMismatchError(snap_q, mine_q)
        if meta != mine:
            raise ValueError(
                f"engine snapshot meta {meta} does not match this engine "
                f"{mine}; build the restoring Engine with the same config")
        compute = self._kc.dtype
        kc_np = np.asarray(state["kc"])
        vc_np = np.asarray(state["vc"])
        if kc_np.dtype == np.uint8 and compute != jnp.uint8:
            # raw-byte snapshot of an fp8 pool: restore the dtype view
            kc_np = kc_np.view(compute)
            vc_np = vc_np.view(compute)
        self._kc = jnp.asarray(kc_np, compute)
        self._vc = jnp.asarray(vc_np, compute)
        if self._kv_sharding is not None:
            # snapshots hold the GLOBAL pool (mp-independent geometry, and
            # the gather-only schedule makes its contents bitwise equal at
            # every mp) — lay the head axis back out across this engine's
            # chips. A snapshot therefore restores across mp degrees, incl.
            # single-chip <-> sharded.
            self._kc = jax.device_put(self._kc, self._kv_sharding)
            self._vc = jax.device_put(self._vc, self._kv_sharding)
        self._pos = np.asarray(state["pos"], np.int32).copy()
        self._tok = np.asarray(state["tok"], np.int32).copy()
        self._keys = np.asarray(state["keys"], np.uint32).copy()
        self._temp = np.asarray(state["temp"], np.float32).copy()
        self._top_p = np.asarray(state["top_p"], np.float32).copy()
        self._do_sample = np.asarray(state["do_sample"], bool).copy()
        self._chunk_off = np.asarray(state["chunk_off"], np.int32).copy()
        if "aid" in state:
            self._aid = np.asarray(state["aid"], np.int32).copy()
        else:                      # pre-adapter snapshot: all base
            self._aid = np.zeros(self.num_slots, np.int32)
        if self.adapters is not None and "adapters" in state:
            self.adapters.load_state_dict(state["adapters"])
            self._adapter_gauges()
        self._admit_seq = np.asarray(state["admit_seq"], np.int64).copy()
        self._admit_count = int(state["admit_count"])
        self._step_count = int(state["step_count"])
        if self.kv_layout == "paged":
            self.pool.load_state_dict(state["pool"])
            # in-flight transfer state is NOT part of a snapshot (the
            # KVTransfer objects live with the supervisor, which replays
            # or re-offers them): staged pages restored by the pool have
            # no owning stream anymore — return them to the free list
            self.pool.clear_staged()
        self._transfers_in = []
        self._install_progress = {}
        self._outbound = {}
        self._fresh_outbound = []
        self._slots = [None if s is None else Request.from_state(s)
                       for s in state["slots"]]
        queue = [Request.from_state(s) for s in state["queue"]]
        self.scheduler.restore_queue(queue)
        if self.role == "prefill":
            # a restored mid-prefill slot has no outbound stream to append
            # to (transfers are not snapshotted): reset it to the queue —
            # re-admission opens a fresh transfer and the replay is
            # bitwise (same prompt, same pages, no tokens emitted yet)
            for b, req in enumerate(self._slots):
                if req is None:
                    continue
                self._free_slot(b, register=False)
                req._requeue()
                self.scheduler.requeue(req)
                metrics.bump("requeued")
        outage = max(0.0, time.time() - float(state["snapshot_wall"]))
        shift = (time.perf_counter() - outage) - float(state["snapshot_t"])
        live = [r for r in self._slots if r is not None] + queue
        for r in live:
            for attr in ("submit_t", "first_token_t", "finish_t"):
                v = getattr(r, attr)
                if v is not None:
                    setattr(r, attr, v + shift)
            if r.trace is not None:
                # spans ride the same clock re-anchoring as the request
                # timestamps, then a restore hop marks the outage on the
                # request's own timeline
                r.trace.shift(shift)
                r.trace.instant("restore", outage_s=outage)
        self._results = {
            d["request_id"]: GenerationResult(
                request_id=d["request_id"], prompt=d["prompt"],
                tokens=list(d["tokens"]), finish_reason=d["finish_reason"],
                ttft=d["ttft"], latency=d["latency"],
                callback_error=d["callback_error"],
                priority=d.get("priority", "batch"),
                tenant=d.get("tenant", "default"),
                params_version=d.get("params_version"),
                adapter=d.get("adapter", 0),
                adapter_version=d.get("adapter_version"),
                retry_after=d.get("retry_after"))
            for d in state["results"]}
        if restore_metrics:
            metrics.import_state(state["metrics"])
        elif self.kv_layout == "paged" and self.pool.prefix_cache_enabled \
                and self.pool.cache_entries > 0:
            # the restored pool carries REAL cache entries whose lookups/
            # hits were counted before the snapshot: without the matching
            # counters, the post-restore hit RATE lies (hits against
            # restored entries over a lookup count that starts at zero).
            # Seed the prefix counters from the snapshot — only when this
            # process hasn't counted any prefix traffic of its own yet
            # (a shared-process sibling engine's ledger is never clobbered)
            metrics.seed_prefix_counters(
                state["metrics"].get("counters", {}))
        metrics.bump("snapshot_restores")
        if self._spec is not None:
            # the restoring engine rebuilt its draft from ITS OWN served
            # weights at construction; the meta check above already
            # guaranteed params_version agreement, so the draft tracks
            # the restored version too (state["spec"] is an audit stamp,
            # not restored state — drafts are boundary-atomic)
            self._draft_params_version = self.params_version
        self._stopped = False
        self._reforming = False
        self._reform_retry_after = None
        self._drained = []
        return self

    def drain(self):
        """Stop the engine and hand back every incomplete request, oldest
        arrival first: running slots are freed (pages released, prefix
        pages published) and their requests reset for requeue — original
        ``submit_t``/deadline kept, progress cleared so a replay re-emits
        the same tokens deterministically — and the wait queue is emptied
        untouched. The engine is left STOPPED: ``submit()`` raises
        ``EngineStoppedError`` (carrying these requests as the requeue
        hint) and ``step()`` returns False. Completed results remain
        available via ``pop_results()``."""
        drained = []
        for b, req in enumerate(self._slots):
            if req is None:
                continue
            self._free_slot(b)
            req._requeue()
            metrics.bump("requeued")
            drained.append(req)
        # transfer hygiene: outbound streams of freed slots were aborted
        # by _free_slot above; inbound streams return their staged pages —
        # their requests live on with the SUPERVISOR (payloads retained on
        # the KVTransfer), which re-offers or replays them elsewhere
        if self.kv_layout == "paged":
            for tr in self._transfers_in:
                self.pool.release_staged(tr.request_id)
            self._transfers_in = []
            self._install_progress = {}
            for tr in self._outbound.values():
                if not tr.done:
                    tr.aborted = True
            self._outbound = {}
            self._fresh_outbound = []
        drained.extend(self.scheduler.drain_queue())
        drained.sort(key=lambda r: (
            r.submit_t if r.submit_t is not None else float("inf"),
            r.request_id))
        self._stopped = True
        self._drained = drained
        return list(drained)

    def preempt_drain(self):
        """Graceful preemption at a step boundary (the serving mirror of
        ``CheckpointManager``'s ``defer=True`` flush; ``run()`` calls this
        between fused steps once the SIGTERM handler marks the manager
        preempted, so the snapshot is never torn mid-dispatch). Order
        matters: snapshot FIRST with slots intact — a cold restart resumes
        every mid-decode request bitwise — THEN requeue in-flight requests
        (the replay hint for a router when the snapshot is stale or
        unreachable), then unwind with ``Preempted``."""
        metrics.bump("preempt_drains")
        step = self._step_count
        state = self.state_dict()
        self.drain()
        if self._ckpt is not None:
            self._ckpt.flush_preempted(state, step=step)  # raises Preempted
        from ..incubate.checkpoint import Preempted
        raise Preempted("engine preempted; in-flight requests requeued")

    def live_requests(self):
        """Every incomplete request this engine owns: running slots (slot
        order) then the wait queue (FCFS)."""
        out = [r for r in self._slots if r is not None]
        out.extend(r for r in self.scheduler._q if r.state != FINISHED)
        return out

    @property
    def stopped(self):
        return self._stopped

    # -- draining ------------------------------------------------------------
    def pop_results(self):
        """Drain resolved requests: returns {request_id: GenerationResult}
        for everything resolved since the last drain and forgets them.
        Call this from a ``step()`` loop — results are held until popped,
        so an undrained long-running engine grows without bound."""
        out, self._results = self._results, {}
        return out

    def export_trace(self, path):
        """Write every collected finished-request trace (process-wide ring,
        this engine's included) as Perfetto-loadable Chrome-trace JSON."""
        return obs_tracing.export_perfetto(path)

    def run(self, requests=None):
        """Submit ``requests`` (optional) and step until queue and slots are
        empty. Returns {request_id: GenerationResult} for everything that
        resolved during this call (including earlier submissions).

        With a checkpoint manager attached, the manager's SIGTERM hook is
        installed in ``defer`` mode for the duration of the loop: a
        preemption notice only marks the manager, the loop finishes the
        current fused step, then ``preempt_drain()`` flushes a consistent
        boundary snapshot, requeues in-flight requests and unwinds with
        ``Preempted`` (BaseException — a preempted server must exit, not
        retry)."""
        if requests is not None:
            for r in requests:
                self.submit(r)
        installed = False
        if self._ckpt is not None and \
                threading.current_thread() is threading.main_thread():
            try:  # signals are main-thread-only; elsewhere rely on cadence
                self._ckpt.install_preemption_hook(None, defer=True)
                installed = True
            except ValueError:
                pass
        try:
            while True:
                if self._ckpt is not None and self._ckpt.preempted:
                    self.preempt_drain()         # raises Preempted
                if not self.step():
                    break
            if self._ckpt is not None and self._ckpt.preempted:
                # the notice landed DURING the final step: still flush and
                # unwind with Preempted — returning normally would let the
                # caller submit more work and the next hook install re-arm
                # (erase) the pending preemption
                self.preempt_drain()
        finally:
            if installed:
                self._ckpt.remove_preemption_hook()
        return self.pop_results()

    def generate(self, prompts, **kw):
        """Batch convenience: one Request per prompt (shared kwargs),
        results returned in submission order."""
        reqs = [Request(p, **kw) for p in prompts]
        results = self.run(reqs)
        return [results[r.request_id] for r in reqs]

    # -- introspection -------------------------------------------------------
    def kv_bytes_per_token(self):
        """Per-chip KV bytes one token position costs at this engine's
        dtype config: K + V across all layers for the chip's head shard,
        plus the amortized per-page scale bytes on a quantized pool — the
        bytes-per-token-by-dtype gauge of the capacity story (int8 ~4x
        fewer than fp32, fp8 likewise)."""
        cfg = self.config
        nh_l = cfg.num_heads // self.mp
        d = cfg.hidden_size // cfg.num_heads
        item = int(self._kc.dtype.itemsize)
        per_tok = 2 * cfg.num_layers * nh_l * d * item
        if self._kv_quant:
            # two fp32 scales per (layer, page), shared by page_size
            # tokens — rounded UP so the gauge never underreports to 0
            per_tok += -(-2 * cfg.num_layers * 4 // self.page_size)
        return per_tok

    def kv_shard_bytes(self):
        """Per-chip bytes of ONE of the two KV pool arrays at the pool's
        STORAGE dtype (int8/fp8 pools report their quantized footprint):
        the whole pool on a single-chip engine, 1/mp of it (the head
        shard) under mp — the memory gate of the sharded engine."""
        if self._kv_sharding is None:
            return int(self._kc.nbytes)
        shape = self._kv_sharding.shard_shape(self._kc.shape)
        n = 1
        for s in shape:
            n *= int(s)
        return n * self._kc.dtype.itemsize

    @property
    def active_slots(self):
        return sum(r is not None for r in self._slots)

    @property
    def queue_depth(self):
        return self.scheduler.qsize()
