"""Elastic serving supervisor: N engine replicas behind a least-loaded
router, with heartbeat failure detection, snapshot respawn and request
replay (the serving mirror of ``distributed.elastic.ElasticAgent``).

The supervisor owns the self-healing contract the engine alone cannot
provide: ZERO requests dropped across replica death. Every submitted
request is tracked until its result is delivered; when a replica dies —
engine exception, simulated kill (``FaultPlan.kill_at_decode_step``), or a
stale heartbeat (frozen process) — the supervisor first tries to respawn
the replica from its last engine snapshot (``Engine.load_state_dict``;
mid-decode requests resume bitwise), cancels whatever the restored engine
would recompute that was already delivered, and REPLAYS on a surviving
replica anything the snapshot predates or — when the snapshot is stale,
corrupt or missing — everything the dead replica still owed. Replays are
*exactly* equivalent: the engine's bitwise-parity guarantee (any admission
order, greedy and sampled, both KV layouts) means a replayed request's
token stream is identical to the one the dead replica would have produced.

Replicas here are in-process ``Engine`` objects driven round-robin — the
deterministic CPU harness the chaos ladder needs. A multi-host deployment
runs one engine per TPU VM with the same CheckpointManager/Heartbeat
wiring (``Engine.run()`` installs the SIGTERM drain per process); the
supervisor logic is identical because every primitive it consumes
(snapshot dirs, heartbeat files) already lives on shared storage.

Rolling restart (``rolling_restart()``) drains one replica at a time —
in-flight requests requeued with their ORIGINAL arrival time and deadline
onto the surviving replicas — so the fleet upgrades with zero drops and
bounded queue-depth spill.
"""
from __future__ import annotations

import os
import threading
import time

from ..flags import get_flags
from ..observability import register_supervisor
from ..incubate.checkpoint import CheckpointManager, Preempted
from ..distributed.elastic import Heartbeat, HeartbeatMonitor
from ..utils.fault_injection import Preemption
from . import metrics
from .engine import EngineStoppedError
from .request import CANCELLED, DROPPED, FINISHED, Request
from .scheduler import QueueFullError, ShedError
from .slo import Autoscaler, TokenBucket


def mp_replica_meshes(num_replicas, mp, devices=None):
    """Partition the device set into ``num_replicas`` DISJOINT 1-D ('mp',)
    meshes of ``mp`` chips each — under tensor-parallel serving a replica
    is an mp GROUP, not a chip. Hand each mesh to its replica's engine via
    a one-arg factory::

        meshes = serving.mp_replica_meshes(2, mp=4)      # 8 chips
        sup = ServingSupervisor(
            lambda i: serving.Engine(params=p, config=cfg,
                                     mesh=meshes[i]),
            num_replicas=2)

    The supervisor calls a factory that accepts an argument with the
    replica index (zero-arg factories keep working unchanged), so
    respawn-after-crash and rolling restarts rebuild each replica on ITS
    OWN chip group."""
    import jax
    import numpy as np
    from jax.sharding import Mesh
    devices = list(jax.devices() if devices is None else devices)
    mp = int(mp)
    need = int(num_replicas) * mp
    if need > len(devices):
        raise ValueError(
            f"{num_replicas} mp={mp} replicas need {need} devices, only "
            f"{len(devices)} available")
    return [Mesh(np.array(devices[i * mp:(i + 1) * mp]), ("mp",))
            for i in range(int(num_replicas))]


class _Replica:
    """One supervised engine slot: the engine itself is replaceable (it
    dies and respawns), the snapshot manager and heartbeat are not."""

    def __init__(self, idx, mgr, hb):
        self.idx = idx
        self.mgr = mgr              # persistent CheckpointManager or None
        self.hb = hb                # persistent Heartbeat or None
        self.engine = None
        # "up" | "down" | "draining" (rolling restart mid-drain: alive but
        # UNROUTABLE — submit/spill/replay must not target it) | "retired"
        # (scaled down: permanently out of rotation, indices stay stable)
        self.state = "down"
        self.restarts = 0
        self.last_error = None

    @property
    def routable(self):
        """Safe as a routing/replay target: up AND its engine accepts
        work (a drained engine raises EngineStoppedError on submit even
        while the replica object still says "up")."""
        return (self.state == "up" and self.engine is not None
                and not self.engine.stopped)

    @property
    def load(self):
        return self.engine.queue_depth + self.engine.active_slots


class ServingSupervisor:
    """Run ``num_replicas`` engines from ``engine_factory`` (a zero-arg
    callable returning a fresh, identically-configured ``Engine``) behind
    a least-queue-depth router::

        sup = ServingSupervisor(lambda: Engine(params=p, config=cfg),
                                num_replicas=2, snapshot_dir=tmp)
        for r in requests:
            sup.submit(r)
        results = sup.run()        # {request_id: GenerationResult}

    ``snapshot_dir`` enables per-replica engine snapshots through the
    hardened checkpoint path (cadence ``snapshot_every`` /
    ``FLAGS_serving_snapshot_every``); ``heartbeat_dir`` enables
    liveness monitoring (a replica whose file goes stale past
    ``heartbeat_timeout`` is failed over even though its process never
    raised). ``max_restarts`` bounds respawns per replica; past it the
    replica stays down and its work is replayed on the survivors.
    """

    def __init__(self, engine_factory, num_replicas=2, *, snapshot_dir=None,
                 snapshot_every=None, max_restarts=None, heartbeat_dir=None,
                 heartbeat_timeout=None, autoscale=None, tenant_rate=None,
                 tenant_burst=None):
        flags = get_flags()
        self.engine_factory = engine_factory
        self._factory_arity = None       # lazily inspected (_call_factory)
        self.snapshot_every = snapshot_every
        self.max_restarts = int(
            max_restarts if max_restarts is not None
            else flags.get("FLAGS_serving_max_restarts", 3))
        # stored so autoscale-grown replicas get the same snapshot/
        # heartbeat wiring the constructor-built ones did
        self._snapshot_dir = snapshot_dir
        self._heartbeat_dir = heartbeat_dir
        self._heartbeat_timeout = heartbeat_timeout
        # hot-swap state: once rolling_restart(new_params=) upgrades the
        # fleet, EVERY later spawn (respawn-after-crash, autoscale grow)
        # serves the new weights — a crash must not resurrect old ones
        self._live_params = None          # (params_tree, version) or None
        self._upgrading = False           # inside rolling_restart(new_params)
        # per-tenant token buckets at the router (ShedError over-rate)
        rate = (flags.get("FLAGS_serving_tenant_rate", 0.0)
                if tenant_rate is None else tenant_rate)
        burst = (flags.get("FLAGS_serving_tenant_burst", 8)
                 if tenant_burst is None else tenant_burst)
        self._tenant_rate = float(rate)
        self._tenant_burst = float(burst)
        self._buckets = {}                # tenant -> TokenBucket
        # telemetry-driven autoscaling (policy: serving/slo.py Autoscaler;
        # actions ride the existing spawn/drain machinery and are applied
        # on the supervising thread at step boundaries only)
        if autoscale is None:
            autoscale = bool(flags.get("FLAGS_serving_autoscale", False))
        if isinstance(autoscale, Autoscaler):
            self.autoscaler = autoscale
        elif autoscale:
            self.autoscaler = Autoscaler.from_flags(flags)
        else:
            self.autoscaler = None
        # One RLock guards the shared TRACKING state (requests/owner/
        # results/delivered) — the same discipline as the serving metrics
        # ledger's module lock — so monitoring threads (telemetry()
        # gauges, pending(), results(), a Prometheus scrape) read a
        # consistent view while the supervision loop runs. The engines
        # themselves are NOT thread-safe: submit()/cancel()/step() must
        # stay on the supervising thread (a router hands work to that
        # thread; it does not call into the engines concurrently).
        self._lock = threading.RLock()
        self._requests = {}          # request_id -> latest live Request
        self._owner = {}             # request_id -> replica idx
        self._results = {}           # request_id -> GenerationResult (1st wins)
        self._delivered = set()      # popped rids: dedup survives pop_results
        self._replicas = []
        for i in range(int(num_replicas)):
            self._replicas.append(self._new_replica(i))
        self.monitor = None
        self._remake_monitor()
        # live per-replica gauges in the metrics registry ("supervisor"
        # family; weakly referenced — dies with this object)
        register_supervisor(self)

    def _new_replica(self, i):
        """Build replica slot ``i`` (constructor AND autoscale-grow path):
        persistent snapshot manager + heartbeat, engine spawned up."""
        mgr = None
        if self._snapshot_dir is not None:
            mgr = CheckpointManager(
                os.path.join(os.fspath(self._snapshot_dir), f"replica_{i}"),
                async_save=False, site="serving_snapshot")
        hb = None
        if self._heartbeat_dir is not None:
            hb = Heartbeat(self._heartbeat_dir, rank=i)
        rep = _Replica(i, mgr, hb)
        rep.engine = self._spawn_engine(rep)
        rep.state = "up"
        if hb is not None:
            hb.beat()
        return rep

    def _remake_monitor(self):
        """(Re)build the heartbeat monitor over the CURRENT replica count
        — called at construction and after an autoscale grow, so new
        replicas are liveness-checked too."""
        if self._heartbeat_dir is None:
            return
        timeout = (self._heartbeat_timeout
                   if self._heartbeat_timeout is not None
                   else get_flags().get("FLAGS_serving_heartbeat_timeout",
                                        10.0))
        self.monitor = HeartbeatMonitor(self._heartbeat_dir,
                                        world_size=len(self._replicas),
                                        timeout=float(timeout))

    def _spawn_engine(self, rep):
        eng = self._call_factory(rep.idx)
        eng.tag = f"replica{rep.idx}"
        if self._live_params is not None:
            # the fleet was hot-upgraded: every spawn — crash respawn,
            # rolling restart, autoscale grow — serves the LIVE weights.
            # Only the upgrade itself counts as a weight swap; later
            # re-applications on respawn/grow are not new swaps
            params, version = self._live_params
            eng.swap_params(params, version=version,
                            count=self._upgrading)
        if rep.mgr is not None:
            eng.attach_checkpoint(rep.mgr, every=self.snapshot_every)
        return eng

    def _call_factory(self, idx):
        """Invoke the engine factory — one-arg factories receive the
        replica index (the tensor-parallel deployment shape: each replica
        builds its engine on its OWN mp device group, see
        ``mp_replica_meshes``); zero-arg factories keep the PR 7
        contract unchanged."""
        if self._factory_arity is None:
            try:
                import inspect
                sig = inspect.signature(self.engine_factory)
                self._factory_arity = sum(
                    1 for p in sig.parameters.values()
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD))
            except (TypeError, ValueError):
                self._factory_arity = 0
        if self._factory_arity >= 1:
            return self.engine_factory(idx)
        return self.engine_factory()

    # -- routing -------------------------------------------------------------
    def _up(self):
        return [r for r in self._replicas if r.state == "up"]

    def _routable(self):
        """Replicas that may receive NEW or replayed work: up and not
        mid-drain (a rolling restart marks the replica "draining" and its
        engine refuses submissions — routing there used to slip through
        because the spill check only compared queue depth)."""
        return [r for r in self._replicas if r.routable]

    def _pick(self, exclude=None):
        ups = [r for r in self._routable() if r is not exclude]
        if not ups:
            return None
        return min(ups, key=lambda r: (r.load, r.idx))

    def _rate_limit(self, request):
        """Per-tenant token bucket at the router: over-rate submissions
        are refused with ``ShedError`` carrying the exact time until the
        tenant's next token accrues — tenant isolation BEFORE the queues,
        so one tenant's flood cannot fill every replica's queue and starve
        the others into QueueFullError."""
        if self._tenant_rate <= 0:
            return
        with self._lock:
            # bucket creation AND take under the supervisor lock: router
            # threads submit concurrently (the documented concurrency
            # surface), and an unlocked read-modify-write of the token
            # count would let a tenant exceed rate*t + burst
            bucket = self._buckets.get(request.tenant)
            if bucket is None:
                bucket = self._buckets[request.tenant] = TokenBucket(
                    self._tenant_rate, self._tenant_burst)
            wait = bucket.take()
            if len(self._buckets) > 1024:
                # tenant ids are client-supplied strings: without a sweep
                # a rotating/adversarial id stream grows the map forever.
                # A refilled-to-burst bucket is indistinguishable from a
                # fresh one, so dropping it changes no admission decision.
                now = time.perf_counter()
                for t, b in list(self._buckets.items()):
                    if b is not bucket and b.idle_full(now):
                        del self._buckets[t]
        if wait > 0:
            metrics.bump("rate_limited")
            raise ShedError(
                f"tenant {request.tenant!r} over rate limit "
                f"({self._tenant_rate:.1f} req/s, burst "
                f"{self._tenant_burst:.0f}); retry in ~{wait:.2f}s",
                qsize=self.fleet_queue_depth(),
                max_queue=self.fleet_max_queue(), retry_after=wait)

    def fleet_queue_depth(self):
        return sum(r.engine.queue_depth for r in self._replicas
                   if r.engine is not None)

    def fleet_max_queue(self):
        return sum(r.engine.scheduler.max_queue for r in self._routable())

    def submit(self, request):
        """Route a request to the least-loaded routable replica (spilling
        to the next when its queue is full; ``QueueFullError`` — with
        FLEET-WIDE ``qsize``/``max_queue`` totals as its back-off hints —
        only once EVERY replica is saturated). Draining/stopped replicas
        are never targeted. Raises ``EngineStoppedError`` when no replica
        is routable, ``ShedError`` when the tenant is over its rate
        limit."""
        if not isinstance(request, Request):
            request = Request(request)
        ups = sorted(self._routable(), key=lambda r: (r.load, r.idx))
        if not ups:
            raise EngineStoppedError(
                "no live serving replica", queue_depth=0, requeued=())
        self._rate_limit(request)
        shedding = []
        for rep in ups:
            # saturation probes, not trial submits: a failed Engine.submit
            # bumps the global submitted/rejected/shed ledger, so spilling
            # by try/except would count one logical request once per full
            # (or shed-latched) replica and skew the SLO surface. Shed
            # state is PER-ENGINE — a replica latched in overload is
            # skipped and the request spills to a healthy one.
            shed = rep.engine._shed
            if shed is not None and shed.shedding \
                    and request.class_rank >= 2:
                shedding.append(rep)
                continue
            if rep.engine.queue_depth < rep.engine.scheduler.max_queue:
                rep.engine.submit(request)
                break
        else:
            # fleet-wide totals: the backoff a client derives from the
            # hint must reflect every queue it competes with, not whatever
            # replica happened to be probed last
            qsize, cap = self.fleet_queue_depth(), self.fleet_max_queue()
            if shedding:
                # every candidate was latched or full: refuse with the
                # largest (most honest) drain hint across latched replicas
                metrics.bump("shed")
                raise ShedError(
                    f"shedding {request.priority} traffic fleet-wide "
                    f"({qsize}/{cap} waiting); retry later",
                    qsize=qsize, max_queue=cap,
                    retry_after=max(
                        r.engine._shed.retry_after(r.engine.queue_depth)
                        for r in shedding))
            raise QueueFullError(
                f"all {len(ups)} replica queues full ({qsize}/{cap} "
                f"waiting fleet-wide); retry later",
                qsize=qsize, max_queue=cap)
        with self._lock:
            self._requests[request.request_id] = request
            self._owner[request.request_id] = rep.idx
        return request

    def _acked(self, rid):
        with self._lock:
            return rid in self._results or rid in self._delivered

    def cancel(self, request):
        """Cancel wherever the request currently lives (race-safe against
        drain/replay: a request caught between the two resolves as
        cancelled here — delivering its result immediately — and is
        skipped by any later requeue)."""
        rid = request.request_id
        if self._acked(rid):
            return
        with self._lock:
            live = self._requests.get(rid, request)
            owner = self._owner.get(rid)
        if owner is not None and self._replicas[owner].state == "up":
            self._replicas[owner].engine.cancel(live)
        elif live.state != FINISHED:
            # owner down / mid-replay: resolve directly so pending() drains
            live._finish(CANCELLED)
            metrics.bump("cancelled")
            with self._lock:
                self._results[rid] = live.result()

    # -- the supervision loop ------------------------------------------------
    def step(self):
        """One supervision round: step every live replica one engine
        iteration (heartbeating it), fail over replicas that died or went
        stale, collect results. Returns True while undelivered requests
        remain."""
        for rep in self._replicas:
            if rep.state != "up":
                continue
            try:
                rep.engine.step()
            except (Preemption, Preempted, Exception) as e:  # noqa: BLE001
                # abrupt death: results resolved DURING the dying step are
                # lost with the process (never read from a dead engine) —
                # recovery recomputes them from snapshot/replay
                self._on_failure(rep, e)
            else:
                self._collect(rep)
                if rep.hb is not None:
                    try:
                        rep.hb.beat(step=rep.engine._step_count)
                    except OSError:
                        # transient heartbeat-file IO is NOT engine death:
                        # the file just ages, and only the monitor's
                        # staleness timeout may eventually fail this
                        # replica over — don't burn its restart budget
                        pass
        if self.monitor is not None:
            for rank in self.monitor.failed_ranks():
                rep = self._replicas[rank]
                if rep.state == "up":
                    metrics.bump("stale_failovers")
                    self._on_failure(rep, RuntimeError(
                        f"stale heartbeat (replica {rank})"))
        if self.autoscaler is not None:
            self._autoscale_step()
        return self.pending() > 0

    # -- telemetry-driven autoscaling ----------------------------------------
    def _autoscale_step(self):
        """Evaluate the autoscale policy against the live fleet gauges
        (queue depth, slot occupancy, TTFT p99 — the PR 9 surface) and
        apply at most one action. Runs on the supervising thread at a step
        boundary, so growth/shrink can never tear an engine mid-dispatch;
        hysteresis windows and the cooldown live in the policy object."""
        ups = self._up()
        action = self.autoscaler.decide(
            alive=len(ups),
            queue_depth=sum(r.engine.queue_depth for r in ups),
            active_slots=sum(r.engine.active_slots for r in ups),
            total_slots=sum(r.engine.num_slots for r in ups),
            ttft_p99=metrics.recent_ttft_p99())
        if action == "grow":
            self._grow_replica()
        elif action == "shrink":
            self._shrink_replica()

    def _grow_replica(self):
        """Scale up: append a fresh replica (same snapshot/heartbeat
        wiring, live weights) and extend the liveness monitor over it."""
        rep = self._new_replica(len(self._replicas))
        self._replicas.append(rep)
        self._remake_monitor()
        metrics.bump("scale_ups")

    def _shrink_replica(self):
        """Scale down: drain the least-loaded replica (its in-flight work
        requeued on the survivors with ORIGINAL arrival — the rolling-
        restart machinery, zero drops) and retire the slot. Indices stay
        stable, so owner bookkeeping and heartbeat ranks never shift."""
        ups = self._up()
        if len(ups) <= 1:
            return
        rep = min(ups, key=lambda r: (r.load, -r.idx))
        rep.state = "draining"
        drained = rep.engine.drain()
        self._collect(rep)
        rep.engine = None
        rep.state = "retired"
        if rep.hb is not None:
            rep.hb.beat(status="stopped")
        for req in drained:
            if req.state == FINISHED:
                continue
            target = self._pick()
            if target is None:          # should not happen (len(ups) > 1)
                rep.engine = self._spawn_engine(rep)
                rep.state = "up"
                target = rep
            target.engine.requeue(req)
            with self._lock:
                self._owner[req.request_id] = target.idx
        metrics.bump("scale_downs")

    def _collect(self, rep):
        popped = rep.engine.pop_results()
        with self._lock:
            for rid, res in popped.items():
                # first result wins: a snapshot-respawned replica recomputes
                # work that was already delivered — recomputation is
                # deterministic, so dropping the duplicate loses nothing
                if not self._acked(rid):
                    self._results[rid] = res

    def _on_failure(self, rep, err):
        """Replica death: respawn from its last snapshot when one exists
        (mid-decode requests resume bitwise; anything newer than the
        snapshot is replayed), otherwise replay everything it still owed
        on the surviving replicas. Past ``max_restarts`` the replica stays
        down permanently."""
        rep.state = "down"
        rep.last_error = err
        rep.engine = None
        with self._lock:
            unacked = [rid for rid, owner in self._owner.items()
                       if owner == rep.idx and not self._acked(rid)]
        snap = None
        if rep.mgr is not None:
            try:
                snap = rep.mgr.restore(None)   # quarantines corrupt steps
            except Exception:
                snap = None
        rep.restarts += 1
        if rep.restarts > self.max_restarts:
            self._replay(unacked)
            return
        eng = self._spawn_engine(rep)
        restored = False
        if snap is not None:
            try:
                eng.load_state_dict(snap)
                restored = True
            except Exception:      # incompatible/stale-format snapshot
                restored = False
        rep.engine = eng
        rep.state = "up"
        metrics.bump("respawns")
        if rep.hb is not None:
            rep.hb.beat(status="running")
        if restored:
            # the snapshot may predate request movement: anything already
            # delivered, or since reassigned to ANOTHER replica (e.g. by a
            # rolling-restart drain), must not be recomputed here — cancel
            # is neighbor-stable, so the resumed slots stay bitwise intact
            for req in list(eng.live_requests()):
                rid = req.request_id
                if self._acked(rid) or self._owner.get(rid) != rep.idx:
                    # hygiene, not a user cancellation: skip the ledger
                    eng.cancel(req, count=None)
                else:
                    with self._lock:
                        self._requests[rid] = req  # live handle for cancel()
            # and purge stale results for moved/delivered requests (the
            # cancels above just minted CANCELLED results; a snapshot can
            # also carry pre-save ones): _collect must never deliver them
            # ahead of — or instead of — the real owner's stream
            for rid in list(eng._results):
                if self._acked(rid) or self._owner.get(rid) != rep.idx:
                    del eng._results[rid]
            recomputes = {r.request_id for r in eng.live_requests()}
            recomputes.update(eng._results)
            self._replay([rid for rid in unacked if rid not in recomputes],
                         prefer=rep)
        else:
            self._replay(unacked)

    def _replay(self, rids, prefer=None):
        """Resubmit lost requests as fresh copies — same request_id, seed,
        sampling params and ORIGINAL submit_t/deadline — on the preferred
        or least-loaded live replica. Exactness rides on the engine parity
        guarantee: the replayed stream is bitwise the one the dead replica
        would have produced."""
        for rid in rids:
            with self._lock:
                src = self._requests.get(rid)
            if src is None or self._acked(rid):
                continue
            if src.state == FINISHED:
                if src.finish_reason == CANCELLED:
                    # cancelled while in flight: its CANCELLED result may
                    # have died with the engine before a collect — deliver
                    # the outcome from the handle so pending() drains
                    with self._lock:
                        self._results[rid] = src.result()
                    continue
                # else: it FINISHED on the dying replica in the very step
                # that crashed (result lost, never collected) — fall
                # through and recompute an exact copy on a survivor
            target = prefer if (prefer is not None and prefer.state == "up") \
                else self._pick()
            if target is None:
                # the whole fleet is gone: resolve terminally so callers
                # driving pending()/run() converge to a visible failure
                # instead of spinning on an undeliverable request
                metrics.bump("dropped")
                src._finish(DROPPED)
                with self._lock:
                    self._results[rid] = src.result()
                continue
            copy = src.replay_copy()
            target.engine.requeue(copy)
            with self._lock:
                self._requests[rid] = copy
                self._owner[rid] = target.idx
            metrics.bump("replayed")

    # -- lifecycle -----------------------------------------------------------
    def _requeue_target(self, req, exclude=None):
        """Requeue target for a drained request: least-loaded routable
        replica, PREFERRING one that serves the weight version the request
        already produced tokens under — during a hot upgrade, in-flight
        work finishes on the version it started on as long as any replica
        of that version survives (only the final drain of the old fleet
        recomputes on the new version, from scratch, so every result is
        single-version consistent either way)."""
        ups = [r for r in self._routable() if r is not exclude]
        if not ups:
            return None
        if req.params_version is not None:
            same = [r for r in ups
                    if r.engine.params_version == req.params_version]
            if same:
                ups = same
        return min(ups, key=lambda r: (r.load, r.idx))

    def rolling_restart(self, absorb_steps=2, new_params=None,
                        params_version=None):
        """Restart the fleet one replica at a time with zero drops: mark
        a replica DRAINING (unroutable — new submissions and replays go
        elsewhere), drain it (in-flight requeued, original arrival kept),
        hand its work to the survivors, respawn it FRESH, then run a few
        supervision rounds so the fleet absorbs before the next drain.

        ``new_params`` turns the restart into a ZERO-DOWNTIME WEIGHT
        UPGRADE: each respawned replica comes back serving the new tree
        (``Engine.swap_params`` — same-shape, builders memoized per
        config, so no retrace), stamped ``params_version`` (default: one
        past the fleet's current version). Snapshots carry the version, so
        a crash-respawn can never resume new-version requests from an
        old-version snapshot's KV (the meta mismatch falls back to replay
        — still zero drops); results carry the version their tokens were
        produced under; and drained in-flight requests prefer surviving
        OLD-version replicas, finishing on the version they started on
        whenever one exists."""
        metrics.bump("rolling_restarts")
        if new_params is not None:
            if params_version is None:
                versions = [r.engine.params_version for r in self._replicas
                            if r.engine is not None]
                params_version = max(versions, default=0) + 1
            self._live_params = (new_params, int(params_version))
            self._upgrading = True
        try:
            for rep in list(self._replicas):
                if rep.state != "up":
                    continue
                rep.state = "draining"  # unroutable while its queue moves
                drained = rep.engine.drain()
                self._collect(rep)
                rep.engine = self._spawn_engine(rep)
                rep.restarts = 0        # a planned restart is not a failure
                rep.state = "up"
                metrics.bump("respawns")
                if rep.hb is not None:
                    rep.hb.beat(status="running")
                for req in drained:
                    if req.state == FINISHED:
                        continue        # cancelled mid-requeue: done already
                    target = self._requeue_target(req, exclude=rep) or rep
                    target.engine.requeue(req)
                    with self._lock:
                        self._owner[req.request_id] = target.idx
                for _ in range(max(0, int(absorb_steps))):
                    self.step()
        finally:
            self._upgrading = False

    def pending(self):
        """Requests submitted but not yet delivered."""
        with self._lock:
            return sum(1 for rid in self._requests if not self._acked(rid))

    def pop_results(self):
        """Drain resolved requests and forget their tracking state (the
        supervisor-level mirror of ``Engine.pop_results`` — an undrained
        long-running supervisor would retain every prompt and token list
        forever). Delivered ids stay in a lightweight seen-set, so a
        replica respawned from a stale snapshot can never re-deliver a
        duplicate after the heavy state is dropped."""
        with self._lock:
            out, self._results = self._results, {}
            for rid in out:
                self._delivered.add(rid)
                self._requests.pop(rid, None)
                self._owner.pop(rid, None)
        return out

    def run(self, requests=None, max_steps=100000):
        """Submit ``requests`` (optional) and supervise until every tracked
        request has a result, then drain: returns {request_id:
        GenerationResult} for everything resolved since the last drain
        (check ``finish_reason`` — a dead-fleet terminal failure surfaces
        as ``DROPPED`` rather than an infinite wait)."""
        if requests is not None:
            for r in requests:
                self.submit(r)
        steps = 0
        while self.pending():
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"supervisor did not converge in {max_steps} rounds "
                    f"({self.pending()} requests still pending)")
        return self.pop_results()

    def shutdown(self):
        """Drain every live replica; returns still-incomplete requests
        (original arrival kept) for hand-off to another fleet."""
        leftovers = []
        for rep in self._replicas:
            if rep.state == "up" and rep.engine is not None:
                leftovers.extend(rep.engine.drain())
                self._collect(rep)
                if rep.hb is not None:
                    rep.hb.beat(status="stopped")
                rep.state = "down"
        return leftovers

    # -- introspection -------------------------------------------------------
    @property
    def alive_replicas(self):
        return len(self._up())

    def results(self):
        """Resolved-but-not-yet-popped results (non-draining peek)."""
        with self._lock:
            return dict(self._results)

    def telemetry(self):
        """Live fleet gauges (the registry's "supervisor" family — one
        scrape shows routing pressure and failover history per replica):
        per-replica up/queue-depth/active-slots/restarts plus the
        fleet-level pending count."""
        out = {"replicas": len(self._replicas),
               "alive": len(self._up()),
               "pending": self.pending(),
               "params_version": (self._live_params[1]
                                  if self._live_params is not None else 0)}
        for rep in self._replicas:
            eng = rep.engine
            out[f"replica{rep.idx}"] = {
                "up": int(rep.state == "up"),
                "state": rep.state,
                "restarts": int(rep.restarts),
                "queue_depth": (0 if eng is None else eng.queue_depth),
                "active_slots": (0 if eng is None else eng.active_slots),
                "step_count": (0 if eng is None else eng._step_count),
                "params_version": (0 if eng is None
                                   else int(eng.params_version)),
            }
        return out
