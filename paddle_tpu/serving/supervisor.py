"""Elastic serving supervisor: N engine replicas behind a least-loaded
router, with heartbeat failure detection, snapshot respawn and request
replay (the serving mirror of ``distributed.elastic.ElasticAgent``).

The supervisor owns the self-healing contract the engine alone cannot
provide: ZERO requests dropped across replica death. Every submitted
request is tracked until its result is delivered; when a replica dies —
engine exception, simulated kill (``FaultPlan.kill_at_decode_step``), or a
stale heartbeat (frozen process) — the supervisor first tries to respawn
the replica from its last engine snapshot (``Engine.load_state_dict``;
mid-decode requests resume bitwise), cancels whatever the restored engine
would recompute that was already delivered, and REPLAYS on a surviving
replica anything the snapshot predates or — when the snapshot is stale,
corrupt or missing — everything the dead replica still owed. Replays are
*exactly* equivalent: the engine's bitwise-parity guarantee (any admission
order, greedy and sampled, both KV layouts) means a replayed request's
token stream is identical to the one the dead replica would have produced.

Replicas here are in-process ``Engine`` objects driven round-robin — the
deterministic CPU harness the chaos ladder needs. A multi-host deployment
runs one engine per TPU VM with the same CheckpointManager/Heartbeat
wiring (``Engine.run()`` installs the SIGTERM drain per process); the
supervisor logic is identical because every primitive it consumes
(snapshot dirs, heartbeat files) already lives on shared storage.

Rolling restart (``rolling_restart()``) drains one replica at a time —
in-flight requests requeued with their ORIGINAL arrival time and deadline
onto the surviving replicas — so the fleet upgrades with zero drops and
bounded queue-depth spill.
"""
from __future__ import annotations

import os
import threading
import time

from ..flags import get_flags
from ..observability import register_supervisor
from ..incubate.checkpoint import CheckpointManager, Preempted
from ..distributed.elastic import Heartbeat, HeartbeatMonitor
from ..utils.fault_injection import Preemption
from . import metrics
from .elastic import (  # noqa: F401  (mp_replica_meshes re-exported here)
    FleetTopology, degraded_count, mp_replica_meshes, record_reform,
    set_group_gauges,
)
from .engine import EngineStoppedError
from .request import CANCELLED, DROPPED, FINISHED, RUNNING, Request
from .scheduler import QueueFullError, ShedError
from .slo import Autoscaler, TokenBucket


class ChipLossError(RuntimeError):
    """A chip of this replica's mp group was lost (injected schedule or
    stale chip heartbeat): the whole group is down and must be re-formed
    over the survivors."""


class AuditFailure(RuntimeError):
    """A replica's shadow-audit suspicion score reached
    ``FLAGS_serving_audit_threshold``: its outputs diverged from the
    ``generate_from_params`` oracle repeatedly — silent state corruption
    (e.g. a finite KV bit flip the all-finite guard cannot see). The
    replica is failed over through the ordinary reform/respawn machinery
    before the corruption spreads through its prefix cache."""


class _Replica:
    """One supervised engine slot: the engine itself is replaceable (it
    dies and respawns), the snapshot manager and heartbeat are not."""

    def __init__(self, idx, mgr, hb):
        self.idx = idx
        self.mgr = mgr              # persistent CheckpointManager or None
        self.hb = hb                # persistent Heartbeat or None
        self.engine = None
        # "up" | "down" | "draining" (rolling restart mid-drain: alive but
        # UNROUTABLE — submit/spill/replay must not target it) |
        # "reforming" (topology-elastic: the mp group is being re-formed
        # over surviving chips — TEMPORARILY unroutable, comes back) |
        # "retired" (scaled down: permanently out of rotation, indices
        # stay stable)
        self.state = "down"
        self.restarts = 0
        self.last_error = None
        # disaggregated serving: the role this replica CURRENTLY runs
        # ("both" | "prefill" | "decode") and the one it was configured
        # with — they diverge while a chip loss has the fleet rebalanced
        # (a prefill worker covering for dead decode capacity flips back
        # once configured decode capacity is routable again)
        self.role = "both"
        self.configured_role = "both"
        # topology-elastic state (None/0 when the supervisor is not in
        # elastic mode): the mesh the CURRENT engine runs on, its mp
        # degree and its global chip ranks
        self.mesh = None
        self.mp = 0
        self.group = ()
        self.chip_lost = False      # down specifically for lost chips
        # spaced retry of a reform whose spawn/restore keeps failing:
        # boundaries left to skip, and the (doubling) next skip length
        self.reform_wait = 0
        self.reform_backoff = 0

    @property
    def routable(self):
        """Safe as a routing/replay target: up AND its engine accepts
        work (a drained engine raises EngineStoppedError on submit even
        while the replica object still says "up")."""
        # single read of engine: a reform on the supervising thread
        # nulls it concurrently with router threads
        eng = self.engine
        return self.state == "up" and eng is not None and not eng.stopped

    @property
    def load(self):
        # single read of engine: a reform on the supervising thread nulls
        # it concurrently with router threads sorting by load — a nulled
        # replica sorts last and the router's in-loop None guard skips it
        eng = self.engine
        if eng is None:
            return float("inf")
        # fold the in-flight PREFILL BACKLOG in (normalized to chunk
        # boundaries — the unit a queued arrival actually waits behind):
        # queue depth + occupied slots alone make a replica grinding
        # through a giant mid-prefill prompt look as idle as one decoding
        # short tails, and the router would pile new prompts onto it
        backlog = eng.prefill_backlog()
        if backlog:
            chunk = getattr(eng, "prefill_chunk", 0) or 1
            return eng.queue_depth + eng.active_slots + backlog / chunk
        return eng.queue_depth + eng.active_slots


class ServingSupervisor:
    """Run ``num_replicas`` engines from ``engine_factory`` (a zero-arg
    callable returning a fresh, identically-configured ``Engine``) behind
    a least-queue-depth router::

        sup = ServingSupervisor(lambda: Engine(params=p, config=cfg),
                                num_replicas=2, snapshot_dir=tmp)
        for r in requests:
            sup.submit(r)
        results = sup.run()        # {request_id: GenerationResult}

    ``snapshot_dir`` enables per-replica engine snapshots through the
    hardened checkpoint path (cadence ``snapshot_every`` /
    ``FLAGS_serving_snapshot_every``); ``heartbeat_dir`` enables
    liveness monitoring (a replica whose file goes stale past
    ``heartbeat_timeout`` is failed over even though its process never
    raised). ``max_restarts`` bounds respawns per replica; past it the
    replica stays down and its work is replayed on the survivors.

    ``mp=N`` turns the supervisor TOPOLOGY-ELASTIC (serving/elastic.py):
    each replica is an mp GROUP of N chips (``devices`` defaults to the
    first ``num_replicas * N`` of ``jax.devices()``), watched at CHIP
    granularity — injected ``FaultPlan.serving_chip_loss_at`` schedules
    and, with ``heartbeat_dir``, per-chip heartbeat staleness. One lost
    chip marks its whole group down; the group re-forms over its
    surviving chips at the largest viable mp degree and respawns through
    the MP-PORTABLE snapshot path (mid-decode requests resume bitwise on
    the smaller group; the rest replays — zero drops). When the chips
    return the group grows back from a live snapshot
    (``FLAGS_serving_elastic_grow``) with zero drops and zero new traces
    (engine builders are memoized per (cfg, mesh, rung)). The factory
    must take ``(replica_idx, mesh)``. While a group is mid-reform the
    router treats it as temporarily unroutable; shed watermarks and the
    autoscaler read live ROUTABLE capacity, so a degraded fleet sheds
    and scales against what can actually serve.
    """

    def __init__(self, engine_factory, num_replicas=2, *, snapshot_dir=None,
                 snapshot_every=None, max_restarts=None, heartbeat_dir=None,
                 heartbeat_timeout=None, autoscale=None, tenant_rate=None,
                 tenant_burst=None, mp=None, devices=None,
                 elastic_grow=None, roles=None, audit_ref=None):
        flags = get_flags()
        self.engine_factory = engine_factory
        # -- sampled shadow audit (FLAGS_serving_audit_rate): replay that
        # fraction of finished greedy requests through the
        # generate_from_params oracle and bitwise-compare tokens. Needs
        # ``audit_ref=(raw_params, config)`` — the engine transforms its
        # own params at construction (logical-qkv / mp-shard / quantize),
        # so the supervisor keeps an untransformed reference copy.
        self._audit_ref = audit_ref
        self._audit_rate = float(
            flags.get("FLAGS_serving_audit_rate", 0.0) or 0.0)
        self._audit_threshold = max(1, int(
            flags.get("FLAGS_serving_audit_threshold", 2)))
        self._audit_warned = False
        # -- disaggregated prefill/decode serving (serving/kv_transfer.py):
        # ``roles`` assigns each replica a serving role — "prefill"
        # workers run only the big-chunk rungs over all their slots and
        # stream finished KV pages to a decode worker; "decode"/"both"
        # replicas install the pages between decode boundaries and emit
        # tokens. With roles unset the supervisor is the plain fleet,
        # byte-identical.
        self._roles = None
        self._disagg = False
        self._affinity_routing = bool(
            flags.get("FLAGS_serving_affinity_routing", True))
        self._transfers = {}         # rid -> in-flight KVTransfer
        self._assign = {}            # rid -> decode replica idx (target)
        self._transfer_src = {}      # rid -> prefill replica idx (source)
        if roles is not None:
            roles = tuple(str(r) for r in roles)
            if len(roles) != int(num_replicas):
                raise ValueError(
                    f"roles has {len(roles)} entries for "
                    f"{int(num_replicas)} replicas")
            for r in roles:
                if r not in ("both", "prefill", "decode"):
                    raise ValueError(
                        f"role must be 'both', 'prefill' or 'decode', "
                        f"got {r!r}")
            if all(r == "prefill" for r in roles):
                raise ValueError(
                    "a disaggregated fleet needs at least one decode-"
                    "capable replica ('decode' or 'both'): prefill "
                    "workers never emit tokens")
            self._roles = roles
            self._disagg = any(r == "prefill" for r in roles)
        self._factory_arity = None       # lazily inspected (_call_factory)
        self.snapshot_every = snapshot_every
        # -- topology-elastic mode (serving/elastic.py): ``mp`` makes each
        # replica an mp GROUP the supervisor watches at CHIP granularity.
        # A lost chip (injected schedule or stale per-chip heartbeat)
        # marks its whole group down; the group is re-formed over its
        # surviving chips at the largest viable mp degree and respawned
        # through the mp-portable snapshot path (bitwise resume). When
        # chips return the group grows back from a LIVE snapshot (zero
        # drops, memoized builders → zero new traces). With ``mp`` unset
        # the supervisor is the plain PR 7/10 fleet, byte-identical.
        self._topology = None
        self._topo_step = 0
        self._configured_mp = 0
        self._elastic_grow = (bool(flags.get("FLAGS_serving_elastic_grow",
                                             True))
                              if elastic_grow is None else bool(elastic_grow))
        self._reform_retries = int(
            flags.get("FLAGS_serving_reform_retries", 2))
        if mp is not None:
            self._configured_mp = int(mp)
            self._topology = FleetTopology(
                devices, self._configured_mp, num_replicas,
                heartbeat_dir=heartbeat_dir,
                heartbeat_timeout=heartbeat_timeout)
            # liveness is per-CHIP in elastic mode (the topology monitor
            # supersedes per-replica heartbeats: a stale chip takes its
            # group down through the reform path, not the failover path)
            heartbeat_dir = None
        self.max_restarts = int(
            max_restarts if max_restarts is not None
            else flags.get("FLAGS_serving_max_restarts", 3))
        # stored so autoscale-grown replicas get the same snapshot/
        # heartbeat wiring the constructor-built ones did
        self._snapshot_dir = snapshot_dir
        self._heartbeat_dir = heartbeat_dir
        self._heartbeat_timeout = heartbeat_timeout
        # hot-swap state: once rolling_restart(new_params=) upgrades the
        # fleet, EVERY later spawn (respawn-after-crash, autoscale grow)
        # serves the new weights — a crash must not resurrect old ones
        self._live_params = None          # (params_tree, version) or None
        self._upgrading = False           # inside rolling_restart(new_params)
        # many-model serving: the fleet's LIVE adapter set (adapter_id ->
        # (tree, alpha)) — the adapter mirror of _live_params. Every later
        # spawn (crash respawn, chip-loss reform, rolling restart,
        # autoscale grow) re-applies it, and a restored snapshot is
        # reconciled against it, so a crash can never resurrect a stale
        # adapter set. Maintained by the fleet-level load_adapter/
        # evict_adapter/swap_adapter below.
        self._live_adapters = {}
        # per-tenant token buckets at the router (ShedError over-rate)
        rate = (flags.get("FLAGS_serving_tenant_rate", 0.0)
                if tenant_rate is None else tenant_rate)
        burst = (flags.get("FLAGS_serving_tenant_burst", 8)
                 if tenant_burst is None else tenant_burst)
        self._tenant_rate = float(rate)
        self._tenant_burst = float(burst)
        self._buckets = {}                # tenant -> TokenBucket
        # telemetry-driven autoscaling (policy: serving/slo.py Autoscaler;
        # actions ride the existing spawn/drain machinery and are applied
        # on the supervising thread at step boundaries only)
        if autoscale is None:
            autoscale = bool(flags.get("FLAGS_serving_autoscale", False))
        if isinstance(autoscale, Autoscaler):
            self.autoscaler = autoscale
        elif autoscale:
            self.autoscaler = Autoscaler.from_flags(flags)
        else:
            self.autoscaler = None
        # One RLock guards the shared TRACKING state (requests/owner/
        # results/delivered) — the same discipline as the serving metrics
        # ledger's module lock — so monitoring threads (telemetry()
        # gauges, pending(), results(), a Prometheus scrape) read a
        # consistent view while the supervision loop runs. The engines
        # themselves are NOT thread-safe: submit()/cancel()/step() must
        # stay on the supervising thread (a router hands work to that
        # thread; it does not call into the engines concurrently).
        self._lock = threading.RLock()
        self._requests = {}          # request_id -> latest live Request
        self._owner = {}             # request_id -> replica idx
        self._results = {}           # request_id -> GenerationResult (1st wins)
        self._delivered = set()      # popped rids: dedup survives pop_results
        self._replicas = []
        for i in range(int(num_replicas)):
            self._replicas.append(self._new_replica(i))
        self.monitor = None
        self._remake_monitor()
        # live per-replica gauges in the metrics registry ("supervisor"
        # family; weakly referenced — dies with this object)
        register_supervisor(self)

    def _new_replica(self, i):
        """Build replica slot ``i`` (constructor AND autoscale-grow path):
        persistent snapshot manager + heartbeat, engine spawned up."""
        mgr = None
        if self._snapshot_dir is not None:
            mgr = CheckpointManager(
                os.path.join(os.fspath(self._snapshot_dir), f"replica_{i}"),
                async_save=False, site="serving_snapshot")
        hb = None
        if self._heartbeat_dir is not None:
            hb = Heartbeat(self._heartbeat_dir, rank=i)
        rep = _Replica(i, mgr, hb)
        if self._roles is not None and i < len(self._roles):
            rep.configured_role = rep.role = self._roles[i]
        if self._topology is not None:
            if i >= self._topology.num_replicas:
                raise ValueError(
                    f"cannot grow replica {i}: the elastic fleet topology "
                    f"was sized for {self._topology.num_replicas} mp="
                    f"{self._configured_mp} groups (autoscale growth needs "
                    f"spare chips the topology does not have)")
            rep.mp, rep.group = self._topology.plan(i, frozenset())
            rep.mesh = self._topology.mesh_for(rep.group)
        rep.engine = self._spawn_engine(rep)
        rep.state = "up"
        if hb is not None:
            hb.beat()
        return rep

    def _remake_monitor(self):
        """(Re)build the heartbeat monitor over the CURRENT replica count
        — called at construction and after an autoscale grow, so new
        replicas are liveness-checked too."""
        if self._heartbeat_dir is None:
            return
        timeout = (self._heartbeat_timeout
                   if self._heartbeat_timeout is not None
                   else get_flags().get("FLAGS_serving_heartbeat_timeout",
                                        10.0))
        self.monitor = HeartbeatMonitor(self._heartbeat_dir,
                                        world_size=len(self._replicas),
                                        timeout=float(timeout))

    def _spawn_engine(self, rep):
        eng = self._call_factory(rep)
        eng.tag = f"replica{rep.idx}"
        if rep.role != "both":
            # the replica's CURRENT role (configured, or rebalanced after
            # a chip loss): applied while the fresh engine is idle — the
            # only window set_role allows
            eng.set_role(rep.role)
        if self._live_params is not None:
            # the fleet was hot-upgraded: every spawn — crash respawn,
            # rolling restart, autoscale grow — serves the LIVE weights.
            # Only the upgrade itself counts as a weight swap; later
            # re-applications on respawn/grow are not new swaps
            params, version = self._live_params
            eng.swap_params(params, version=version,
                            count=self._upgrading)
        self._sync_adapters(eng)
        if rep.mgr is not None:
            eng.attach_checkpoint(rep.mgr, every=self.snapshot_every)
        return eng

    def _call_factory(self, rep):
        """Invoke the engine factory — one-arg factories receive the
        replica index (the tensor-parallel deployment shape: each replica
        builds its engine on its OWN mp device group, see
        ``mp_replica_meshes``); zero-arg factories keep the PR 7
        contract unchanged. In topology-elastic mode the factory MUST
        take ``(idx, mesh)`` — the mesh changes across reforms, so a
        factory that bakes its own mesh cannot follow the topology."""
        if self._factory_arity is None:
            try:
                import inspect
                sig = inspect.signature(self.engine_factory)
                self._factory_arity = sum(
                    1 for p in sig.parameters.values()
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD))
            except (TypeError, ValueError):
                self._factory_arity = 0
            if self._topology is not None and self._factory_arity < 2:
                raise TypeError(
                    "a topology-elastic supervisor (mp=...) needs a "
                    "two-arg engine factory (replica_idx, mesh): the mesh "
                    "changes when the group re-forms over surviving chips")
        if self._factory_arity >= 2 and self._topology is not None:
            return self.engine_factory(rep.idx, rep.mesh)
        if self._factory_arity >= 1:
            return self.engine_factory(rep.idx)
        return self.engine_factory()

    def _sync_adapters(self, eng):
        """Reconcile an engine's resident adapter set with the fleet's
        LIVE one. A fresh spawn carries nothing and just loads the live
        set; a restored snapshot may PREDATE a fleet-level load/evict/
        swap, so residents the fleet has since evicted are dropped and
        live adapters are re-applied (content rewrite, zero retraces).
        All re-application, never new ops — ``count=False`` keeps the
        ledger counting each fleet-level op exactly once, at apply time.

        An adapter bound to a restored RUNNING slot is left untouched:
        the resumed stream keeps the delta bits it started under (the
        same mid-stream guarantee ``_check_adapter_unbound`` enforces on
        live engines); the next fleet-level op re-syncs it once the
        slot frees."""
        reg = getattr(eng, "adapters", None)
        if reg is None:
            return
        for aid in list(reg.resident_ids()):
            if aid not in self._live_adapters:
                try:
                    eng.evict_adapter(aid, count=False)
                except RuntimeError:
                    pass              # bound mid-stream: keep its bits
        for aid, (tree, alpha) in self._live_adapters.items():
            if reg.resident(aid) and aid != 0:
                try:
                    eng.evict_adapter(aid, count=False)
                except RuntimeError:
                    continue          # bound mid-stream: keep its bits
            eng.load_adapter(aid, tree, alpha=alpha, count=False)

    # -- routing -------------------------------------------------------------
    def _up(self):
        return [r for r in self._replicas if r.state == "up"]

    def _routable(self):
        """Replicas that may receive NEW or replayed work: up and not
        mid-drain (a rolling restart marks the replica "draining" and its
        engine refuses submissions — routing there used to slip through
        because the spill check only compared queue depth)."""
        return [r for r in self._replicas if r.routable]

    def _pick(self, exclude=None):
        ups = [r for r in self._routable() if r is not exclude]
        if not ups:
            return None
        return min(ups, key=lambda r: (r.load, r.idx))

    def _pick_decode(self, exclude=None):
        """Least-loaded routable DECODE-CAPABLE replica (transfer targets
        and in-transfer re-offers must never land on a prefill worker)."""
        ups = [r for r in self._routable()
               if r.role != "prefill" and r is not exclude]
        if not ups:
            return None
        return min(ups, key=lambda r: (r.load, r.idx))

    def _route_disagg(self, request, ups):
        """Role- and cache-aware candidate order for a disaggregated
        fleet. Returns ``(candidates, affinity_rep)``:

        1. PREFIX AFFINITY — when a decode-capable replica's prefix cache
           already covers the prompt to within one page (the engine's
           exact-hit re-forward handles the tail), route STRAIGHT to it:
           the prefill compute AND the transfer are skipped entirely.
           Best coverage wins; load breaks ties.
        2. Sub-page prompts (nothing cached anywhere) also go straight to
           a decode worker — a one-page handoff costs more than the one
           chunk it saves — but are NOT counted as affinity hits.
        3. Otherwise prefill workers by load (the handoff pipeline), then
           decode-capable replicas as spill.
        4. No routable prefill worker at all -> pure-decode fallback (the
           decode-capable replicas chunk-prefill locally, exactly like a
           plain fleet) — counted, it signals degraded disaggregation."""
        prefills = [r for r in ups if r.role == "prefill"]
        decodes = [r for r in ups if r.role != "prefill"]
        affinity = None
        short = False
        if self._affinity_routing and decodes:
            plen = request.prompt_len
            best = None
            for r in decodes:
                eng = r.engine
                if eng is None or eng.kv_layout != "paged":
                    continue
                cov = eng.prefix_coverage(request.prompt)
                if cov > 0 and plen - cov <= eng.page_size:
                    key = (-cov, r.load, r.idx)
                    if best is None or key < best[0]:
                        best = (key, r)
                elif plen <= eng.page_size:
                    short = True
            if best is not None:
                affinity = best[1]
        if affinity is not None:
            order = [affinity] + [r for r in prefills + decodes
                                  if r is not affinity]
        elif short:
            order = sorted(decodes, key=lambda r: (r.load, r.idx)) + prefills
        elif prefills:
            order = prefills + decodes
        else:
            if decodes:
                metrics.bump("disagg_fallbacks")
            order = decodes
        return order, affinity

    def _rate_limit(self, request):
        """Per-tenant token bucket at the router: over-rate submissions
        are refused with ``ShedError`` carrying the exact time until the
        tenant's next token accrues — tenant isolation BEFORE the queues,
        so one tenant's flood cannot fill every replica's queue and starve
        the others into QueueFullError."""
        if self._tenant_rate <= 0:
            return
        with self._lock:
            # bucket creation AND take under the supervisor lock: router
            # threads submit concurrently (the documented concurrency
            # surface), and an unlocked read-modify-write of the token
            # count would let a tenant exceed rate*t + burst
            bucket = self._buckets.get(request.tenant)
            if bucket is None:
                bucket = self._buckets[request.tenant] = TokenBucket(
                    self._tenant_rate, self._tenant_burst)
            wait = bucket.take()
            if len(self._buckets) > 1024:
                # tenant ids are client-supplied strings: without a sweep
                # a rotating/adversarial id stream grows the map forever.
                # A refilled-to-burst bucket is indistinguishable from a
                # fresh one, so dropping it changes no admission decision.
                now = time.perf_counter()
                for t, b in list(self._buckets.items()):
                    if b is not bucket and b.idle_full(now):
                        del self._buckets[t]
        if wait > 0:
            metrics.bump("rate_limited")
            raise ShedError(
                f"tenant {request.tenant!r} over rate limit "
                f"({self._tenant_rate:.1f} req/s, burst "
                f"{self._tenant_burst:.0f}); retry in ~{wait:.2f}s",
                qsize=self.fleet_queue_depth(),
                max_queue=self.fleet_max_queue(), retry_after=wait)

    def fleet_queue_depth(self):
        # single read of rep.engine per replica: a reform on the
        # supervising thread nulls it concurrently with router threads
        engines = [r.engine for r in self._replicas]
        return sum(e.queue_depth for e in engines if e is not None)

    def fleet_max_queue(self):
        engines = [r.engine for r in self._routable()]
        return sum(e.scheduler.max_queue for e in engines if e is not None)

    def _reform_hint(self):
        """retry-after estimate while the fleet is mid-reform: the last
        observed reform latency (elastic ledger), floored/capped to a
        sane backoff window. None when nothing is reforming."""
        reforming = False
        for r in self._replicas:
            eng = r.engine            # single read (reform race, above)
            if r.state == "reforming" or (eng is not None
                                          and eng._reforming):
                reforming = True
                break
        if not reforming:
            return None
        return self._last_reform_latency()

    def submit(self, request):
        """Route a request to the least-loaded routable replica (spilling
        to the next when its queue is full; ``QueueFullError`` — with
        FLEET-WIDE ``qsize``/``max_queue`` totals as its back-off hints —
        only once EVERY replica is saturated). Draining/stopped replicas
        are never targeted; a replica MID-REFORM is temporarily
        unroutable, not dead — with every replica reforming the router
        backs off (bounded retries with a deterministic per-request
        jitter) and only then raises ``EngineStoppedError`` with
        ``reforming=True`` and a ``retry_after`` hint. Raises plain
        ``EngineStoppedError`` when the fleet is genuinely dead,
        ``ShedError`` when the tenant is over its rate limit."""
        if not isinstance(request, Request):
            request = Request(request)
        for attempt in range(self._reform_retries + 1):
            ups = sorted(self._routable(), key=lambda r: (r.load, r.idx))
            if ups:
                break
            hint = self._reform_hint()
            if hint is None:
                raise EngineStoppedError(
                    "no live serving replica", queue_depth=0, requeued=())
            if attempt >= self._reform_retries:
                raise EngineStoppedError(
                    f"every replica is mid-reform (chip loss/return); "
                    f"retry in ~{hint:.2f}s", queue_depth=0, requeued=(),
                    reforming=True, retry_after=hint)
            # bounded jittered backoff: deterministic per request (id-
            # derived jitter in [0.5, 1.0)), so a thundering herd of
            # routers desynchronizes without wall-clock randomness
            time.sleep(min(hint, 0.25)
                       * (0.5 + (request.request_id % 8) / 16.0))
        self._rate_limit(request)
        affinity = None
        if self._disagg:
            ups, affinity = self._route_disagg(request, ups)
        shedding = []
        stopped_midway = 0
        for rep in ups:
            # saturation probes, not trial submits: a failed Engine.submit
            # bumps the global submitted/rejected/shed ledger, so spilling
            # by try/except would count one logical request once per full
            # (or shed-latched) replica and skew the SLO surface. Shed
            # state is PER-ENGINE — a replica latched in overload is
            # skipped and the request spills to a healthy one.
            eng = rep.engine
            if eng is None:
                # a reform nulled the engine after the routable snapshot
                # (router threads vs the supervising thread): temporarily
                # unroutable, same as a mid-reform stop
                stopped_midway += 1
                continue
            shed = eng._shed
            if shed is not None and shed.shedding \
                    and request.class_rank >= 2:
                shedding.append(eng)    # the engine object: reform-safe
                continue
            if eng.queue_depth < eng.scheduler.max_queue:
                rid = request.request_id
                # register ownership BEFORE the engine accepts the work: a
                # group reform landing between a successful submit and a
                # later owner-map write could not see this request in
                # _unacked_of and would restore a snapshot predating it —
                # owned by nobody, hosted by nobody, pending forever
                with self._lock:
                    self._requests[rid] = request
                    self._owner[rid] = rep.idx
                try:
                    eng.submit(request)
                except EngineStoppedError:
                    # stopped between the probe and the submit (a reform/
                    # drain on another thread): temporarily unroutable,
                    # not dead — spill to the next candidate. Undo only
                    # OUR registration: a reform that already saw it has
                    # replayed a copy and re-homed the maps, and that
                    # copy IS the routed request.
                    with self._lock:
                        rerouted = not (
                            self._requests.get(rid) is request
                            and self._owner.get(rid) == rep.idx)
                        if not rerouted:
                            del self._requests[rid]
                            del self._owner[rid]
                    if rerouted:
                        break
                    stopped_midway += 1
                    continue
                except BaseException:
                    with self._lock:
                        if self._requests.get(rid) is request \
                                and self._owner.get(rid) == rep.idx:
                            del self._requests[rid]
                            del self._owner[rid]
                    raise
                break
        else:
            if ups and stopped_midway == len(ups):
                # EVERY candidate stopped between the routable() snapshot
                # and its submit (the fleet went mid-reform under us):
                # surface the typed temporary error, never a bogus
                # saturation hint computed from now-empty queues
                hint = self._reform_hint()
                if hint is not None:
                    raise EngineStoppedError(
                        f"every replica went mid-reform while routing; "
                        f"retry in ~{hint:.2f}s", queue_depth=0,
                        requeued=(), reforming=True, retry_after=hint)
                raise EngineStoppedError(
                    "no live serving replica", queue_depth=0, requeued=())
            # fleet-wide totals: the backoff a client derives from the
            # hint must reflect every queue it competes with, not whatever
            # replica happened to be probed last
            qsize, cap = self.fleet_queue_depth(), self.fleet_max_queue()
            if shedding:
                # every candidate was latched or full: refuse with the
                # largest (most honest) drain hint across latched replicas
                metrics.bump("shed")
                raise ShedError(
                    f"shedding {request.priority} traffic fleet-wide "
                    f"({qsize}/{cap} waiting); retry later",
                    qsize=qsize, max_queue=cap,
                    retry_after=max(
                        e._shed.retry_after(e.queue_depth)
                        for e in shedding))
            # the hint must not claim pure saturation when part of the
            # fleet is actually mid-reform and about to come back
            reform_note = (f"; {stopped_midway} replica(s) mid-reform"
                           if stopped_midway else "")
            raise QueueFullError(
                f"all {len(ups) - stopped_midway} routable replica queues "
                f"full ({qsize}/{cap} waiting fleet-wide{reform_note}); "
                f"retry later", qsize=qsize, max_queue=cap)
        if affinity is not None and rep is affinity:
            # the shared-prefix prompt landed on the replica that already
            # holds its pages: no prefill-worker compute, no KV transfer
            metrics.bump("affinity_hits")
            if request.trace is not None:
                request.trace.instant("affinity_route", replica=rep.idx)
        return request

    def _acked(self, rid):
        with self._lock:
            return rid in self._results or rid in self._delivered

    def cancel(self, request):
        """Cancel wherever the request currently lives (race-safe against
        drain/replay: a request caught between the two resolves as
        cancelled here — delivering its result immediately — and is
        skipped by any later requeue)."""
        rid = request.request_id
        if self._acked(rid):
            return
        with self._lock:
            live = self._requests.get(rid, request)
            owner = self._owner.get(rid)
            tr = self._transfers.get(rid) if self._disagg else None
        if tr is not None and live.state == RUNNING and live.slot is None:
            # cancelled MID-TRANSFER: the request occupies no slot on any
            # engine (the prefill worker freed or never finished its slot,
            # the decode worker has not seated it) — abort the stream and
            # route the cancel to the install side so staged pages return
            tr.aborted = True
            tgt = self._assign.get(rid)
            teng = (self._replicas[tgt].engine
                    if tgt is not None else None)
            if teng is not None and teng.has_transfer(rid):
                teng.cancel(live)
                return
            live._finish(CANCELLED)
            metrics.bump("cancelled")
            with self._lock:
                self._results[rid] = live.result()
            return
        rep = self._replicas[owner] if owner is not None else None
        # single read of .engine: a group reform (or crash failover) on
        # the supervising thread nulls it between a state check and the
        # dereference — fall through to the direct resolve instead
        eng = rep.engine if rep is not None and rep.state == "up" else None
        if eng is not None:
            eng.cancel(live)
        elif live.state != FINISHED:
            # owner down / mid-replay: resolve directly so pending() drains
            live._finish(CANCELLED)
            metrics.bump("cancelled")
            with self._lock:
                self._results[rid] = live.result()

    # -- the supervision loop ------------------------------------------------
    def step(self):
        """One supervision round: step every live replica one engine
        iteration (heartbeating it), fail over replicas that died or went
        stale, collect results. Returns True while undelivered requests
        remain."""
        if self._topology is not None:
            self._poll_topology()
        for rep in self._replicas:
            if rep.state != "up":
                continue
            try:
                rep.engine.step()
            except (Preemption, Preempted, Exception) as e:  # noqa: BLE001
                # abrupt death: results resolved DURING the dying step are
                # lost with the process (never read from a dead engine) —
                # recovery recomputes them from snapshot/replay
                self._on_failure(rep, e)
            else:
                self._collect(rep)
                if rep.hb is not None:
                    try:
                        rep.hb.beat(step=rep.engine._step_count)
                    except OSError:
                        # transient heartbeat-file IO is NOT engine death:
                        # the file just ages, and only the monitor's
                        # staleness timeout may eventually fail this
                        # replica over — don't burn its restart budget
                        pass
        if self.monitor is not None:
            for rank in self.monitor.failed_ranks():
                rep = self._replicas[rank]
                if rep.state == "up":
                    metrics.bump("stale_failovers")
                    self._on_failure(rep, RuntimeError(
                        f"stale heartbeat (replica {rank})"))
        if self._disagg:
            self._pump_transfers()
            self._rebalance_roles()
        if self.autoscaler is not None:
            self._autoscale_step()
        return self.pending() > 0

    # -- disaggregated prefill/decode: transfer routing, role balance --------
    def _pump_transfers(self):
        """One transfer-routing round: collect streams the prefill
        workers opened since last round, assign each to the least-loaded
        decode-capable replica, and reconcile every in-flight stream —
        seated streams flip ownership and drop, aborted ones drop,
        failed ones replay, orphaned ones (their target died mid-install)
        re-offer the RETAINED host payloads to a survivor."""
        for rep in self._replicas:
            eng = rep.engine
            if rep.state != "up" or eng is None or eng.role != "prefill":
                continue
            for tr in eng.take_outbound():
                rid = tr.request_id
                with self._lock:
                    self._transfers[rid] = tr
                self._transfer_src[rid] = rep.idx
                target = self._pick_decode()
                if target is not None:
                    target.engine.offer_transfer(tr)
                    self._assign[rid] = target.idx
        for rid, tr in list(self._transfers.items()):
            req = tr.request
            if tr.seated:
                # terminal success: the decode replica hosts the request
                # now — its death replays there, not at the prefill source
                tgt = self._assign.get(rid)
                if tgt is not None:
                    with self._lock:
                        self._owner[rid] = tgt
                self._drop_transfer(rid)
                continue
            if tr.aborted or req.state == FINISHED or self._acked(rid):
                # handled elsewhere (cancel/expire/shed/drain): the normal
                # resolution path owns it — no replay from here
                self._drop_transfer(rid)
                continue
            if tr.failed:
                # the stream is unusable but the request is live (e.g.
                # params_version moved mid-flight): single-version replay
                self._drop_transfer(rid)
                self._replay([rid])
                continue
            tgt = self._assign.get(rid)
            rep_t = self._replicas[tgt] if tgt is not None else None
            if rep_t is None or not rep_t.routable \
                    or rep_t.engine is None \
                    or not rep_t.engine.has_transfer(rid):
                # no target yet, or it died/drained mid-install: the page
                # payloads are retained host-side until seated — re-offer
                # the SAME stream to a survivor (no prompt recompute)
                target = self._pick_decode()
                if target is None:
                    continue          # fleet degraded: retry next round
                target.engine.offer_transfer(tr)
                self._assign[rid] = target.idx
            if tr.done:
                # every page is host-resident and the prefill worker has
                # freed its slot: from here the DECODE side owns delivery
                with self._lock:
                    self._owner[rid] = self._assign[rid]

    def _drop_transfer(self, rid):
        with self._lock:
            self._transfers.pop(rid, None)
        self._assign.pop(rid, None)
        self._transfer_src.pop(rid, None)

    def _drop_transfers_for(self, rep):
        """Reconcile in-flight transfers against replica ``rep`` going
        away (crash, stale heartbeat, chip loss, reform, role flip)."""
        if not self._disagg:
            return
        for rid, tr in list(self._transfers.items()):
            if self._transfer_src.get(rid) == rep.idx and not tr.done:
                # the SOURCE died mid-stream: the remaining pages can
                # never arrive — abort (the decode side returns its
                # staged pages) and let the caller's replay recompute
                tr.aborted = True
                self._drop_transfer(rid)
            elif self._assign.get(rid) == rep.idx:
                # the TARGET died: payloads survive on the host — unassign
                # and let the pump re-offer the stream to a survivor
                self._assign.pop(rid, None)

    def _rebalance_roles(self):
        """Role elasticity under chip loss: decoding must never stall —
        when no decode-capable replica is routable, the least-loaded live
        prefill worker flips to decode (drain + respawn, zero drops); it
        flips back to its configured role once native decode capacity is
        routable again."""
        ups = self._routable()
        decodes = [r for r in ups if r.role != "prefill"]
        prefills = [r for r in ups if r.role == "prefill"]
        if not decodes and prefills:
            rep = min(prefills, key=lambda r: (r.load, r.idx))
            self._set_replica_role(rep, "decode")
            metrics.bump("role_rebalances")
        elif decodes and not prefills:
            conv = [r for r in decodes if r.configured_role == "prefill"]
            native = [r for r in decodes if r.configured_role != "prefill"]
            if conv and native:
                rep = min(conv, key=lambda r: (r.load, r.idx))
                self._set_replica_role(rep, rep.configured_role)
                metrics.bump("role_rebalances")

    def _set_replica_role(self, rep, role):
        """Flip a replica's serving role through a drain (the only safe
        window — set_role refuses non-idle engines): in-flight work
        requeues on the survivors (original arrival kept, zero drops),
        the engine respawns in the new role (builders memoized — zero
        new traces over warm shapes)."""
        if rep.role == role:
            return
        eng = rep.engine
        if eng is None or rep.state != "up":
            rep.role = role          # applied at the next spawn
            return
        self._drop_transfers_for(rep)
        rep.state = "draining"
        drained = eng.drain()
        self._collect(rep)
        rep.role = role
        rep.engine = self._spawn_engine(rep)
        rep.state = "up"
        metrics.bump("respawns")
        if rep.hb is not None:
            rep.hb.beat(status="running")
        for req in drained:
            if req.state == FINISHED:
                continue
            target = self._requeue_target(req, exclude=rep) or rep
            target.engine.requeue(req)
            with self._lock:
                self._owner[req.request_id] = target.idx

    # -- telemetry-driven autoscaling ----------------------------------------
    def _autoscale_step(self):
        """Evaluate the autoscale policy against the live fleet gauges
        (queue depth, slot occupancy, TTFT p99 — the PR 9 surface) and
        apply at most one action. Runs on the supervising thread at a step
        boundary, so growth/shrink can never tear an engine mid-dispatch;
        hysteresis windows and the cooldown live in the policy object.

        Counts LIVE ROUTABLE capacity, not the configured replica count:
        a fleet degraded by a chip loss (groups down or mid-reform) has
        genuinely less capacity, and the policy must see the queue
        pressure against what can actually serve right now."""
        ups = self._routable()
        if not ups:
            return
        action = self.autoscaler.decide(
            alive=len(ups),
            queue_depth=sum(r.engine.queue_depth for r in ups),
            active_slots=sum(r.engine.active_slots for r in ups),
            total_slots=sum(r.engine.num_slots for r in ups),
            ttft_p99=metrics.recent_ttft_p99())
        if action == "grow":
            self._grow_replica()
        elif action == "shrink":
            self._shrink_replica()

    def _grow_replica(self):
        """Scale up: append a fresh replica (same snapshot/heartbeat
        wiring, live weights) and extend the liveness monitor over it.
        A topology-elastic fleet cannot grow past the chip groups it was
        sized for — growth there is the chips RETURNING (grow-back), not
        new replicas."""
        if self._topology is not None \
                and len(self._replicas) >= self._topology.num_replicas:
            return
        rep = self._new_replica(len(self._replicas))
        self._replicas.append(rep)
        self._remake_monitor()
        metrics.bump("scale_ups")

    def _shrink_replica(self):
        """Scale down: drain the least-loaded replica (its in-flight work
        requeued on the survivors with ORIGINAL arrival — the rolling-
        restart machinery, zero drops) and retire the slot. Indices stay
        stable, so owner bookkeeping and heartbeat ranks never shift.

        A topology-elastic fleet never retires a chip group this way:
        _grow_replica cannot re-create one past the topology (retirement
        would be IRREVERSIBLE — healthy chips pinned idle forever), so
        there capacity follows the chips (reform/grow-back), and the
        autoscaler's shrink decision is a no-op."""
        if self._topology is not None:
            return
        ups = self._up()
        if len(ups) <= 1:
            return
        rep = min(ups, key=lambda r: (r.load, -r.idx))
        rep.state = "draining"
        drained = rep.engine.drain()
        self._collect(rep)
        rep.engine = None
        rep.state = "retired"
        if rep.hb is not None:
            rep.hb.beat(status="stopped")
        for req in drained:
            if req.state == FINISHED:
                continue
            target = self._pick()
            if target is None:          # should not happen (len(ups) > 1)
                rep.engine = self._spawn_engine(rep)
                rep.state = "up"
                target = rep
            target.engine.requeue(req)
            with self._lock:
                self._owner[req.request_id] = target.idx
        metrics.bump("scale_downs")

    def _collect(self, rep):
        popped = rep.engine.pop_results()
        failed_audit = ()
        if self._audit_rate > 0.0 and popped:
            failed_audit = self._audit(rep, popped)
            for rid in failed_audit:
                # a mismatched result is NEVER delivered: the request is
                # still unacked and will be recomputed bitwise elsewhere
                popped.pop(rid, None)
        with self._lock:
            for rid, res in popped.items():
                # first result wins: a snapshot-respawned replica recomputes
                # work that was already delivered — recomputation is
                # deterministic, so dropping the duplicate loses nothing
                if not self._acked(rid):
                    self._results[rid] = res
        if failed_audit:
            from ..distributed import integrity as _integrity
            sus = _integrity.sdc_counters().get(
                f"suspicion_replica{rep.idx}", 0)
            if sus >= self._audit_threshold:
                # repeat offender: fail the whole replica over before its
                # corrupted state spreads through the prefix cache — the
                # ordinary respawn path replays everything it still owed
                _integrity.clear_suspicion(rep.idx)
                self._on_failure(rep, AuditFailure(
                    f"replica {rep.idx}: {sus} shadow-audit mismatches "
                    f"(threshold {self._audit_threshold})"))
            else:
                self._replay(failed_audit)

    def _audit_sampled(self, rid):
        """Deterministic per-request sampling decision (stable across
        replays: the same rid always lands on the same side of the
        rate)."""
        import zlib
        u = (zlib.crc32(str(rid).encode()) % 1000000) / 1000000.0
        return u < self._audit_rate

    def _audit(self, rep, popped):
        """Sampled shadow audit: re-run sampled finished GREEDY requests
        through the raw-params ``generate_from_params`` oracle and
        bitwise-compare the token streams (the engine parity contract
        makes any divergence corruption, not noise). Returns the rids
        that failed; their suspicion is charged to ``rep``."""
        if self._audit_ref is None:
            if not self._audit_warned:
                self._audit_warned = True
                import warnings
                warnings.warn(
                    "FLAGS_serving_audit_rate > 0 but no audit_ref="
                    "(params, config) was passed to ReplicatedEngines; "
                    "the shadow audit is disabled")
            return ()
        from ..distributed import integrity as _integrity
        from ..models.generation import generate_from_params
        import numpy as np
        params, config = self._audit_ref
        failed = []
        for rid, res in popped.items():
            if res.finish_reason not in ("stop", "length"):
                continue
            if not self._audit_sampled(rid):
                continue
            with self._lock:
                req = self._requests.get(rid)
            if req is None or getattr(req, "do_sample", False):
                continue            # greedy-only oracle
            prompt = np.asarray(res.prompt).reshape(-1)
            out = generate_from_params(
                params, prompt[None, :].astype(np.int32), config,
                max_new_tokens=req.max_new_tokens, do_sample=False,
                eos_token_id=req.eos_token_id,
                stop_token_ids=req.stop_token_ids)
            expect = [int(t) for t in
                      np.asarray(out._data)[0, len(prompt):].tolist()]
            got = [int(t) for t in res.tokens]
            # prefix compare: a finished row's oracle tail is eos padding;
            # any real corruption flips tokens INSIDE the emitted stream
            ok = bool(got) and got == expect[:len(got)]
            _integrity.note_audit(ok, rep.idx)
            if not ok:
                failed.append(rid)
        return tuple(failed)

    def _on_failure(self, rep, err):
        """Replica death: respawn from its last snapshot when one exists
        (mid-decode requests resume bitwise; anything newer than the
        snapshot is replayed), otherwise replay everything it still owed
        on the surviving replicas. Past ``max_restarts`` the replica stays
        down permanently."""
        rep.state = "down"
        rep.last_error = err
        rep.engine = None
        self._drop_transfers_for(rep)
        unacked = self._unacked_of(rep)
        rep.restarts += 1
        if rep.restarts > self.max_restarts:
            self._replay(unacked)
            return
        self._respawn_from_snapshot(rep, unacked)

    def _unacked_of(self, rep):
        with self._lock:
            return [rid for rid, owner in self._owner.items()
                    if owner == rep.idx and not self._acked(rid)]

    def _respawn_from_snapshot(self, rep, unacked):
        """Shared respawn core (crash failover AND chip-loss reform):
        spawn a fresh engine on the replica's CURRENT mesh, restore its
        last disk snapshot when one loads, reconcile restored work
        against delivery/ownership, replay the remainder. Returns True
        when the snapshot restored."""
        snap = None
        if rep.mgr is not None:
            try:
                snap = rep.mgr.restore(None)   # quarantines corrupt steps
            except Exception:
                snap = None
        eng = self._spawn_engine(rep)
        restored = False
        if snap is not None:
            try:
                eng.load_state_dict(snap)
                restored = True
            except Exception:      # incompatible/stale-format snapshot
                restored = False
        if restored:
            # the snapshot replaced the registry content _spawn_engine
            # just applied — and may predate a fleet-level adapter op;
            # bring the restored set back to the LIVE one
            self._sync_adapters(eng)
        rep.engine = eng
        rep.state = "up"
        metrics.bump("respawns")
        if rep.hb is not None:
            rep.hb.beat(status="running")
        if restored:
            hosted = self._reconcile_restored(rep, eng)
            self._replay([rid for rid in unacked if rid not in hosted],
                         prefer=rep)
        else:
            self._replay(unacked)
        return restored

    def _reconcile_restored(self, rep, eng):
        """Reconcile a restored engine's work against delivery/ownership
        (shared by crash/loss respawn AND grow-back). The snapshot may
        predate request movement: anything already delivered, cancelled,
        or since reassigned to ANOTHER replica (e.g. by a rolling-restart
        drain) must not be recomputed here — cancel is neighbor-stable,
        so the resumed slots stay bitwise intact. Stale results for
        moved/delivered requests are purged (the cancels just minted
        CANCELLED results; a snapshot can also carry pre-save ones):
        _collect must never deliver them ahead of — or instead of — the
        real owner's stream. Returns the rids the engine still hosts."""
        for req in list(eng.live_requests()):
            rid = req.request_id
            if self._acked(rid) or self._owner.get(rid) != rep.idx:
                # hygiene, not a user cancellation: skip the ledger
                eng.cancel(req, count=None)
            else:
                with self._lock:
                    self._requests[rid] = req  # live handle for cancel()
        for rid in list(eng._results):
            if self._acked(rid) or self._owner.get(rid) != rep.idx:
                del eng._results[rid]
        hosted = {r.request_id for r in eng.live_requests()}
        hosted.update(eng._results)
        return hosted

    # -- topology-elastic: chip loss, group reform, grow-back ----------------
    def _poll_topology(self):
        """One chip-liveness round (elastic mode): beat the per-chip
        heartbeats, read the lost-chip set (injected serving schedule +
        stale chips), and reconcile every group against its plan — a
        group that lost a chip re-forms over its survivors at the largest
        viable mp degree; a degraded group whose chips returned grows
        back. Runs BEFORE the replicas step, so a group is marked down
        deterministically at the boundary the loss fires on — the dead
        engine is never stepped past the loss point."""
        topo = self._topology
        step = self._topo_step
        self._topo_step += 1
        topo.beat(step)
        lost = topo.lost_chips(step)
        for rep in self._replicas:
            if rep.state in ("retired", "draining"):
                continue
            hit = any(c in lost for c in rep.group)
            degraded = rep.state != "up" or rep.mp < self._configured_mp
            if not hit and not degraded:
                continue            # healthy full-degree group: no plan
            plan = topo.plan(rep.idx, lost)
            try:
                if rep.state == "up" and hit:
                    self._reform_group(rep, plan, lost)
                elif rep.state in ("down", "reforming") and rep.chip_lost \
                        and plan is not None \
                        and (rep.mp > 0 or self._elastic_grow):
                    # every chip of the group had died (or a prior reform
                    # attempt failed); chips are available now — bring the
                    # group back at whatever degree they support. A failed
                    # reform attempt (mp > 0: it was mid-shrink at a viable
                    # degree) retries regardless of the grow flag; a FULLY
                    # dead group (mp == 0) coming back is a grow-back and
                    # honors FLAGS_serving_elastic_grow=False ("chip
                    # losses are sticky, groups only shrink")
                    if rep.reform_wait > 0:
                        # spaced retry: a persistently-failing spawn/
                        # restore must not cost the healthy groups a full
                        # spawn attempt at EVERY boundary
                        rep.reform_wait -= 1
                    else:
                        self._reform_group(rep, plan, lost)
                elif self._elastic_grow and rep.state == "up" \
                        and plan is not None and plan[0] > rep.mp:
                    self._grow_group(rep, plan)
            except Exception as e:  # noqa: BLE001 — a failed spawn/restore
                # mid-reform must neither kill the supervising loop nor
                # wedge the replica in "reforming": the group goes down,
                # its work replays on the survivors (zero drops), and the
                # resurrect branch above retries it — with a DOUBLING
                # boundary backoff, so a survivor set that can never host
                # the engine does not stall the fleet with per-token
                # spawn attempts
                rep.state = "down"
                rep.engine = None
                rep.chip_lost = True
                rep.last_error = e
                rep.reform_backoff = min(max(1, rep.reform_backoff * 2), 32)
                rep.reform_wait = rep.reform_backoff
                self._drop_transfers_for(rep)
                self._replay(self._unacked_of(rep))
        set_group_gauges(self._replicas, self._configured_mp)

    def _reform_group(self, rep, plan, lost):
        """Chip-loss reform: the group lost at least one chip, so the
        whole replica is down (its device state — sharded weights and KV
        — is gone with the chip). Re-form over the surviving chips at the
        largest viable mp degree and respawn through the MP-PORTABLE
        snapshot path: the pool geometry is global and the gather-only
        schedule is bitwise at every degree, so mid-decode requests
        resume bitwise on the smaller group; anything newer than the
        snapshot (or everything, with no snapshot) replays — zero drops
        either way. Does NOT burn the crash-restart budget: a topology
        event is not an engine fault."""
        t0 = time.perf_counter()
        # a dead group whose chips came back, or the retry of a reform
        # attempt that failed mid-spawn (engine already gone either way)
        returning = rep.state in ("down", "reforming")
        # state flips BEFORE the engine is nulled (same order as
        # _on_failure): a router thread reading state=="up" must never
        # then find engine None mid-dereference
        rep.state = "reforming"
        if not returning:
            dead = [c for c in rep.group if c in lost]
            rep.chip_lost = True
            rep.last_error = ChipLossError(
                f"replica {rep.idx} lost chip(s) {dead} of mp={rep.mp} "
                f"group {list(rep.group)}")
            if rep.engine is not None:
                # late submissions from router threads see a TYPED
                # temporary stop (reforming + retry_after), not a bare
                # dead engine
                rep.engine.stop_for_reform(self._last_reform_latency())
            rep.engine = None
        self._drop_transfers_for(rep)
        unacked = self._unacked_of(rep)
        if plan is None:
            # no home chip survives: the group stays down (degraded to
            # zero capacity) until chips return; its work replays on the
            # surviving groups
            rep.state = "down"
            rep.mp, rep.mesh, rep.group = 0, None, ()
            self._replay(unacked)
            # no record_reform: nothing re-formed — counting this as a
            # group_reform (and clobbering reform_latency_s_last with the
            # microseconds it took to mark the group down) would skew
            # every later retry_after hint and the ladder's latency p99;
            # the loss itself shows in degraded_groups / chips-lost
            return
        prev_mp = rep.mp
        rep.mp, rep.group = plan
        rep.mesh = self._topology.mesh_for(rep.group)
        if returning:
            rep.chip_lost = False
        self._respawn_from_snapshot(rep, unacked)
        rep.reform_wait = rep.reform_backoff = 0   # spawn worked again
        self._mark_reform_hop(rep)
        # "grow" only when the degree actually rose (a fully-dead group
        # coming back): the RETRY of a loss-reform that failed mid-spawn
        # also arrives with returning=True but lands at the same-or-lower
        # degree and must not inflate the grow_backs audit trail
        record_reform("grow" if returning and plan[0] > prev_mp else "loss",
                      time.perf_counter() - t0)

    def _grow_group(self, rep, plan):
        """Grow-back: chips returned (``serving_chip_return_at`` fired /
        heartbeats recovered) and the group can host a higher mp degree
        again. The replica is HEALTHY, so the reform is a live handoff:
        snapshot the running engine in memory (slots intact), rebuild on
        the bigger mesh, restore — zero drops, zero replays, bitwise
        (the mp-portable snapshot contract), and zero new traces: the
        engine builders are memoized per (cfg, mesh, rung), so the
        original topology's executables are still warm."""
        t0 = time.perf_counter()
        eng_old = rep.engine
        rep.state = "reforming"
        # stop FIRST, snapshot second: a router-thread submit landing in
        # eng_old after the snapshot would exist only in the engine about
        # to be discarded (owned but on no engine — a silent drop). Once
        # stopped, late submits get the typed reforming error and spill.
        eng_old.stop_for_reform(self._last_reform_latency())
        state = eng_old.state_dict()      # live, boundary-consistent
        prev = (rep.mp, rep.group, rep.mesh)
        rep.mp, rep.group = plan
        rep.mesh = self._topology.mesh_for(rep.group)
        rep.chip_lost = False
        try:
            eng = self._spawn_engine(rep)
            eng.load_state_dict(state)    # mp-portable: bitwise resume
        except BaseException:
            # a failed grow must not leave the replica claiming the
            # TARGET degree: the retry's prev_mp comparison would
            # misrecord the eventual grow-back as a loss-reform, and
            # gauges would report capacity the group does not have
            rep.mp, rep.group, rep.mesh = prev
            raise
        rep.engine = eng
        rep.state = "up"
        rep.reform_wait = rep.reform_backoff = 0   # spawn worked again
        # the rebuilt engine holds neither the old engine's outbound
        # streams nor its staged install pages: abort unfinished sourced
        # transfers (their slots were requeued by the restore) and
        # unassign inbound ones so the pump re-offers them
        self._drop_transfers_for(rep)
        # same reconciliation as the loss path: the handoff minted FRESH
        # Request objects (from_state), so live handles must be refreshed
        # for cancel() identity-routing; a request cancelled MID-grow
        # (acked directly while the engine was nulled) must not be
        # resurrected and decoded to completion on the grown engine; and
        # a router thread that passed eng_old's stopped check just before
        # stop_for_reform can land its request in eng_old AFTER the state
        # snapshot (submit registers ownership BEFORE the engine accepts,
        # so it is visible here) — anything owned but hosted by neither
        # the snapshot nor a result replays on the grown engine
        hosted = self._reconcile_restored(rep, eng)
        self._replay([rid for rid in self._unacked_of(rep)
                      if rid not in hosted], prefer=rep)
        self._mark_reform_hop(rep)
        record_reform("grow", time.perf_counter() - t0)

    def _last_reform_latency(self):
        from ..distributed.elastic import elastic_counters
        last = elastic_counters().get("reform_latency_s_last", 0.0)
        return min(1.0, max(0.02, 2.0 * last))

    def _mark_reform_hop(self, rep):
        """Traced requests crossing a reform carry a "reform" hop on
        their timeline (like the requeue/replay/restore hops)."""
        if rep.engine is None:
            return
        for req in rep.engine.live_requests():
            if req.trace is not None:
                req.trace.instant("reform", mp=rep.mp,
                                  group=list(rep.group))

    def _replay(self, rids, prefer=None):
        """Resubmit lost requests as fresh copies — same request_id, seed,
        sampling params and ORIGINAL submit_t/deadline — on the preferred
        or least-loaded live replica. Exactness rides on the engine parity
        guarantee: the replayed stream is bitwise the one the dead replica
        would have produced."""
        for rid in rids:
            with self._lock:
                src = self._requests.get(rid)
            if src is None or self._acked(rid):
                continue
            tr = self._transfers.get(rid) if self._disagg else None
            if tr is not None and tr.done \
                    and not (tr.aborted or tr.failed or tr.seated):
                # complete KV stream retained host-side: the pump re-offers
                # it to a surviving decode worker — cheaper than a full
                # prompt recompute, still zero drops
                self._assign.pop(rid, None)
                continue
            if src.state == FINISHED:
                if src.finish_reason == CANCELLED:
                    # cancelled while in flight: its CANCELLED result may
                    # have died with the engine before a collect — deliver
                    # the outcome from the handle so pending() drains
                    with self._lock:
                        self._results[rid] = src.result()
                    continue
                # else: it FINISHED on the dying replica in the very step
                # that crashed (result lost, never collected) — fall
                # through and recompute an exact copy on a survivor
            target = prefer if (prefer is not None and prefer.state == "up") \
                else self._pick()
            if target is None:
                # the whole fleet is gone: resolve terminally so callers
                # driving pending()/run() converge to a visible failure
                # instead of spinning on an undeliverable request
                metrics.bump("dropped")
                src._finish(DROPPED)
                with self._lock:
                    self._results[rid] = src.result()
                continue
            copy = src.replay_copy()
            target.engine.requeue(copy)
            with self._lock:
                self._requests[rid] = copy
                self._owner[rid] = target.idx
            metrics.bump("replayed")

    # -- lifecycle -----------------------------------------------------------
    def _requeue_target(self, req, exclude=None):
        """Requeue target for a drained request: least-loaded routable
        replica, PREFERRING one that serves the weight version the request
        already produced tokens under — during a hot upgrade, in-flight
        work finishes on the version it started on as long as any replica
        of that version survives (only the final drain of the old fleet
        recomputes on the new version, from scratch, so every result is
        single-version consistent either way)."""
        ups = [r for r in self._routable() if r is not exclude]
        if not ups:
            return None
        if req.params_version is not None:
            same = [r for r in ups
                    if r.engine.params_version == req.params_version]
            if same:
                ups = same
        return min(ups, key=lambda r: (r.load, r.idx))

    def rolling_restart(self, absorb_steps=2, new_params=None,
                        params_version=None):
        """Restart the fleet one replica at a time with zero drops: mark
        a replica DRAINING (unroutable — new submissions and replays go
        elsewhere), drain it (in-flight requeued, original arrival kept),
        hand its work to the survivors, respawn it FRESH, then run a few
        supervision rounds so the fleet absorbs before the next drain.

        ``new_params`` turns the restart into a ZERO-DOWNTIME WEIGHT
        UPGRADE: each respawned replica comes back serving the new tree
        (``Engine.swap_params`` — same-shape, builders memoized per
        config, so no retrace), stamped ``params_version`` (default: one
        past the fleet's current version). Snapshots carry the version, so
        a crash-respawn can never resume new-version requests from an
        old-version snapshot's KV (the meta mismatch falls back to replay
        — still zero drops); results carry the version their tokens were
        produced under; and drained in-flight requests prefer surviving
        OLD-version replicas, finishing on the version they started on
        whenever one exists."""
        metrics.bump("rolling_restarts")
        if new_params is not None:
            if params_version is None:
                versions = [r.engine.params_version for r in self._replicas
                            if r.engine is not None]
                params_version = max(versions, default=0) + 1
            self._live_params = (new_params, int(params_version))
            self._upgrading = True
        try:
            for rep in list(self._replicas):
                if rep.state != "up":
                    continue
                rep.state = "draining"  # unroutable while its queue moves
                drained = rep.engine.drain()
                self._collect(rep)
                rep.engine = self._spawn_engine(rep)
                rep.restarts = 0        # a planned restart is not a failure
                rep.state = "up"
                metrics.bump("respawns")
                if rep.hb is not None:
                    rep.hb.beat(status="running")
                for req in drained:
                    if req.state == FINISHED:
                        continue        # cancelled mid-requeue: done already
                    target = self._requeue_target(req, exclude=rep) or rep
                    target.engine.requeue(req)
                    with self._lock:
                        self._owner[req.request_id] = target.idx
                for _ in range(max(0, int(absorb_steps))):
                    self.step()
        finally:
            self._upgrading = False

    # -- many-model serving: fleet-level adapter ops -------------------------
    def _live_adapter_engines(self):
        engines = [r.engine for r in self._replicas
                   if r.state == "up" and r.engine is not None]
        if not engines:
            raise EngineStoppedError("no live serving replica",
                                     queue_depth=0, requeued=())
        return engines

    def load_adapter(self, adapter_id, tree, alpha=None):
        """Hot-load ``adapter_id`` onto every live replica and record it
        in the fleet's LIVE adapter set, so every later spawn — crash
        respawn, chip-loss reform, rolling restart, autoscale grow —
        comes back serving it (a crash never resurrects a stale set, the
        ``_live_params`` discipline). Counted ONCE in the ledger; zero
        retraces and no prefix-cache flush per the engine contract.
        Runs on the supervising thread (like rolling_restart)."""
        engines = self._live_adapter_engines()
        for eng in engines:           # all-or-nothing precheck first
            eng._require_adapters()._check_id(adapter_id)
            eng._check_adapter_unbound(adapter_id, "load over")
        for i, eng in enumerate(engines):
            eng.load_adapter(adapter_id, tree, alpha=alpha, count=(i == 0))
        self._live_adapters[int(adapter_id)] = (tree, alpha)

    def evict_adapter(self, adapter_id):
        """Drop ``adapter_id`` fleet-wide (and from the live set, so
        respawns stay evicted). Refused — before any replica mutates —
        while ANY replica has the adapter bound to a running slot."""
        engines = self._live_adapter_engines()
        for eng in engines:
            eng._require_adapters()
            eng._check_adapter_unbound(adapter_id, "evict")
        for i, eng in enumerate(engines):
            eng.evict_adapter(adapter_id, count=(i == 0))
        self._live_adapters.pop(int(adapter_id), None)

    def swap_adapter(self, adapter_id, tree, alpha=None):
        """Replace a resident adapter's delta fleet-wide, in place (the
        adapter analogue of ``rolling_restart(new_params=)`` — but with
        no drain needed: the unbound precheck is the consistency
        boundary, and the rewrite is content-only with zero retraces)."""
        engines = self._live_adapter_engines()
        for eng in engines:
            eng._require_adapters()
            eng._check_adapter_unbound(adapter_id, "swap")
        for i, eng in enumerate(engines):
            eng.swap_adapter(adapter_id, tree, alpha=alpha, count=(i == 0))
        self._live_adapters[int(adapter_id)] = (tree, alpha)

    def pending(self):
        """Requests submitted but not yet delivered."""
        with self._lock:
            return sum(1 for rid in self._requests if not self._acked(rid))

    def pop_results(self):
        """Drain resolved requests and forget their tracking state (the
        supervisor-level mirror of ``Engine.pop_results`` — an undrained
        long-running supervisor would retain every prompt and token list
        forever). Delivered ids stay in a lightweight seen-set, so a
        replica respawned from a stale snapshot can never re-deliver a
        duplicate after the heavy state is dropped."""
        with self._lock:
            out, self._results = self._results, {}
            for rid in out:
                self._delivered.add(rid)
                self._requests.pop(rid, None)
                self._owner.pop(rid, None)
        return out

    def run(self, requests=None, max_steps=100000):
        """Submit ``requests`` (optional) and supervise until every tracked
        request has a result, then drain: returns {request_id:
        GenerationResult} for everything resolved since the last drain
        (check ``finish_reason`` — a dead-fleet terminal failure surfaces
        as ``DROPPED`` rather than an infinite wait)."""
        if requests is not None:
            for r in requests:
                self.submit(r)
        steps = 0
        while self.pending():
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"supervisor did not converge in {max_steps} rounds "
                    f"({self.pending()} requests still pending)")
        return self.pop_results()

    def shutdown(self):
        """Drain every live replica; returns still-incomplete requests
        (original arrival kept) for hand-off to another fleet."""
        leftovers = []
        for rep in self._replicas:
            if rep.state == "up" and rep.engine is not None:
                leftovers.extend(rep.engine.drain())
                self._collect(rep)
                if rep.hb is not None:
                    rep.hb.beat(status="stopped")
                rep.state = "down"
        if self._disagg:
            # requests caught mid-transfer live on NO engine (the prefill
            # worker freed its slot, the decode worker never seated them):
            # without this they would vanish from the hand-off set
            seen = {r.request_id for r in leftovers}
            for rid, tr in list(self._transfers.items()):
                req = tr.request
                tr.aborted = True
                self._drop_transfer(rid)
                if rid not in seen and not tr.seated \
                        and req.state != FINISHED:
                    req._requeue()
                    leftovers.append(req)
        return leftovers

    # -- introspection -------------------------------------------------------
    @property
    def alive_replicas(self):
        return len(self._up())

    def results(self):
        """Resolved-but-not-yet-popped results (non-draining peek)."""
        with self._lock:
            return dict(self._results)

    def telemetry(self):
        """Live fleet gauges (the registry's "supervisor" family — one
        scrape shows routing pressure and failover history per replica):
        per-replica up/queue-depth/active-slots/restarts plus the
        fleet-level pending count."""
        out = {"replicas": len(self._replicas),
               "alive": len(self._up()),
               "routable": len(self._routable()),
               "pending": self.pending(),
               "params_version": (self._live_params[1]
                                  if self._live_params is not None else 0)}
        if self._disagg:
            with self._lock:
                out["transfers_inflight"] = len(self._transfers)
        if any(getattr(r.engine, "adapters", None) is not None
               for r in self._replicas if r.engine is not None):
            out["adapters_live"] = len(self._live_adapters)
        if self._topology is not None:
            out["configured_mp"] = int(self._configured_mp)
            out["degraded_groups"] = degraded_count(self._replicas,
                                                    self._configured_mp)
        for rep in self._replicas:
            eng = rep.engine
            out[f"replica{rep.idx}"] = {
                "up": int(rep.state == "up"),
                "state": rep.state,
                "role": rep.role,
                "restarts": int(rep.restarts),
                "queue_depth": (0 if eng is None else eng.queue_depth),
                "active_slots": (0 if eng is None else eng.active_slots),
                "step_count": (0 if eng is None else eng._step_count),
                "params_version": (0 if eng is None
                                   else int(eng.params_version)),
            }
            if eng is not None and getattr(eng, "adapters", None) is not None:
                out[f"replica{rep.idx}"]["adapters_resident"] = len(
                    eng.adapters.resident_ids())
            if self._topology is not None:
                out[f"replica{rep.idx}"]["mp"] = int(rep.mp)
                out[f"replica{rep.idx}"]["group"] = list(rep.group)
        return out
