"""Request scheduler for the continuous-batching engine.

FCFS admission at STEP boundaries (Orca-style iteration-level scheduling):
between decode iterations the engine asks the scheduler for requests to
prefill into free slots. The scheduler owns the wait queue (bounded —
`submit` raises `QueueFullError` past `max_queue`, the backpressure signal a
frontend turns into HTTP 429), prefill-bucket selection (prompt padded up to
the smallest configured bucket, so steady state compiles one prefill
executable per bucket, not per length), and per-request deadlines (expired
requests are failed at the boundary instead of wasting a prefill).
"""
from __future__ import annotations

import time
from collections import deque

from .request import EXPIRED, FINISHED, QUEUED


class QueueFullError(RuntimeError):
    """Raised by submit() when the wait queue is at max_queue. Carries
    ``qsize`` (waiting requests at rejection time) and ``max_queue`` so a
    router can back off proportionally (retry-after ~ qsize/max_queue)
    instead of blind-retrying."""

    def __init__(self, message, qsize=None, max_queue=None):
        super().__init__(message)
        self.qsize = qsize
        self.max_queue = max_queue


class Scheduler:
    def __init__(self, buckets, max_queue=256):
        buckets = sorted(int(b) for b in buckets)
        if not buckets:
            raise ValueError("need at least one prefill bucket")
        self.buckets = tuple(buckets)
        self.max_queue = int(max_queue)
        self._q = deque()

    # -- queue ---------------------------------------------------------------
    def submit(self, req):
        if len(self._q) >= self.max_queue:
            raise QueueFullError(
                f"serving queue full ({self.max_queue} waiting); retry later",
                qsize=len(self._q), max_queue=self.max_queue)
        if req.state != QUEUED:
            raise ValueError(f"request {req.request_id} already "
                             f"{req.state}; requests are single-use")
        if req.submit_t is None:
            # first submission stamps the arrival clock; a drained/replayed
            # request keeps its ORIGINAL submit_t (and therefore deadline —
            # preemption must not grant a fresh one, and TTFT counts from
            # first submission)
            req.submit_t = time.perf_counter()
        self._q.append(req)

    def requeue(self, req):
        """Return a previously-admitted (drained/preempted) request to the
        wait queue at its ARRIVAL position: inserted before any request
        that was submitted later, so global FCFS order is preserved across
        a drain. ``max_queue`` is intentionally bypassed — the request was
        already accepted once and dropping it now would break the
        zero-requests-dropped drain guarantee. Race-safe against cancel: a
        request resolved while it was in flight between ``drain`` and this
        call is skipped (returns False)."""
        if req.state == FINISHED:
            return False              # cancelled mid-requeue: nothing to do
        req.state = QUEUED
        req.slot = None
        t = req.submit_t if req.submit_t is not None else float("-inf")
        idx = len(self._q)
        for i, other in enumerate(self._q):
            if other.submit_t is not None and other.submit_t > t:
                idx = i
                break
        self._q.insert(idx, req)
        return True

    def cancel(self, req):
        """Remove a still-queued request; returns True if it was waiting."""
        try:
            self._q.remove(req)
            return True
        except ValueError:
            return False

    def qsize(self):
        return len(self._q)

    # -- bucket selection ----------------------------------------------------
    def bucket_for(self, prompt_len):
        """Smallest configured bucket >= prompt_len."""
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds largest prefill bucket "
            f"{self.buckets[-1]}")

    # -- expiry --------------------------------------------------------------
    def expire(self, now=None):
        """Remove and return every queued request whose deadline passed —
        called at EVERY step boundary (not just when a slot frees), so dead
        entries never inflate qsize()/backpressure while all slots are busy.
        Returned requests are already marked EXPIRED."""
        now = time.perf_counter() if now is None else now
        expired = [r for r in self._q if r.state != FINISHED
                   and r.deadline is not None and now > r.deadline]
        for req in expired:
            self._q.remove(req)
            req._finish(EXPIRED)
        return expired

    # -- admission -----------------------------------------------------------
    def admit(self, free_slots, now=None, fits=None):
        """Pop up to free_slots admissible requests FCFS. Requests whose
        deadline already passed are popped, marked EXPIRED and returned
        separately (they never occupy a slot).

        ``fits`` is the paged engine's page-aware admission predicate: the
        queue head is admitted only when the page pool can hold its whole
        lifetime (prompt + max_new_tokens, minus prefix-shared pages) —
        admission is bounded by PAGES, not whole-Smax slots. A head that
        doesn't fit STOPS admission (strict FCFS — no head-of-line bypass,
        so admission order stays deterministic and starvation-free)."""
        now = time.perf_counter() if now is None else now
        admitted, expired = [], []
        while self._q and len(admitted) < free_slots:
            req = self._q[0]
            if req.state == FINISHED:
                # cancelled while queued (e.g. mid-requeue race where the
                # cancel lost the deque.remove): already resolved, skip
                self._q.popleft()
                continue
            dl = req.deadline
            if dl is not None and now > dl:
                self._q.popleft()
                req._finish(EXPIRED)
                expired.append(req)
                continue
            if fits is not None and not fits(req):
                break
            self._q.popleft()
            admitted.append(req)
        return admitted, expired

    # -- snapshot ------------------------------------------------------------
    def drain_queue(self):
        """Pop and return every waiting request (engine drain/shutdown
        path); their ``submit_t`` is untouched so a resubmission elsewhere
        keeps the original arrival clock."""
        out = [r for r in self._q if r.state != FINISHED]
        self._q.clear()
        return out

    def queue_state(self):
        """Serializable snapshot of the wait queue (FCFS order)."""
        return [r.to_state() for r in self._q if r.state != FINISHED]

    def restore_queue(self, reqs):
        """Replace the wait queue with ``reqs`` (engine restore path)."""
        self._q = deque(reqs)
