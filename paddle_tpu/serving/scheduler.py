"""Request scheduler for the continuous-batching engine.

FCFS admission at STEP boundaries (Orca-style iteration-level scheduling):
between decode iterations the engine asks the scheduler for requests to
prefill into free slots. The scheduler owns the wait queue (bounded —
`submit` raises `QueueFullError` past `max_queue`, the backpressure signal a
frontend turns into HTTP 429), prefill-bucket selection (prompt padded up to
the smallest configured bucket, so steady state compiles one prefill
executable per bucket, not per length), and per-request deadlines (expired
requests are failed at the boundary instead of wasting a prefill).
"""
from __future__ import annotations

import time
from collections import deque

from .request import EXPIRED, FINISHED, QUEUED, SHED


class QueueFullError(RuntimeError):
    """Raised by submit() when the wait queue is at max_queue. Carries
    ``qsize`` (waiting requests at rejection time) and ``max_queue`` so a
    router can back off proportionally (retry-after ~ qsize/max_queue)
    instead of blind-retrying. At the supervisor both fields are
    FLEET-WIDE totals (every replica's waiting requests / capacity), so
    the hint reflects the traffic the client actually competes with."""

    def __init__(self, message, qsize=None, max_queue=None):
        super().__init__(message)
        self.qsize = qsize
        self.max_queue = max_queue


class ShedError(QueueFullError):
    """Load shedding refused this request: the fleet is in sustained
    overload and the request's class is being shed. Shares the
    ``qsize``/``max_queue`` backpressure fields with ``QueueFullError``
    (so existing 429 handlers catch both) and adds ``retry_after`` —
    seconds until the shed backlog should have drained, derived from the
    LIVE queue-drain rate rather than a blind exponential backoff."""

    def __init__(self, message, qsize=None, max_queue=None,
                 retry_after=None):
        super().__init__(message, qsize=qsize, max_queue=max_queue)
        self.retry_after = retry_after


class Scheduler:
    """``priority=False`` (default) is strict FCFS — byte-identical to the
    pre-SLO scheduler the parity suites gate. ``priority=True`` makes
    admission class-aware (serving/slo.py): best class first, and within a
    class weighted fair queueing across tenants (deficit round-robin over
    per-tenant FCFS lanes, ``tenant_weights`` credits per rotation) so one
    tenant's burst cannot starve another's trickle. The wait queue itself
    stays ONE arrival-ordered deque either way: snapshots, drains,
    requeue-at-original-arrival and cancel races are order-agnostic and
    shared between both modes — priority is a pure admission-order policy
    computed at the boundary.

    ``lane_key`` generalizes the WFQ lane axis: the default (None) lanes
    by ``r.tenant``; an adapter-serving engine passes ``lambda r:
    r.adapter or 0`` so fairness rotates across ADAPTERS — one hot
    fine-tune's burst cannot starve the other models sharing the engine.
    ``tenant_weights`` keys by whatever the lane key returns."""

    def __init__(self, buckets, max_queue=256, priority=False,
                 tenant_weights=None, lane_key=None):
        buckets = sorted(int(b) for b in buckets)
        if not buckets:
            raise ValueError("need at least one prefill bucket")
        self.buckets = tuple(buckets)
        self.max_queue = int(max_queue)
        self.priority = bool(priority)
        # weights clamp to >= 1: a zero credit would starve the tenant's
        # lane AND stall the WFQ rotation that expects every pass to drain
        self.tenant_weights = {str(t): max(1, int(w))
                               for t, w in (tenant_weights or {}).items()}
        self.lane_key = (lambda r: r.tenant) if lane_key is None else lane_key
        self._wfq_last = {}            # class rank -> last-served lane
        self._q = deque()

    def set_tenant_weight(self, tenant, weight):
        """WFQ credit per rotation for ``tenant`` (default 1): a weight-2
        tenant is served two requests per round-robin pass."""
        self.tenant_weights[str(tenant)] = max(1, int(weight))

    # -- queue ---------------------------------------------------------------
    def submit(self, req):
        if len(self._q) >= self.max_queue:
            raise QueueFullError(
                f"serving queue full ({self.max_queue} waiting); retry later",
                qsize=len(self._q), max_queue=self.max_queue)
        if req.state != QUEUED:
            raise ValueError(f"request {req.request_id} already "
                             f"{req.state}; requests are single-use")
        if req.submit_t is None:
            # first submission stamps the arrival clock; a drained/replayed
            # request keeps its ORIGINAL submit_t (and therefore deadline —
            # preemption must not grant a fresh one, and TTFT counts from
            # first submission)
            req.submit_t = time.perf_counter()
        self._q.append(req)

    def requeue(self, req):
        """Return a previously-admitted (drained/preempted) request to the
        wait queue at its ARRIVAL position: inserted before any request
        that was submitted later, so global FCFS order is preserved across
        a drain. ``max_queue`` is intentionally bypassed — the request was
        already accepted once and dropping it now would break the
        zero-requests-dropped drain guarantee. Race-safe against cancel: a
        request resolved while it was in flight between ``drain`` and this
        call is skipped (returns False)."""
        if req.state == FINISHED:
            return False              # cancelled mid-requeue: nothing to do
        req.state = QUEUED
        req.slot = None
        t = req.submit_t if req.submit_t is not None else float("-inf")
        idx = len(self._q)
        for i, other in enumerate(self._q):
            if other.submit_t is not None and other.submit_t > t:
                idx = i
                break
        self._q.insert(idx, req)
        return True

    def cancel(self, req):
        """Remove a still-queued request; returns True if it was waiting."""
        try:
            self._q.remove(req)
            return True
        except ValueError:
            return False

    def qsize(self):
        return len(self._q)

    # -- bucket selection ----------------------------------------------------
    def bucket_for(self, prompt_len):
        """Smallest configured bucket >= prompt_len."""
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds largest prefill bucket "
            f"{self.buckets[-1]}")

    # -- expiry --------------------------------------------------------------
    def expire(self, now=None):
        """Remove and return every queued request whose deadline passed —
        called at EVERY step boundary (not just when a slot frees), so dead
        entries never inflate qsize()/backpressure while all slots are busy.
        Returned requests are already marked EXPIRED. Boundary semantics
        are ``Request.expired`` (``now >= deadline``) — the single
        predicate every expiry site shares."""
        now = time.perf_counter() if now is None else now
        expired = [r for r in self._q if r.state != FINISHED
                   and r.expired(now)]
        for req in expired:
            self._q.remove(req)
            req._finish(EXPIRED)
        return expired

    # -- admission -----------------------------------------------------------
    def _admission_order(self):
        """Live queued requests in admission order. FCFS mode returns the
        arrival order verbatim; priority mode orders best class first and,
        within a class, deficit-round-robins across tenants (arrival order
        within each tenant's lane). The rotation resumes after the
        class's last-served tenant, so fairness holds across boundaries,
        not just within one."""
        live = [r for r in self._q if r.state != FINISHED]
        if not self.priority or len(live) <= 1:
            return live
        by_class = {}
        for r in live:
            by_class.setdefault(r.class_rank, []).append(r)
        out = []
        for rank in sorted(by_class):
            out.extend(self._wfq_order(rank, by_class[rank]))
        return out

    def _wfq_order(self, rank, reqs):
        """Weighted fair order across lanes (tenants, or adapters under
        ``lane_key``) within one class."""
        lanes, keys = {}, []
        for r in reqs:                     # arrival order within each lane
            k = self.lane_key(r)
            if k not in lanes:
                keys.append(k)
                lanes[k] = deque()
            lanes[k].append(r)
        if len(keys) <= 1:
            return reqs
        last = self._wfq_last.get(rank)
        if last in keys:                   # resume AFTER the last-served
            i = keys.index(last) + 1
            keys = keys[i:] + keys[:i]
        out = []
        while lanes:
            for t in keys:
                lane = lanes.get(t)
                if lane is None:
                    continue
                # weights keyed by the lane key; non-string keys (adapter
                # ids) fall back to their string spelling so flag-file
                # weights ({"1": 2}) apply to integer lanes too
                w = self.tenant_weights.get(
                    t, self.tenant_weights.get(str(t), 1))
                for _ in range(w):
                    if not lane:
                        break
                    out.append(lane.popleft())
                if not lane:
                    del lanes[t]
        return out

    def admit(self, free_slots, now=None, fits=None):
        """Pop up to free_slots admissible requests in admission order
        (FCFS, or class-aware WFQ under ``priority``). Requests whose
        deadline already passed are popped, marked EXPIRED and returned
        separately (they never occupy a slot).

        ``fits`` is the paged engine's page-aware admission predicate: a
        candidate is admitted only when the page pool can hold its whole
        lifetime (prompt + max_new_tokens, minus prefix-shared pages) —
        admission is bounded by PAGES, not whole-Smax slots. A candidate
        that doesn't fit STOPS admission (strict in-order — no bypass, so
        admission order stays deterministic and starvation-free; in
        priority mode a stuck interactive head blocks batch behind it
        rather than inverting priority)."""
        now = time.perf_counter() if now is None else now
        admitted, expired = [], []
        if free_slots > 0:
            for req in self._admission_order():
                if len(admitted) >= free_slots:
                    break
                if req.expired(now):
                    self._q.remove(req)
                    req._finish(EXPIRED)
                    expired.append(req)
                    continue
                if fits is not None and not fits(req):
                    break
                self._q.remove(req)
                admitted.append(req)
                if self.priority:
                    self._wfq_last[req.class_rank] = self.lane_key(req)
        while self._q and self._q[0].state == FINISHED:
            # cancelled while queued (e.g. mid-requeue race where the
            # cancel lost the deque.remove): already resolved, drop
            self._q.popleft()
        return admitted, expired

    # -- SLO policy hooks (priority / shedding) ------------------------------
    def deadline_risk(self, now, margin):
        """The queued request most entitled to preempt: unexpired, has a
        deadline, and its slack (deadline - now) is within ``margin`` —
        i.e. it will miss its deadline unless it is admitted about now.
        Best class wins; earliest arrival breaks ties. None when nothing
        is at risk."""
        best = None
        for r in self._q:
            if r.state == FINISHED or r.deadline is None or r.expired(now):
                continue
            if r.deadline - now <= margin:
                key = (r.class_rank, r.submit_t if r.submit_t is not None
                       else float("inf"))
                if best is None or key < best[0]:
                    best = (key, r)
        return None if best is None else best[1]

    def shed(self, target_len, spare_rank=0):
        """Shed queued work down to ``target_len`` live entries, lowest
        class first and youngest arrival first within a class (the request
        that would have been served LAST goes first — the oldest, best
        work keeps its place). Requests of class rank <= ``spare_rank``
        are never shed (interactive degrades via deadlines, not drops).
        Shed requests are marked ``SHED`` and returned; the caller
        attaches the retry-after hint and resolves them."""
        live = [r for r in self._q if r.state != FINISHED]
        excess = len(live) - max(0, int(target_len))
        if excess <= 0:
            return []
        victims = sorted(
            (r for r in live if r.class_rank > spare_rank),
            key=lambda r: (-r.class_rank,
                           -(r.submit_t if r.submit_t is not None else 0.0)))
        shed = victims[:excess]
        for req in shed:
            self._q.remove(req)
            req._finish(SHED)
        return shed

    # -- snapshot ------------------------------------------------------------
    def drain_queue(self):
        """Pop and return every waiting request (engine drain/shutdown
        path); their ``submit_t`` is untouched so a resubmission elsewhere
        keeps the original arrival clock."""
        out = [r for r in self._q if r.state != FINISHED]
        self._q.clear()
        return out

    def queue_state(self):
        """Serializable snapshot of the wait queue (FCFS order)."""
        return [r.to_state() for r in self._q if r.state != FINISHED]

    def restore_queue(self, reqs):
        """Replace the wait queue with ``reqs`` (engine restore path)."""
        self._q = deque(reqs)
