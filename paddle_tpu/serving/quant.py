"""Serving-side quantization: int8/fp8 weight-only GEMMs and the
quantized paged KV pool, calibrated through ``paddle_tpu.quantization``.

Two independent dtype axes, both default-OFF
(``FLAGS_serving_weight_dtype`` / ``FLAGS_serving_kv_dtype`` = "bf16" =
today's full-precision bitwise-exact path, untouched):

* **weights** — per-OUTPUT-CHANNEL symmetric scales computed at engine
  build (absmax of each output column) or imported from a PTQ
  calibration (``calibrate()``). The stored leaves become int8/fp8 with a
  float32 ``<name>_s`` scale companion; the dequant multiply rides the
  GEMM epilogue (``ops.pallas_kernels.quant_gemm`` on TPU, the same
  ``(x @ q.astype(dt)) * s`` algebra as a jnp fallback elsewhere), so no
  fp weight copy is ever materialized — including the mp rungs, where
  the int8 shard feeds ``fused_gemm_ag``'s epilogue directly and scales
  shard with their channels.
* **KV** — per-PAGE scales stored host-side beside the page table
  (``PagedKVPool.k_scale``/``v_scale``, uploaded as traced operands like
  the table itself): pages are the natural quantization block — CoW
  copies, prefix sharing and the trash-page masking all move quantized
  bytes and their scale entries together. Writes quantize in
  ``paged_kv_scatter``; dequant happens inside the paged-decode Pallas
  kernel's online-softmax loop and in the pure-jnp gather fallback. The
  scale VALUES come from per-layer |K|/|V| clip ranges: a PTQ
  calibration over a token sample (``calibrate``/``kv_ranges``), or an
  automatic one-forward calibration at engine build.

Exactness contract: "bitwise-exact" moves to "exact at a given dtype
config" — a quantized engine is still admission-order invariant,
kill-and-resume bitwise, and mp∈{2,4} output is bitwise identical to the
single-chip QUANTIZED output (per-channel quantization commutes with
column sharding; the gather-only schedule moves bytes, never math). The
bf16/bf16 config stays bitwise identical to the unquantized engine
because none of this code runs.
"""
from __future__ import annotations

from dataclasses import dataclass, replace

import numpy as np
import jax
import jax.numpy as jnp

DTYPES = ("bf16", "int8", "fp8")
# serving storage dtype + symmetric max per quantized dtype ("bf16" means
# "leave at full precision" — the serving fp path never actually casts)
STORE_DTYPES = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}
QMAX = {"int8": 127.0, "fp8": 448.0}
# block-stacked matmul leaves that quantize (scale shape [L, out]);
# head_w quantizes too (scale [V]). Embeddings/norms/biases stay fp —
# the GEMM weights are where the HBM lives.
BLOCK_WEIGHTS = ("qkv_w", "out_w", "up_w", "down_w")


class QuantSpecError(ValueError):
    """A QuantSpec that cannot serve these params/config — unknown dtype,
    or calibrated scale/clip shapes that don't match the tree (the error
    names the offending leaf)."""


class QuantDtypeMismatchError(ValueError):
    """Snapshot dtype config != restoring engine's dtype config. Restoring
    quantized KV bytes into a pool of another dtype would deserialize
    garbage; the refusal names BOTH configs so the operator can rebuild
    the engine (or pick the right snapshot) instead of debugging NaNs."""

    def __init__(self, snap, mine):
        self.snapshot_config = tuple(snap)
        self.engine_config = tuple(mine)
        super().__init__(
            f"snapshot was taken at dtype config weight={snap[0]}/"
            f"kv={snap[1]} but this engine serves weight={mine[0]}/"
            f"kv={mine[1]}; build the restoring Engine with the snapshot's "
            f"quant config (quantized KV bytes do not reinterpret)")


@dataclass
class QuantSpec:
    """Static serving-quantization config + optional calibrated artifacts.

    ``weight_scales`` (optional) pins per-output-channel fp32 scales from
    a PTQ calibration: ``{"blocks": {leaf: [L, out]}, "head_w": [V]}`` on
    the LOGICAL qkv layout (the mp engine permutes qkv columns head-major
    together with the weights). ``kv_k_clip``/``kv_v_clip`` are per-layer
    symmetric |K|/|V| clip ranges ([L] float); the engine divides by its
    kv dtype's qmax to get the per-page scales. Leave them None to let
    the engine auto-calibrate (weights: absmax at build; KV: one fp
    forward over a deterministic token sample)."""

    weight_dtype: str = "bf16"
    kv_dtype: str = "bf16"
    weight_scales: dict | None = None
    kv_k_clip: np.ndarray | None = None
    kv_v_clip: np.ndarray | None = None

    def __post_init__(self):
        for name, d in (("weight_dtype", self.weight_dtype),
                        ("kv_dtype", self.kv_dtype)):
            if d not in DTYPES:
                raise QuantSpecError(
                    f"{name} must be one of {DTYPES}, got {d!r}")

    @property
    def active(self):
        return self.weight_dtype != "bf16" or self.kv_dtype != "bf16"

    @property
    def quantizes_weights(self):
        return self.weight_dtype != "bf16"

    @property
    def quantizes_kv(self):
        return self.kv_dtype != "bf16"

    def key(self):
        """Hashable static key for the memoized executable builders."""
        return (self.weight_dtype, self.kv_dtype)


def resolve(quant, flags):
    """Normalize the Engine's ``quant=`` argument: a QuantSpec passes
    through, a dtype string ("int8"/"fp8") quantizes both axes, and None
    reads ``FLAGS_serving_weight_dtype``/``FLAGS_serving_kv_dtype``.
    Returns None when the resolved config is the full-precision bf16/bf16
    path — the engine then runs byte-identical to the unquantized code."""
    if isinstance(quant, QuantSpec):
        return quant if quant.active else None
    if isinstance(quant, str):
        spec = QuantSpec(weight_dtype=quant, kv_dtype=quant)
        return spec if spec.active else None
    if quant is not None:
        raise QuantSpecError(
            f"quant= must be a QuantSpec, a dtype string or None, got "
            f"{type(quant).__name__}")
    wd = str(flags.get("FLAGS_serving_weight_dtype", "bf16"))
    kd = str(flags.get("FLAGS_serving_kv_dtype", "bf16"))
    spec = QuantSpec(weight_dtype=wd, kv_dtype=kd)
    return spec if spec.active else None


def page_scales(clip, num_pages, qmax):
    """THE per-page scale seeding rule, shared by ``PagedKVPool`` and the
    drift harness: every page of layer l starts at ``clip[l]/qmax``
    (floored at 1e-8), the trash page (physical 0) stays 1.0 — its
    garbage is never read unmasked, and a 1.0 divisor keeps trash writes
    finite. Returns [L, P] float32."""
    clip = np.asarray(clip, np.float64)
    out = np.ones((clip.shape[0], int(num_pages)), np.float32)
    out[:, 1:] = (np.maximum(clip, 1e-8) / float(qmax))[:, None]
    return out


# ---------------------------------------------------------------------------
# validation (up-front, naming the leaf)


def _expected_scale_shapes(params):
    out = {}
    blocks = params["blocks"]
    for name in BLOCK_WEIGHTS:
        w = np.shape(blocks[name])
        out[f"blocks.{name}"] = (w[0], w[-1])
    out["head_w"] = (np.shape(params["head_w"])[-1],)
    return out


def validate(spec, params, config):
    """Reject a spec whose calibrated artifacts don't match this params
    tree BEFORE anything is built — the error names the offending leaf."""
    if spec.weight_scales is not None:
        expected = _expected_scale_shapes(params)
        given = dict(spec.weight_scales)
        blocks = given.pop("blocks", {})
        flat = {f"blocks.{k}": v for k, v in blocks.items()}
        flat.update(given)
        for leaf, arr in flat.items():
            if leaf not in expected:
                raise QuantSpecError(
                    f"QuantSpec.weight_scales names leaf {leaf!r}, which "
                    f"is not a quantized serving weight "
                    f"({sorted(expected)})")
            shape = tuple(np.shape(arr))
            if shape != expected[leaf]:
                raise QuantSpecError(
                    f"QuantSpec.weight_scales[{leaf!r}] has shape {shape} "
                    f"but the params tree needs {expected[leaf]} "
                    f"(per-output-channel scales)")
        missing = [k for k in expected if k not in flat]
        if missing:
            raise QuantSpecError(
                f"QuantSpec.weight_scales is missing scales for "
                f"{missing}; calibrate() produces the full set")
    if spec.quantizes_kv:
        L = int(config.num_layers)
        for name, clip in (("kv_k_clip", spec.kv_k_clip),
                           ("kv_v_clip", spec.kv_v_clip)):
            if clip is not None and np.shape(clip) != (L,):
                raise QuantSpecError(
                    f"QuantSpec.{name} has shape {np.shape(clip)} but the "
                    f"model has {L} layers (one clip per layer)")
    return spec


# ---------------------------------------------------------------------------
# weight quantization


def _quantize_leaf(w, dtype, scale=None):
    """Per-output-channel symmetric quantization of a matmul weight
    [..., K, F] along its LAST axis. Channel-independent by construction,
    so a column shard of the result equals the result of the shard — the
    mp bitwise contract."""
    wf = jnp.asarray(w, jnp.float32)
    qmax = QMAX[dtype]
    if scale is None:
        amax = jnp.max(jnp.abs(wf), axis=-2)            # [..., F]
        scale = jnp.maximum(amax, 1e-8) / qmax
    else:
        scale = jnp.asarray(scale, jnp.float32)
    sb = scale[..., None, :]                            # broadcast over K
    if dtype == "int8":
        q = jnp.clip(jnp.round(wf / sb), -128, 127).astype(jnp.int8)
    else:
        q = jnp.clip(wf / sb, -qmax, qmax).astype(STORE_DTYPES[dtype])
    return q, scale.astype(jnp.float32)


def quantize_params(params, config, spec, qkv_perm=None):
    """Quantize the serving GEMM weights of an ``init_gpt_params`` tree to
    ``spec.weight_dtype``, adding a fp32 ``<name>_s`` scale leaf per
    quantized weight. Pinned ``spec.weight_scales`` are honored
    (``qkv_perm`` relabels the pinned qkv columns when the caller already
    permuted the tree head-major); otherwise scales are fresh absmax of
    the live weights — which is exactly what ``swap_params`` wants."""
    if not spec.quantizes_weights:
        return params
    pinned = spec.weight_scales or {}
    pinned_blocks = dict(pinned.get("blocks", {}))
    if qkv_perm is not None and "qkv_w" in pinned_blocks:
        pinned_blocks["qkv_w"] = np.asarray(
            pinned_blocks["qkv_w"])[..., qkv_perm]
    blocks = dict(params["blocks"])
    for name in BLOCK_WEIGHTS:
        q, s = _quantize_leaf(blocks[name], spec.weight_dtype,
                              pinned_blocks.get(name))
        blocks[name] = q
        blocks[name + "_s"] = s
    out = dict(params)
    out["blocks"] = blocks
    q, s = _quantize_leaf(params["head_w"], spec.weight_dtype,
                          pinned.get("head_w"))
    out["head_w"] = q
    out["head_w_s"] = s
    return out


def scale_bytes(params):
    """Total bytes of the fp32 scale leaves riding a quantized tree."""
    total = 0
    leaves = dict(params.get("blocks", {}))
    leaves["head_w_s"] = params.get("head_w_s")
    for name, a in leaves.items():
        if name.endswith("_s") and a is not None:
            total += int(np.prod(np.shape(a))) * 4
    return total


# ---------------------------------------------------------------------------
# calibration bridge (paddle_tpu.quantization observers -> QuantSpec)


def _observer_clip(obs):
    """Symmetric clip range recorded by an 8-bit observer: scales() is
    clip/qmax, so clip = scales() * (2^(bits-1) - 1)."""
    return np.asarray(obs.scales(), np.float64) * \
        (2.0 ** (obs.bit_length() - 1) - 1.0)


def _calibration_sample(config, n_tokens):
    """Deterministic token sample for the automatic (no-data) KV
    calibration: a fixed sweep over the vocabulary."""
    T = max(2, min(int(n_tokens), config.max_seq_len))
    return (np.arange(T, dtype=np.int32) * 7 + 1) % config.vocab_size


def kv_ranges(params, config, sample_ids=None, n_tokens=64,
              observer_factory=None):
    """Per-layer |K| / |V| clip ranges from ONE full-precision prefill
    over ``sample_ids`` (default: the deterministic sweep), recorded
    through ``quantization`` observers (AbsmaxObserver by default; pass
    e.g. ``lambda: PercentileObserver(99.9)`` to clip outliers). Returns
    (k_clip [L], v_clip [L]) float64 numpy arrays."""
    from ..models.generation import _forward_cached, _logical_qkv
    from ..quantization import AbsmaxObserver
    params = _logical_qkv(params, config)
    if sample_ids is None:
        sample_ids = _calibration_sample(config, n_tokens)
    ids = jnp.asarray(np.asarray(sample_ids, np.int32))[None]
    T = ids.shape[1]
    if T > config.max_seq_len:
        raise QuantSpecError(
            f"calibration sample ({T} tokens) exceeds the model's "
            f"max_seq_len ({config.max_seq_len})")
    L = config.num_layers
    nh = config.num_heads
    d = config.hidden_size // nh
    compute = jnp.dtype(config.compute_dtype or "float32")
    kc = jnp.zeros((L, 1, T, nh, d), compute)
    vc = jnp.zeros((L, 1, T, nh, d), compute)
    _, kc, vc = _forward_cached(params, config, ids, kc, vc, 0)
    make = observer_factory or AbsmaxObserver
    k_clip = np.zeros(L)
    v_clip = np.zeros(L)
    for layer in range(L):
        ok, ov = make(), make()
        ok.observe(kc[layer])
        ov.observe(vc[layer])
        ok.cal_thresholds()
        ov.cal_thresholds()
        k_clip[layer] = float(np.max(_observer_clip(ok)))
        v_clip[layer] = float(np.max(_observer_clip(ov)))
    return k_clip, v_clip


def calibrate(params, config, sample_ids=None, weight_dtype="int8",
              kv_dtype="int8", kv_observer=None):
    """PTQ calibration bridge: run the ``quantization`` package's
    observers against the params tree and a token sample, producing a
    serving ``QuantSpec`` (per-output-channel weight scales + per-layer
    KV clip ranges) that ``Engine(quant=...)``, ``Predictor.serve()`` and
    ``inference.serve()`` accept. Scales are recorded on the LOGICAL qkv
    layout (the mp engine permutes them with the weights)."""
    from ..models.generation import _logical_qkv
    from ..quantization import PerChannelAbsmaxObserver
    spec = QuantSpec(weight_dtype=weight_dtype, kv_dtype=kv_dtype)
    if spec.quantizes_weights:
        logical = _logical_qkv(params, config)
        qmax = QMAX[weight_dtype]
        blocks = {}
        for name in BLOCK_WEIGHTS:
            w = np.asarray(logical["blocks"][name], np.float32)
            # one per-channel observer per layer: quant_axis is the OUT
            # (last) axis of this layer's [K, F] slice
            scales = []
            for layer in range(w.shape[0]):
                obs = PerChannelAbsmaxObserver(quant_axis=w.ndim - 2)
                obs.observe(w[layer])
                obs.cal_thresholds()
                scales.append(np.maximum(
                    _observer_clip(obs), 1e-8) / qmax)
            blocks[name] = np.stack(scales).astype(np.float32)
        obs = PerChannelAbsmaxObserver(quant_axis=1)
        obs.observe(np.asarray(logical["head_w"], np.float32))
        obs.cal_thresholds()
        head_s = (np.maximum(_observer_clip(obs), 1e-8) / qmax
                  ).astype(np.float32)
        spec = replace(spec, weight_scales={"blocks": blocks,
                                            "head_w": head_s})
    if spec.quantizes_kv:
        k_clip, v_clip = kv_ranges(params, config, sample_ids,
                                   observer_factory=kv_observer)
        spec = replace(spec, kv_k_clip=k_clip, kv_v_clip=v_clip)
    return validate(spec, params, config)


def ensure_kv_clips(spec, params, config):
    """Fill missing KV clip ranges by auto-calibration (one fp forward
    over the deterministic sample) — the flags-only path where no PTQ
    artifact exists. Returns the (possibly updated) spec."""
    if not spec.quantizes_kv or (spec.kv_k_clip is not None
                                 and spec.kv_v_clip is not None):
        return spec
    k_clip, v_clip = kv_ranges(params, config)
    return replace(spec,
                   kv_k_clip=spec.kv_k_clip if spec.kv_k_clip is not None
                   else k_clip,
                   kv_v_clip=spec.kv_v_clip if spec.kv_v_clip is not None
                   else v_clip)


# ---------------------------------------------------------------------------
# drift measurement (the smoke harness' gate metric)


def max_logit_drift(params, config, spec, prompt, page_size=8):
    """Max |logits_fp - logits_quant| of ONE prefill forward over
    ``prompt`` through the paged serving forward — the drift stat the
    memory-equal smoke rung gates and ``serving_summary()`` surfaces.
    Returns (max_abs_drift, max_abs_fp_logit)."""
    from ..models.generation import _logical_qkv
    from .paged_kv import pages_for
    from .paged_attention import paged_forward
    params = _logical_qkv(params, config)
    spec = ensure_kv_clips(spec, params, config)
    prompt = np.asarray(prompt, np.int32)
    T = len(prompt)
    L = config.num_layers
    nh = config.num_heads
    d = config.hidden_size // nh
    MP = pages_for(T, page_size)
    P = MP + 1
    compute = jnp.dtype(config.compute_dtype or "float32")
    ids = jnp.asarray(prompt)[None]
    start = jnp.zeros((1,), jnp.int32)
    valid = jnp.asarray([T], jnp.int32)
    table = jnp.asarray(np.arange(1, MP + 1, dtype=np.int32))[None]

    def run(p, kv_dtype, kv_scales):
        store = (compute if kv_dtype == "bf16"
                 else STORE_DTYPES[kv_dtype])
        kc = jnp.zeros((L, P, page_size, nh, d), store)
        vc = jnp.zeros((L, P, page_size, nh, d), store)
        logits, _, _ = paged_forward(p, config, ids, kc, vc, start, valid,
                                     table, page_size, False,
                                     kv_scales=kv_scales)
        return np.asarray(logits, np.float64)

    ref = run(params, "bf16", None)
    qparams = quantize_params(params, config, spec)
    kv_scales = None
    if spec.quantizes_kv:
        qmax = QMAX[spec.kv_dtype]
        kv_scales = (jnp.asarray(page_scales(spec.kv_k_clip, P, qmax)),
                     jnp.asarray(page_scales(spec.kv_v_clip, P, qmax)))
    got = run(qparams, spec.kv_dtype, kv_scales)
    return float(np.max(np.abs(ref - got))), float(np.max(np.abs(ref)))


# ---------------------------------------------------------------------------
# speculative-draft plumbing (the draft model is DERIVED, never loaded)


DRAFT_SOURCES = ("quant", "shallow")


@dataclass
class DraftSpec:
    """Static speculative-decoding config: how many tokens the draft
    proposes per boundary (``k``) and where the draft model comes from —
    ``"quant"`` (int8 self-draft: the engine's own weights quantized
    per-channel; degenerates to the engine weights when the engine is
    already quantized) or ``"shallow"`` (the first ``layers`` transformer
    blocks of the same tree, sharing embeddings/final-LN/head).
    ``layers=0`` means auto (num_layers // 2, at least 1)."""

    k: int
    source: str = "quant"
    layers: int = 0

    def __post_init__(self):
        self.k = int(self.k)
        if self.k < 1:
            raise QuantSpecError(
                f"DraftSpec.k must be >= 1, got {self.k}")
        if self.source not in DRAFT_SOURCES:
            raise QuantSpecError(
                f"DraftSpec.source must be one of {DRAFT_SOURCES}, got "
                f"{self.source!r}")
        self.layers = int(self.layers)
        if self.layers < 0:
            raise QuantSpecError(
                f"DraftSpec.layers must be >= 0 (0 = auto), got "
                f"{self.layers}")

    def num_layers(self, total_layers):
        if self.source != "shallow":
            return int(total_layers)
        n = self.layers or max(1, int(total_layers) // 2)
        return min(n, int(total_layers))

    def key(self):
        """Hashable static key for the memoized draft builder."""
        return (self.k, self.source, self.layers)


def resolve_draft(speculate_k, source, layers, flags):
    """Normalize the Engine's speculation arguments: explicit kwargs win,
    None falls back to the FLAGS_serving_speculate_k family. Returns None
    when the resolved k is 0 — the engine then builds byte-identical
    executables to a pre-speculation engine."""
    k = (int(flags.get("FLAGS_serving_speculate_k", 0))
         if speculate_k is None else int(speculate_k))
    if k <= 0:
        return None
    src = (str(flags.get("FLAGS_serving_draft_source", "quant"))
           if source is None else str(source))
    n = (int(flags.get("FLAGS_serving_draft_layers", 0))
         if layers is None else int(layers))
    return DraftSpec(k=k, source=src, layers=n)


def shallow_draft_params(params, n_layers):
    """Truncate a (possibly quantized) params tree to its first
    ``n_layers`` transformer blocks. Embeddings, final LN and the LM head
    are SHARED with the full tree (same arrays, no copy); only the
    stacked block leaves — and their ``_s`` scale companions, which stack
    the same layer axis — are sliced."""
    blocks = {name: leaf[:n_layers]
              for name, leaf in params["blocks"].items()}
    out = dict(params)
    out["blocks"] = blocks
    return out
