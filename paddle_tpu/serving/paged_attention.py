"""Paged attention for the serving engine: transformer forward over a
block-paged KV pool ``[L, P, page_size, nh, d]`` read through a per-slot
page table.

Two implementations of the decode-attention read:

* **pure-jnp page gather** (default, every backend) — gather each slot's
  pages into virtual ``[B, S, nh, d]`` order and run exactly the math of
  ``models.generation._layer_decode_slots``. Because appended masked keys
  contribute exact zeros to the softmax and context sums, the result is
  BITWISE identical to the pooled layout and to single-request
  ``generate_from_params`` — this is the tier-1 parity path.
* **Pallas TPU kernel** (``paged_decode_attention``) — one-token decode
  that walks each slot's page list via scalar-prefetched table indices, so
  only that slot's LIVE pages move HBM->VMEM (the gather path materializes
  the full virtual window). Online-softmax accumulation: numerically
  equivalent, not bitwise identical — gated behind
  ``FLAGS_serving_paged_kernel`` and a TPU-backend + shape predicate
  (``paged_kernel_supported``), mirroring the flash-attention routing.

The fused step here is ALSO the chunked-prefill executable: every slot
processes a ``T``-token window at its own offset (``T=1`` pure decode;
``T=chunk`` while any prompt is prefilling), with per-slot ``start`` /
``valid`` / ``emit`` as traced operands. Padding lanes and inactive slots
scatter their K/V to physical page 0 (the trash page) and are never read
back unmasked.

Quantized serving (serving/quant.py, default-OFF): when the engine's kv
dtype is int8/fp8 the pool stores quantized values and ``kv_scales`` =
(k_scale, v_scale) ``[L, P]`` per-PAGE traced operands ride along —
writes quantize in ``paged_kv_scatter``, reads dequantize here (scores
are computed against the quantized keys and multiplied by the per-page
scale AFTER the dot, identically in every read branch, so all branches
— and every mp shard — stay bitwise consistent with each other at a
given dtype config). Quantized WEIGHT leaves carry a ``<name>_s``
per-output-channel scale companion consumed by ``quant_gemm`` (dequant
in the GEMM epilogue — no fp weight copy). With both dtypes at "bf16"
none of these operands exist and the math is byte-identical to the
unquantized engine.
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.gpt import ln_fp32
from ..models.generation import _final_ln, _final_logits
from ..ops.pallas_kernels.quant_gemm import quant_gemm, lora_delta, \
    compose_delta

logger = logging.getLogger("paddle_tpu.paged_attention")


def _proj(h, p, name, wq_kernel=False):
    """One projection GEMM: full-precision ``h @ w`` when the leaf is fp,
    or the weight-only quantized GEMM (int8/fp8 leaf + per-output-channel
    ``<name>_s`` scale, dequant fused into the epilogue) when the engine
    quantized its weights."""
    s = p.get(name + "_s")
    if s is None:
        return h @ p[name].astype(h.dtype)
    return quant_gemm(h, p[name], s, use_kernel=wq_kernel)


def paged_kernel_supported(nh, d, page_size, why=""):
    """Routing predicate for the Pallas paged-decode kernel (same pattern
    as ops.pallas_kernels.flash_supported): TPU backend + Mosaic-friendly
    shapes, logged fallback otherwise."""
    reasons = []
    if jax.default_backend() != "tpu":
        reasons.append("backend is not TPU")
    if d % 128 != 0:
        reasons.append(f"head_dim {d} not a multiple of 128")
    if nh % 8 != 0:
        reasons.append(f"num_heads {nh} not a multiple of 8")
    if page_size % 8 != 0:
        reasons.append(f"page_size {page_size} not a multiple of 8")
    if reasons:
        logger.info("paged decode kernel fallback to jnp gather%s: %s",
                    f" ({why})" if why else "", "; ".join(reasons))
        return False
    return True


# ---------------------------------------------------------------------------
# Pallas TPU kernel: one-token decode through the page table


def _decode_kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, page_size, scale):
    """Grid (B, MP): slot b sweeps its logical pages j; the BlockSpec
    index_map already resolved logical->physical through the prefetched
    table, so k_ref/v_ref hold THIS slot's j-th page. Online softmax state
    (m, l, acc) lives in VMEM scratch across the page sweep."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32)                     # [nh, d]
    k = k_ref[0].astype(jnp.float32)                     # [ps, nh, d]
    v = v_ref[0].astype(jnp.float32)
    s = jnp.einsum("hd,shd->hs", q, k,
                   preferred_element_type=jnp.float32) * scale  # [nh, ps]
    key_pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)                    # [1, ps]
    valid = key_pos <= pos_ref[b]
    s = jnp.where(valid, s, -jnp.inf)

    m_prev = m_ref[:, :1]                                # [nh, 1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    # fully-masked pages keep m at -inf; guard the exp(-inf - -inf) NaNs
    alpha = jnp.where(m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_new))
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)        # [nh, ps]
    l_ref[:] = jnp.broadcast_to(alpha * l_prev +
                                jnp.sum(p, axis=-1, keepdims=True),
                                l_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    # ctx update: [nh, ps] x [ps, nh, d] -> per-head [nh, d]
    pv = jnp.einsum("hs,shd->hd", p, v,
                    preferred_element_type=jnp.float32)
    acc_ref[:] = acc_ref[:] * alpha + pv

    @pl.when(j == nj - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def _decode_kernel_q(table_ref, pos_ref, ksc_ref, vsc_ref, q_ref, k_ref,
                     v_ref, o_ref, m_ref, l_ref, acc_ref, *, page_size,
                     scale):
    """Quantized-KV variant of ``_decode_kernel``: the pool holds int8/
    fp8 values and the per-PAGE dequant scales arrive as scalar-prefetch
    operands — the dequant multiply lives INSIDE the online-softmax page
    sweep (scores scale after the q·k dot, v contributions scale inside
    the ctx accumulation), so the fp K/V bytes never exist in HBM."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    MP = nj
    phys = table_ref[b * MP + j]
    ks = ksc_ref[phys]
    vs = vsc_ref[phys]
    q = q_ref[0].astype(jnp.float32)                     # [nh, d]
    k = k_ref[0].astype(jnp.float32)                     # [ps, nh, d]
    v = v_ref[0].astype(jnp.float32)
    s = jnp.einsum("hd,shd->hs", q, k,
                   preferred_element_type=jnp.float32) * scale * ks
    key_pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)                    # [1, ps]
    valid = key_pos <= pos_ref[b]
    s = jnp.where(valid, s, -jnp.inf)

    m_prev = m_ref[:, :1]                                # [nh, 1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.where(m_prev == -jnp.inf, 0.0, jnp.exp(m_prev - m_new))
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)        # [nh, ps]
    l_ref[:] = jnp.broadcast_to(alpha * l_prev +
                                jnp.sum(p, axis=-1, keepdims=True),
                                l_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    pv = jnp.einsum("hs,shd->hd", p, v,
                    preferred_element_type=jnp.float32) * vs
    acc_ref[:] = acc_ref[:] * alpha + pv

    @pl.when(j == nj - 1)
    def _():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_decode_attention_q(q, kc_l, vc_l, table, pos, ksc_l, vsc_l, *,
                             page_size, interpret=False):
    """Quantized-pool one-token paged attention: like
    ``paged_decode_attention`` plus per-page dequant scales ksc_l/vsc_l
    [P] (fp32) prefetched to SMEM and applied inside the page sweep."""
    B, nh, d = q.shape
    MP = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,      # flat table, pos, k scales, v scales
        grid=(B, MP),
        in_specs=[
            pl.BlockSpec((1, nh, d),
                         lambda b, j, tab, pos, ks, vs: (b, 0, 0)),
            pl.BlockSpec((1, page_size, nh, d),
                         lambda b, j, tab, pos, ks, vs:
                         (tab[b * MP + j], 0, 0, 0)),
            pl.BlockSpec((1, page_size, nh, d),
                         lambda b, j, tab, pos, ks, vs:
                         (tab[b * MP + j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, d),
                               lambda b, j, tab, pos, ks, vs: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),      # m (lane-broadcast)
            pltpu.VMEM((nh, 128), jnp.float32),      # l
            pltpu.VMEM((nh, d), jnp.float32),        # acc
        ],
    )
    kernel = functools.partial(_decode_kernel_q, page_size=page_size,
                               scale=1.0 / (d ** 0.5))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nh, d), jnp.float32),
        interpret=interpret,
    )(table.reshape(-1).astype(jnp.int32), pos.astype(jnp.int32),
      ksc_l.astype(jnp.float32), vsc_l.astype(jnp.float32),
      q.astype(jnp.float32), kc_l, vc_l)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def paged_decode_attention(q, kc_l, vc_l, table, pos, *, page_size,
                           interpret=False):
    """One-token paged attention: q [B, nh, d] (fp32), kc_l/vc_l
    [P, page_size, nh, d], table [B, MP], pos [B] -> ctx [B, nh, d] fp32.
    Unmapped table entries are 0 (trash page) and masked by pos."""
    B, nh, d = q.shape
    MP = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,              # flat table [B*MP], pos [B]
        grid=(B, MP),
        in_specs=[
            pl.BlockSpec((1, nh, d), lambda b, j, tab, pos: (b, 0, 0)),
            pl.BlockSpec((1, page_size, nh, d),
                         lambda b, j, tab, pos: (tab[b * MP + j], 0, 0, 0)),
            pl.BlockSpec((1, page_size, nh, d),
                         lambda b, j, tab, pos: (tab[b * MP + j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, d), lambda b, j, tab, pos: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),      # m (lane-broadcast)
            pltpu.VMEM((nh, 128), jnp.float32),      # l
            pltpu.VMEM((nh, d), jnp.float32),        # acc
        ],
    )
    kernel = functools.partial(_decode_kernel, page_size=page_size,
                               scale=1.0 / (d ** 0.5))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nh, d), jnp.float32),
        interpret=interpret,
    )(table.reshape(-1).astype(jnp.int32), pos.astype(jnp.int32),
      q.astype(jnp.float32), kc_l, vc_l)


# ---------------------------------------------------------------------------
# fused step forward (jnp gather path; kernel spliced in for T=1 on TPU)


def _quantize_kv(x, sc, dtype):
    """Quantize one K/V window [B, T, nh', d] with its per-position page
    scale sc [B, T] into the pool's storage dtype (int8 round+clip; fp8
    saturating cast). Head-independent, so any head subset (the mp
    engine's shard) quantizes bitwise-identically to the full write."""
    scaled = x.astype(jnp.float32) / sc[:, :, None, None]
    if dtype == jnp.int8:
        return jnp.clip(jnp.round(scaled), -128, 127).astype(jnp.int8)
    info = jnp.finfo(dtype)
    return jnp.clip(scaled, float(info.min), float(info.max)).astype(dtype)


def paged_kv_scatter(kc_l, vc_l, k, v, table, pos, valid, page_size,
                     ksc_l=None, vsc_l=None):
    """Scatter one window's K/V [B, T, nh', d] into the paged pool through
    the slot->page table: logical page -> physical; lanes past valid[b]
    (and whole inactive slots) write to trash page 0. ``nh'`` is whichever
    head count the caller holds — all heads single-chip, the local shard
    under mp (the table is head-independent). With a quantized pool the
    per-page scales ksc_l/vsc_l [P] quantize the write in place (trash
    page 0 keeps scale 1.0; its garbage is never read unmasked)."""
    MP = table.shape[1]
    T = pos.shape[1]
    writable = jnp.arange(T)[None, :] < valid[:, None]          # [B, T]
    li = jnp.minimum(pos // page_size, MP - 1)
    phys = jnp.where(writable, jnp.take_along_axis(table, li, axis=1), 0)
    off = pos % page_size
    if ksc_l is not None:
        k = _quantize_kv(k, ksc_l[phys], kc_l.dtype)
        v = _quantize_kv(v, vsc_l[phys], vc_l.dtype)
    kc_l = kc_l.at[phys, off].set(k.astype(kc_l.dtype))
    vc_l = vc_l.at[phys, off].set(v.astype(vc_l.dtype))
    return kc_l, vc_l


def paged_attention_read(q, kc_l, vc_l, table, pos, page_size, use_kernel,
                         out_dtype, ksc_l=None, vsc_l=None):
    """Paged attention read: q [B, T, nh', d] against the pool's nh' heads
    through the table; returns ctx [B, T, nh', d] in ``out_dtype``. Every
    head's math is independent and mirrors generation._layer_decode_slots
    exactly, so any head SUBSET (the mp engine's per-chip shard) is
    bitwise identical to the same heads of the full computation.

    Quantized pool (ksc_l/vsc_l [P] per-page scales present): scores are
    computed against the QUANTIZED keys and multiplied by the key page's
    scale AFTER the dot — every position of a page shares one scale, so
    the multiply factors out of the contraction and both read branches
    below compute bit-identical scores; V dequantizes after its gather.
    The per-dtype exactness contract (mp == single-chip, order/restore
    invariance) rides on this branch-consistency."""
    B, T, nh, d = q.shape
    MP = table.shape[1]

    if use_kernel and T == 1:
        if ksc_l is not None:
            return paged_decode_attention_q(
                q[:, 0].astype(jnp.float32), kc_l, vc_l, table, pos[:, 0],
                ksc_l, vsc_l,
                page_size=page_size)[:, None].astype(out_dtype)
        return paged_decode_attention(
            q[:, 0].astype(jnp.float32), kc_l, vc_l, table, pos[:, 0],
            page_size=page_size)[:, None].astype(out_dtype)     # [B,1,nh,d]
    S = MP * page_size
    P = kc_l.shape[0]
    if T == 1 and 2 * P * page_size <= B * S:
        # decode on an UNDERSUBSCRIBED pool (physical pages well below
        # the sum of virtual windows — the memory-equal serving
        # regime): score the query against the pool once and gather
        # only the tiny score rows into virtual order. Each score is
        # the same q-dot-k over d either way, so this is bitwise
        # identical to scoring gathered keys while reading far fewer
        # key bytes (measured ~2.8x faster at P*ps ~ B*S/6; the
        # gather branch wins when P*ps ~ B*S, hence the static 2x
        # shape guard).
        s_all = jnp.einsum("bthd,pshd->bhtps", q.astype(jnp.float32),
                           kc_l.astype(jnp.float32)) / (d ** 0.5)
        scores = jax.vmap(lambda sa, tb: sa[:, :, tb])(
            s_all, table).reshape(B, nh, T, S)
    else:
        # chunk prefill (pool-wide scoring is FLOP-heavy for T
        # queries) and amply-sized pools: gather the key window
        kv_k = kc_l[table].reshape(B, S, nh, d)
        scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                            kv_k.astype(jnp.float32)) / (d ** 0.5)
    if ksc_l is not None:
        # per-position key scale in virtual order [B, S]: the dequant
        # multiply lands AFTER the dot in both branches identically
        k_sc = jnp.repeat(ksc_l[table], page_size, axis=1)      # [B, S]
        scores = scores * k_sc[:, None, None, :]
    kv_v = vc_l[table].reshape(B, S, nh, d).astype(jnp.float32)
    if vsc_l is not None:
        v_sc = jnp.repeat(vsc_l[table], page_size, axis=1)      # [B, S]
        kv_v = kv_v * v_sc[:, :, None, None]
    # absolute causal mask; masked keys (incl. trash/unmapped reads)
    # contribute exact zeros, preserving bitwise parity with the
    # contiguous layouts
    mask = jnp.arange(S)[None, None, :] <= pos[:, :, None]      # [B, T, S]
    scores = jnp.where(mask[:, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", probs,
                      kv_v).astype(out_dtype)


def _adapted_proj(h, p, name, wq_kernel, aid, ad_l):
    """``_proj`` plus the per-slot LoRA delta epilogue: when this layer's
    adapter slab covers ``name`` the low-rank delta joins the base GEMM
    output (before bias) through the masked compose — aid==0 rows keep
    the base product bitwise. qkv_w is never in ``ad_l`` by construction
    (AdapterRegistry forbids it), keeping the delta GEMM out of the
    attention inner loop; prefix pages of ADAPTED requests still depend
    on the delta bits through the residual stream, which is why the
    engine salts their prefix-cache keys (engine._prefix_salt)."""
    base = _proj(h, p, name, wq_kernel)
    if ad_l is None or name not in ad_l:
        return base
    A_l, B_l = ad_l[name]
    return compose_delta(base, lora_delta(h, A_l, B_l, aid), aid)


def _layer_paged(p, h, kc_l, vc_l, table, pos, valid, nh, eps, page_size,
                 use_kernel, ksc_l=None, vsc_l=None, wq_kernel=False,
                 aid=None, ad_l=None):
    """One transformer block over h [B, T, H] where each batch row is a
    serving slot processing the token window at absolute positions
    pos[b, :] (valid[b] of them real). K/V are scattered through the page
    table (padding lanes -> trash page 0); attention reads the gathered
    virtual window with the absolute causal mask. Math mirrors
    generation._layer_decode_slots / _layer_cached exactly, so a slot's
    stream is bitwise identical to single-request decode. Quantized
    engines route the GEMMs through ``_proj`` (epilogue dequant) and the
    KV writes/reads through the per-page scales. With adapters enabled,
    aid [B] + this layer's slab rows ``ad_l`` route each slot's low-rank
    delta into the out/up/down projection epilogues (qkv itself stays
    un-adapted)."""
    B, T, H = h.shape
    d = H // nh

    h1 = ln_fp32(h, p["ln1_g"], p["ln1_b"], eps)
    qkv = _proj(h1, p, "qkv_w", wq_kernel) + p["qkv_b"].astype(h.dtype)
    q, k, v = jnp.split(qkv.reshape(B, T, 3, nh, d), 3, axis=2)
    q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]

    kc_l, vc_l = paged_kv_scatter(kc_l, vc_l, k, v, table, pos, valid,
                                  page_size, ksc_l, vsc_l)
    ctx = paged_attention_read(q, kc_l, vc_l, table, pos, page_size,
                               use_kernel, h.dtype, ksc_l, vsc_l)

    attn = _adapted_proj(ctx.reshape(B, T, H), p, "out_w", wq_kernel,
                         aid, ad_l) + p["out_b"].astype(h.dtype)
    h = h + attn
    h2 = ln_fp32(h, p["ln2_g"], p["ln2_b"], eps)
    up = _adapted_proj(h2, p, "up_w", wq_kernel, aid, ad_l) + \
        p["up_b"].astype(h.dtype)
    up = jax.nn.gelu(up, approximate=True)
    return h + _adapted_proj(up, p, "down_w", wq_kernel, aid, ad_l) + \
        p["down_b"].astype(h.dtype), kc_l, vc_l


def paged_forward(params, config, ids, kc, vc, start, valid, table,
                  page_size, use_kernel=False, kv_scales=None,
                  wq_kernel=False, adapters=None):
    """Fused chunk/decode forward: ids [B, T] is each slot's token window at
    absolute positions start[b]..start[b]+T-1 (valid[b] real). Returns
    logits at each slot's position valid[b]-1 ([B, V]) plus the updated
    paged pools [L, P, page_size, nh, d]. ``kv_scales`` = (k_scale,
    v_scale) [L, P] traced per-page dequant scales when the pool is
    quantized; ``wq_kernel`` routes quantized weight GEMMs through the
    Pallas quant kernel (TPU). ``adapters`` = (aid [B], slabs {target:
    (A [L, cap, K, r], B [L, cap, r, F])}) traced per-slot adapter rows —
    the slabs ride the layer scan alongside the block weights and the
    per-slot delta joins the projection epilogues (adapters.py)."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    B, T = ids.shape
    pos = start[:, None] + jnp.arange(T)[None, :]               # [B, T]
    x = params["wte"].astype(compute)[ids] + \
        jnp.take(params["wpe"].astype(compute), pos, axis=0)
    nh = config.num_heads
    ksc, vsc = kv_scales if kv_scales is not None else (None, None)
    aid, slabs = adapters if adapters is not None else (None, None)

    def layer_fn(h, xs):
        if adapters is not None:
            xs, ad_l = xs[:-1], xs[-1]
        else:
            ad_l = None
        if kv_scales is not None:
            p_l, kc_l, vc_l, ksc_l, vsc_l = xs
        else:
            p_l, kc_l, vc_l = xs
            ksc_l = vsc_l = None
        h, kc_l, vc_l = _layer_paged(p_l, h, kc_l, vc_l, table, pos, valid,
                                     nh, config.layer_norm_epsilon,
                                     page_size, use_kernel, ksc_l, vsc_l,
                                     wq_kernel, aid, ad_l)
        return h, (kc_l, vc_l)

    xs = ((params["blocks"], kc, vc) if kv_scales is None
          else (params["blocks"], kc, vc, ksc, vsc))
    if adapters is not None:
        xs = xs + (slabs,)
    x, (kc, vc) = jax.lax.scan(layer_fn, x, xs)
    idx = jnp.maximum(valid - 1, 0)
    xlast = jax.vmap(
        lambda xb, i: jax.lax.dynamic_slice_in_dim(xb, i, 1, axis=0))(
            x, idx)[:, 0]                                       # [B, H]
    if "head_w_s" in params:
        xn = _final_ln(params, config, xlast)
        logits = quant_gemm(xn, params["head_w"], params["head_w_s"],
                            use_kernel=wq_kernel)
        return logits, kc, vc
    return _final_logits(params, config, xlast), kc, vc


# ---------------------------------------------------------------------------
# speculative decoding: verify forward (+ KV rewind) and the draft forward


def _layer_verify(p, h, kc_l, vc_l, table, pos, valid, nh, eps, page_size,
                  use_kernel, ksc_l=None, vsc_l=None, wq_kernel=False):
    """``_layer_paged`` with the attention read decomposed PER LANE: each
    of the T window lanes reads the pool at the [B, 1] shape — the exact
    dot/softmax/contraction shapes of the plain engine's one-token decode
    — instead of one [B, T] read. The [B, T] contraction over the virtual
    window is mathematically identical but NOT bitwise (the backend may
    block a T-row GEMM differently than T=1's matvec), and the verify
    pass's whole contract is that an accepted lane's KV bytes and logits
    are bit-for-bit what the plain engine would have produced. T is the
    static k+1, so the unrolled loop stays a small fixed cost."""
    B, T, H = h.shape
    d = H // nh

    h1 = ln_fp32(h, p["ln1_g"], p["ln1_b"], eps)
    qkv = _proj(h1, p, "qkv_w", wq_kernel) + p["qkv_b"].astype(h.dtype)
    q, k, v = jnp.split(qkv.reshape(B, T, 3, nh, d), 3, axis=2)
    q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]

    kc_l, vc_l = paged_kv_scatter(kc_l, vc_l, k, v, table, pos, valid,
                                  page_size, ksc_l, vsc_l)
    ctx = jnp.concatenate(
        [paged_attention_read(q[:, t:t + 1], kc_l, vc_l, table,
                              pos[:, t:t + 1], page_size, use_kernel,
                              h.dtype, ksc_l, vsc_l)
         for t in range(T)], axis=1)

    attn = _proj(ctx.reshape(B, T, H), p, "out_w", wq_kernel) + \
        p["out_b"].astype(h.dtype)
    h = h + attn
    h2 = ln_fp32(h, p["ln2_g"], p["ln2_b"], eps)
    up = _proj(h2, p, "up_w", wq_kernel) + p["up_b"].astype(h.dtype)
    up = jax.nn.gelu(up, approximate=True)
    return h + _proj(up, p, "down_w", wq_kernel) + \
        p["down_b"].astype(h.dtype), kc_l, vc_l


def _head_logits(params, config, x, wq_kernel=False):
    """LM-head logits over arbitrary leading dims, routing through the
    quantized head when the tree carries one."""
    if "head_w_s" in params:
        xn = _final_ln(params, config, x)
        return quant_gemm(xn, params["head_w"], params["head_w_s"],
                          use_kernel=wq_kernel)
    return _final_logits(params, config, x)


def paged_verify_forward(params, config, ids, kc, vc, start, valid, table,
                         page_size, use_kernel=False, kv_scales=None,
                         wq_kernel=False):
    """Speculative VERIFY forward: exactly ``paged_forward``'s math over
    the window ids [B, T] (T = k+1: the last emitted token + k draft
    proposals), except that (a) logits come back for EVERY lane
    ([B, T, V] — the accept scan needs all of them) and (b) the pre-write
    STORAGE-dtype pool bytes of every written position are gathered per
    layer BEFORE the scatter and returned ([L, B, T, nh, d] saved_k/
    saved_v), so ``paged_kv_rewind`` can restore rejected lanes without a
    second forward. Lane 0's logits are bitwise identical to the plain
    fused step's logits for the same slot state: the scatter-then-read
    order, the absolute causal mask and the per-row LN/GEMM math are all
    unchanged, and appended masked lanes contribute exact zeros."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    B, T = ids.shape
    MP = table.shape[1]
    pos = start[:, None] + jnp.arange(T)[None, :]               # [B, T]
    x = params["wte"].astype(compute)[ids] + \
        jnp.take(params["wpe"].astype(compute), pos, axis=0)
    nh = config.num_heads
    ksc, vsc = kv_scales if kv_scales is not None else (None, None)
    # the same phys/off routing as paged_kv_scatter: padding lanes and
    # inactive slots resolve to trash page 0, whose pre-write bytes are
    # saved (and later rewritten) harmlessly
    writable = jnp.arange(T)[None, :] < valid[:, None]          # [B, T]
    li = jnp.minimum(pos // page_size, MP - 1)
    phys = jnp.where(writable, jnp.take_along_axis(table, li, axis=1), 0)
    off = pos % page_size

    def layer_fn(h, xs):
        if kv_scales is not None:
            p_l, kc_l, vc_l, ksc_l, vsc_l = xs
        else:
            p_l, kc_l, vc_l = xs
            ksc_l = vsc_l = None
        saved_k = kc_l[phys, off]            # [B, T, nh, d] storage dtype
        saved_v = vc_l[phys, off]
        h, kc_l, vc_l = _layer_verify(p_l, h, kc_l, vc_l, table, pos,
                                      valid, nh, config.layer_norm_epsilon,
                                      page_size, use_kernel, ksc_l, vsc_l,
                                      wq_kernel)
        return h, (kc_l, vc_l, saved_k, saved_v)

    xs = ((params["blocks"], kc, vc) if kv_scales is None
          else (params["blocks"], kc, vc, ksc, vsc))
    x, (kc, vc, saved_k, saved_v) = jax.lax.scan(layer_fn, x, xs)
    logits = _head_logits(params, config, x, wq_kernel)         # [B, T, V]
    return logits, kc, vc, saved_k, saved_v


def paged_kv_rewind(kc, vc, saved_k, saved_v, table, start, valid, n_emit,
                    page_size):
    """Restore the pool bytes the verify pass wrote past each slot's
    accepted length: lanes n_emit[b] <= i < valid[b] get their pre-write
    STORAGE-dtype bytes back (already-quantized bytes on a quantized
    pool — the restore bypasses re-quantization by construction, and the
    host-side per-page scales were never touched). After this the pool is
    byte-identical to a plain engine that decoded n_emit[b] tokens —
    except physical page 0, the trash page, which both engines treat as
    write-only garbage. Non-restored lanes route to page 0 exactly like
    ``paged_kv_scatter``'s padding lanes."""
    T = saved_k.shape[2]
    MP = table.shape[1]
    pos = start[:, None] + jnp.arange(T)[None, :]               # [B, T]
    lane = jnp.arange(T)[None, :]
    restore = (lane >= n_emit[:, None]) & (lane < valid[:, None])
    li = jnp.minimum(pos // page_size, MP - 1)
    phys = jnp.where(restore, jnp.take_along_axis(table, li, axis=1), 0)
    off = pos % page_size

    def layer_fn(carry, xs):
        kc_l, vc_l, sk_l, sv_l = xs
        kc_l = kc_l.at[phys, off].set(sk_l)
        vc_l = vc_l.at[phys, off].set(sv_l)
        return carry, (kc_l, vc_l)

    _, (kc, vc) = jax.lax.scan(layer_fn, 0, (kc, vc, saved_k, saved_v))
    return kc, vc


def _draft_layer(p_l, h, kc_l, vc_l, sk_l, sv_l, table, base_pos, i, nh,
                 eps, page_size, ksc_l, vsc_l):
    """One draft transformer block at T=1: the current draft token reads
    the REAL paged pool (strictly below base_pos — positions at/past it
    hold stale rewound bytes) jointly with the in-flight draft K/V
    sidecar (lanes 0..i), one concatenated softmax. The pool is never
    written: draft K/V live only in the sidecar, so rejected drafts need
    zero rewind."""
    B, T, H = h.shape
    d = H // nh
    kmax = sk_l.shape[1]

    h1 = ln_fp32(h, p_l["ln1_g"], p_l["ln1_b"], eps)
    qkv = _proj(h1, p_l, "qkv_w") + p_l["qkv_b"].astype(h.dtype)
    q, kx, vx = jnp.split(qkv.reshape(B, 1, 3, nh, d), 3, axis=2)
    q, kx, vx = q[:, :, 0], kx[:, :, 0], vx[:, :, 0]
    sk_l = sk_l.at[:, i].set(kx[:, 0])
    sv_l = sv_l.at[:, i].set(vx[:, 0])

    S = table.shape[1] * page_size
    kv_k = kc_l[table].reshape(B, S, nh, d)
    sc_pool = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                         kv_k.astype(jnp.float32)) / (d ** 0.5)
    if ksc_l is not None:
        k_sc = jnp.repeat(ksc_l[table], page_size, axis=1)      # [B, S]
        sc_pool = sc_pool * k_sc[:, None, None, :]
    sc_side = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                         sk_l.astype(jnp.float32)) / (d ** 0.5)
    pool_mask = (jnp.arange(S)[None, :] <
                 base_pos[:, None])[:, None, None, :]           # strict
    side_mask = (jnp.arange(kmax) <= i)[None, None, None, :]
    scores = jnp.concatenate(
        [jnp.where(pool_mask, sc_pool, -jnp.inf),
         jnp.where(side_mask, sc_side, -jnp.inf)], axis=-1)
    probs = jax.nn.softmax(scores, axis=-1)
    kv_v = vc_l[table].reshape(B, S, nh, d).astype(jnp.float32)
    if vsc_l is not None:
        v_sc = jnp.repeat(vsc_l[table], page_size, axis=1)      # [B, S]
        kv_v = kv_v * v_sc[:, :, None, None]
    vals = jnp.concatenate([kv_v, sv_l.astype(jnp.float32)], axis=1)
    ctx = jnp.einsum("bhts,bshd->bthd", probs, vals).astype(h.dtype)

    attn = _proj(ctx.reshape(B, 1, H), p_l, "out_w") + \
        p_l["out_b"].astype(h.dtype)
    h = h + attn
    h2 = ln_fp32(h, p_l["ln2_g"], p_l["ln2_b"], eps)
    up = _proj(h2, p_l, "up_w") + p_l["up_b"].astype(h.dtype)
    up = jax.nn.gelu(up, approximate=True)
    return h + _proj(up, p_l, "down_w") + \
        p_l["down_b"].astype(h.dtype), sk_l, sv_l


def paged_draft_forward(params, config, tok, kc, vc, pos, table, page_size,
                        k, kv_scales=None):
    """Speculative DRAFT forward: greedily roll the draft model ``k``
    tokens ahead of each slot's last emitted token ``tok`` [B] at
    absolute position ``pos`` [B], reading the engine's paged pool
    READ-ONLY and carrying the draft's own K/V in a compute-dtype sidecar
    [Ld, B, k, nh, d]. ``params`` may be a quantized and/or
    layer-truncated tree (Ld = its block count; the pool's leading layers
    line up because shallow drafts keep the FIRST blocks). Proposals are
    always greedy — the verify pass owns sampling and the PRNG stream.
    Returns proposals [B, k] int32."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    B = tok.shape[0]
    nh = config.num_heads
    d = config.hidden_size // nh
    Ld = params["blocks"]["qkv_w"].shape[0]
    ksc, vsc = kv_scales if kv_scales is not None else (None, None)
    kcd, vcd = kc[:Ld], vc[:Ld]
    kscd = ksc[:Ld] if ksc is not None else None
    vscd = vsc[:Ld] if vsc is not None else None
    sk0 = jnp.zeros((Ld, B, k, nh, d), compute)
    sv0 = jnp.zeros((Ld, B, k, nh, d), compute)

    def step_fn(carry, i):
        cur, sk, sv = carry
        p = pos + i
        # jnp.take clips OOB positions (a slot about to hit max_seq_len)
        x = params["wte"].astype(compute)[cur][:, None] + \
            jnp.take(params["wpe"].astype(compute), p, axis=0)[:, None]

        def layer_fn(h, xs):
            if kv_scales is not None:
                p_l, kc_l, vc_l, sk_l, sv_l, ksc_l, vsc_l = xs
            else:
                p_l, kc_l, vc_l, sk_l, sv_l = xs
                ksc_l = vsc_l = None
            h, sk_l, sv_l = _draft_layer(p_l, h, kc_l, vc_l, sk_l, sv_l,
                                         table, pos, i, nh,
                                         config.layer_norm_epsilon,
                                         page_size, ksc_l, vsc_l)
            return h, (sk_l, sv_l)

        xs = ((params["blocks"], kcd, vcd, sk, sv) if kv_scales is None
              else (params["blocks"], kcd, vcd, sk, sv, kscd, vscd))
        x, (sk, sv) = jax.lax.scan(layer_fn, x, xs)
        logits = _head_logits(params, config, x[:, 0])          # [B, V]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, sk, sv), nxt

    _, props = jax.lax.scan(step_fn, (tok, sk0, sv0), jnp.arange(k))
    return props.T                                              # [B, k]
