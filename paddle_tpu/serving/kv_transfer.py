"""KV-page streaming between disaggregated serving roles.

A PREFILL worker runs only the big-chunk rungs of the chunked-prefill
ladder: it computes a request's whole prompt KV, never decodes, and
streams finished pages to the DECODE worker the supervisor assigned.
This module is the wire format of that handoff:

``PagePayload``
    one logical page hauled to the host — K/V blocks at the pool's
    STORAGE dtype (bf16, or int8 / fp8-as-uint8 for quantized pools, so
    the wire is ~4x cheaper at 8-bit) plus the per-page dequant scale
    columns when quantized.

``KVTransfer``
    the per-request stream: the prefill engine appends payloads as
    chunks complete (pages become final the moment the chunk boundary
    passes them — KV of a token depends only on its prefix, which is
    what makes the handoff bitwise-safe), the supervisor routes the
    object, and the decode engine installs pages into its own allocator
    between decode boundaries (a bounded number per boundary, T3-style:
    the copy hides behind compute, decoding slots never stall). Payloads
    are RETAINED until the request is seated so a decode-worker death
    mid-transfer can re-offer the same stream to a survivor from the
    host copies — no recompute unless the PREFILL side died.

State flags (host-side, single supervising thread — no locking):

- ``done``     all ``total_pages`` payloads appended; the prefill side
               has freed its slot and registered its prefix cache.
- ``seated``   the decode side adopted the pages into a slot; terminal
               success.
- ``aborted``  the request was handled elsewhere (cancelled, expired,
               shed, drained, quarantined) — the supervisor takes NO
               replay action, the normal resolution path owns it.
- ``failed``   the transfer itself is unusable (e.g. the decode worker's
               params_version moved mid-flight) while the request is
               still live — the supervisor MUST replay it.

End-to-end wire integrity (``FLAGS_kv_transfer_crc``): the prefill side
stamps each payload with a CRC32 over the page bytes and scale columns
at creation; the decode side re-computes it just before installing the
page and raises ``KVIntegrityError`` on mismatch — a typed refusal the
engine turns into dropping the transfer so the supervisor re-offers the
RETAINED (still clean) host payloads. Default off: ``crc=None`` and
``verify()`` is a no-op, wire format unchanged.
"""
from __future__ import annotations

import time
import zlib

import numpy as np


class KVIntegrityError(RuntimeError):
    """A streamed KV page's bytes no longer match the CRC stamped at the
    prefill side — wire/host corruption. The payload must be refused
    (never installed); the retained transfer can be re-offered."""


def payload_crc(payload):
    """CRC32 over a payload's K/V page bytes and (when quantized) the
    fp32 scale columns — the exact bytes ``_install_page`` will seat."""
    crc = zlib.crc32(np.ascontiguousarray(payload.k).view(np.uint8))
    crc = zlib.crc32(np.ascontiguousarray(payload.v).view(np.uint8), crc)
    if payload.k_scale is not None:
        crc = zlib.crc32(
            np.ascontiguousarray(payload.k_scale).view(np.uint8), crc)
    if payload.v_scale is not None:
        crc = zlib.crc32(
            np.ascontiguousarray(payload.v_scale).view(np.uint8), crc)
    return crc & 0xFFFFFFFF


class PagePayload:
    """One KV page on the wire: host copies of the K and V blocks for
    every layer (``[L, page_size, nh, d]`` at the pool's storage dtype)
    and, for quantized pools, the fp32 per-page scale columns ``[L]``."""

    __slots__ = ("index", "k", "v", "k_scale", "v_scale", "crc")

    def __init__(self, index, k, v, k_scale=None, v_scale=None, crc=None):
        self.index = int(index)          # logical page number within the prompt
        self.k = np.asarray(k)
        self.v = np.asarray(v)
        self.k_scale = None if k_scale is None else np.asarray(k_scale)
        self.v_scale = None if v_scale is None else np.asarray(v_scale)
        self.crc = None if crc is None else int(crc)

    def stamp(self):
        """Record the current bytes' CRC32 (prefill side, at creation)."""
        self.crc = payload_crc(self)
        return self

    def verify(self):
        """Raise ``KVIntegrityError`` if the bytes drifted from the
        stamped CRC. No-op for unstamped payloads (CRC flag off)."""
        if self.crc is None:
            return
        got = payload_crc(self)
        if got != self.crc:
            raise KVIntegrityError(
                f"KV page {self.index}: crc {got:#010x} != stamped "
                f"{self.crc:#010x}")

    @property
    def nbytes(self):
        n = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes
        if self.v_scale is not None:
            n += self.v_scale.nbytes
        return n


class KVTransfer:
    """A request's prompt-KV stream from a prefill worker to a decode
    worker. Shared in-process object: the prefill engine appends, the
    decode engine reads, the supervisor routes — all on the supervising
    thread."""

    def __init__(self, request, page_size, kv_dtype, src_tag):
        from .paged_kv import pages_for
        self.request = request
        self.prompt_len = int(request.prompt_len)
        self.page_size = int(page_size)
        self.total_pages = pages_for(self.prompt_len, self.page_size)
        self.kv_dtype = str(kv_dtype)
        self.src_tag = str(src_tag)
        self.pages = []                  # PagePayloads in logical order
        self.done = False
        self.seated = False
        self.aborted = False
        self.failed = False
        self.t_open = time.perf_counter()

    @property
    def request_id(self):
        return self.request.request_id

    @property
    def bytes_total(self):
        return sum(p.nbytes for p in self.pages)

    def append(self, payload):
        assert not self.done, "append after finish()"
        assert payload.index == len(self.pages), (
            f"out-of-order page {payload.index} (expected {len(self.pages)})")
        self.pages.append(payload)

    def finish(self):
        assert len(self.pages) == self.total_pages, (
            f"finish() with {len(self.pages)}/{self.total_pages} pages")
        self.done = True
