"""paddle_tpu.serving — continuous-batching TPU serving engine.

Iteration-level (Orca-style) scheduling over a fixed B-slot decode batch.
The default KV layout is block-PAGED (vLLM-style: fixed-size pages + a
slot->page table, prefix reuse copy-on-write, chunked prefill fused into
the decode step); the PR 5 pooled ``[L, B, Smax, nh, d]`` layout remains
available as the bitwise parity baseline (``kv_layout="pooled"``). See
engine.py for the design; `profiler.serving_counters()` /
`serving_summary()` for observability.

Self-healing (engine.py + supervisor.py): `Engine.state_dict()` /
`load_state_dict()` snapshot the FULL engine (KV, slot table, PRNG
streams, queue, results, metrics) through the hardened checkpoint path —
a cold restart resumes every in-flight request bitwise mid-decode;
`Engine.run()` installs a SIGTERM boundary drain that flushes a snapshot
and requeues in-flight requests instead of dropping them; and
`ServingSupervisor` runs N replicas behind a least-loaded router with
heartbeat failure detection, snapshot respawn and exact request replay
(zero requests dropped across replica death / rolling restarts).

Telemetry: with ``FLAGS_serving_trace`` on, every Request carries a span
trace (queue → prefill chunks → decode → deliver, plus CoW/prefix and
self-healing hops) that survives engine snapshots and exports as
Perfetto JSON / JSONL — see ``paddle_tpu.observability``.

Tensor-parallel serving (mp_forward.py): ``Engine(mp=N)`` shards the GPT
weights column-parallel and the paged KV pool's HEAD axis over a 1-D
'mp' mesh (per-chip KV ~ 1/mp; the page table stays global), with a
GATHER-ONLY collective schedule so engine output stays bitwise identical
to the single-chip engine on every rung (``FLAGS_comm_backend``:
mp=gspmd | ring | fused Pallas GEMM+collective kernels). Snapshots are
mp-portable; a supervisor replica is an mp group
(``mp_replica_meshes``).

Topology-elastic serving (elastic.py): ``ServingSupervisor(mp=N)``
watches every CHIP of every mp group (injected
``FaultPlan.serving_chip_loss_at`` schedules + per-chip heartbeats) —
one lost chip re-forms its group over the surviving chips at the
largest viable mp degree via the mp-portable snapshot path (bitwise
resume, zero drops), the fleet runs degraded (router backs off
mid-reform with typed ``retry_after``; shed/autoscale read live
routable capacity), and returning chips grow the group back with zero
drops and zero new traces. A traced per-slot anomaly guard
(``FLAGS_serving_anomaly_policy=quarantine``) resolves a slot whose
logits went non-finite as ``finish_reason="error"`` without poisoning
the shared batch, the prefix cache or a snapshot.

Quantized serving (quant.py + ops/pallas_kernels/quant_gemm.py;
default-OFF behind ``FLAGS_serving_weight_dtype`` /
``FLAGS_serving_kv_dtype`` = bf16|int8|fp8): weight-only int8/fp8 GEMMs
with per-output-channel scales dequantized in the GEMM epilogue (Pallas
quant kernel on TPU; the mp rungs feed the quantized shard straight into
``fused_gemm_ag``), and a quantized paged KV pool with per-PAGE scales
stored beside the page table — the same HBM holds ~2-4x the pages/slots.
Calibrate through the ``paddle_tpu.quantization`` package
(``quant.calibrate`` -> ``QuantSpec`` -> ``Engine(quant=...)``). The
exactness contract becomes "exact at a given dtype config": order
invariance, bitwise kill-and-resume and mp==single-chip bitwise all hold
per config; bf16/bf16 stays bitwise identical to the unquantized engine,
and a dtype-mismatched snapshot restore raises the typed
``QuantDtypeMismatchError`` naming both configs.

Disaggregated prefill/decode serving (kv_transfer.py; opt-in via
``ServingSupervisor(roles=...)`` / ``FLAGS_serving_role``): dedicated
PREFILL workers run only the big-chunk rungs of the ladder over all
their slots (never the [B,1] decode dispatch) and stream each request's
finished KV pages — at the pool's storage dtype, int8/fp8 wires carry
per-page scales — to a decode worker, which installs a bounded number of
pages per decode boundary (``FLAGS_serving_transfer_pages_per_boundary``)
and seats the request exactly like an exact-prefix-cache hit, so the
disaggregated token stream stays BITWISE identical to a single engine,
greedy and sampled, per dtype config. The router is role- and
cache-aware (``Engine.prefix_page_hashes`` is the stable routing key):
a prompt whose prefix a decode worker already caches routes straight
there — no prefill compute, no transfer — and the fleet rebalances
roles when a chip loss strands decode capacity (pure-decode fallback,
zero drops; transfers retain payloads until seated so a decode-worker
death mid-stream re-offers, not recomputes).

Many-model serving (adapters.py; default-off behind
``FLAGS_serving_adapter_slots``): one paged engine serves N low-rank
(LoRA-class) adapter variants of the base model at once. Adapter deltas
live as stacked device slabs (one row per adapter id; id 0 is the
pinned all-zeros base row), each slot's ``adapter_id`` is a TRACED
operand, and the per-slot delta GEMM fuses into the base projection
epilogue — so a mixed-adapter batch reuses the SAME two steady-state
executables (``paged_traces==2`` holds with adapters on), and hot
``load_adapter`` / ``evict_adapter`` / ``swap_adapter`` are pure
content rewrites with ZERO retraces. Attention projections are
deliberately un-adapted (no delta GEMM in the attention inner loop);
adapted requests' prefix-cache keys carry their (adapter id, content
version) while base traffic keeps shared unsalted keys — so adapter ops
never flush the prefix cache (a swap strands the old version's entries
to age out of the LRU) and base-weight swaps keep the full flush.
Per-slot outputs stay bitwise identical to solo
``generate_from_params(adapters=...)`` runs regardless of batch
composition or admission order, greedy and sampled, single-chip and mp.
Requests pick a model via ``Request(adapter=...)`` or the
``FLAGS_serving_tenant_adapters`` tenant mapping; WFQ fairness rotates
across adapters; snapshots and supervisor respawn/reform carry the
resident adapter set.

SLO traffic management (slo.py; all default-off, host-side policy over
the machinery above): priority classes with WFQ tenant fairness and
deadline-driven preemption (``FLAGS_serving_priority_classes``),
graceful load shedding with drain-rate retry-after hints
(``FLAGS_serving_shed``, ``ShedError``), per-tenant token-bucket rate
limits, telemetry-driven autoscaling (``FLAGS_serving_autoscale``), and
zero-downtime weight swaps (``rolling_restart(new_params=)`` /
``Engine.swap_params``; snapshots and results carry ``params_version``).
"""
from .request import (  # noqa: F401
    Request, GenerationResult,
    QUEUED, RUNNING, FINISHED, STOP, LENGTH, EXPIRED, CANCELLED, DROPPED,
    SHED, ERROR,
)
from .scheduler import Scheduler, QueueFullError, ShedError  # noqa: F401
from .slo import (  # noqa: F401
    CLASSES, class_rank, Autoscaler, ShedPolicy, TokenBucket,
)
from .paged_kv import PagedKVPool, PagePoolExhausted, pages_for  # noqa: F401
from .kv_transfer import KVTransfer, PagePayload  # noqa: F401
from .engine import Engine, EngineStoppedError  # noqa: F401
from .mp_forward import replica_mesh  # noqa: F401
from .elastic import FleetTopology, viable_mp  # noqa: F401
from .supervisor import (  # noqa: F401
    ChipLossError, ServingSupervisor, mp_replica_meshes,
)
from .metrics import (  # noqa: F401
    serving_counters, reset_serving_counters, serving_summary,
)
from . import quant  # noqa: F401
from .quant import (  # noqa: F401
    QuantSpec, QuantSpecError, QuantDtypeMismatchError,
)
from .adapters import (  # noqa: F401
    AdapterRegistry, AdapterSpec, UnknownAdapterError,
)
