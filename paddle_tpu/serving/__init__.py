"""paddle_tpu.serving — continuous-batching TPU serving engine.

Iteration-level (Orca-style) scheduling over a fixed B-slot decode batch
with a pooled KV cache and exactly two steady-state executables (bucketed
single-sequence prefill + one-token decode over all slots). See engine.py
for the design; `profiler.serving_counters()` / `serving_summary()` for
observability.
"""
from .request import (  # noqa: F401
    Request, GenerationResult,
    QUEUED, RUNNING, FINISHED, STOP, LENGTH, EXPIRED, CANCELLED,
)
from .scheduler import Scheduler, QueueFullError  # noqa: F401
from .engine import Engine  # noqa: F401
from .metrics import (  # noqa: F401
    serving_counters, reset_serving_counters, serving_summary,
)
