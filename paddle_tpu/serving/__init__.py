"""paddle_tpu.serving — continuous-batching TPU serving engine.

Iteration-level (Orca-style) scheduling over a fixed B-slot decode batch.
The default KV layout is block-PAGED (vLLM-style: fixed-size pages + a
slot->page table, prefix reuse copy-on-write, chunked prefill fused into
the decode step); the PR 5 pooled ``[L, B, Smax, nh, d]`` layout remains
available as the bitwise parity baseline (``kv_layout="pooled"``). See
engine.py for the design; `profiler.serving_counters()` /
`serving_summary()` for observability.
"""
from .request import (  # noqa: F401
    Request, GenerationResult,
    QUEUED, RUNNING, FINISHED, STOP, LENGTH, EXPIRED, CANCELLED,
)
from .scheduler import Scheduler, QueueFullError  # noqa: F401
from .paged_kv import PagedKVPool, PagePoolExhausted, pages_for  # noqa: F401
from .engine import Engine  # noqa: F401
from .metrics import (  # noqa: F401
    serving_counters, reset_serving_counters, serving_summary,
)
