"""Per-slot LoRA-class adapters: many fine-tuned models on ONE paged engine.

A fleet serves thousands of fine-tuned variants of one base checkpoint, not
thousands of checkpoints. Holding a full weight copy per variant multiplies
HBM by the variant count; low-rank deltas (LoRA: W' = W + A @ B * alpha/r)
make each variant a few MB, so N variants fit where two full copies would
not. This module is the residency layer for those deltas:

  * Deltas live STACKED: for each adapted projection, one device slab
    ``A: [L, A_max, K, r]`` / ``B: [L, A_max, r, F]`` holding every resident
    adapter as a ROW (leading L so the slabs ride the per-layer ``lax.scan``
    exactly like the base block weights). Row 0 is the base model and is
    pinned all-zeros — adapter_id 0 means "no delta".
  * ``adapter_id`` is a TRACED per-slot operand, and capacity
    (``slots``/``rank``/targets) is the only static axis. A mixed-adapter
    batch — including base-model rows — therefore shares the engine's two
    steady-state executables (``paged_traces == 2`` holds with adapters on),
    and load/evict/swap are pure DATA updates on fixed-shape slabs: zero
    retraces, same mechanism as ``Engine.swap_params``.
  * Ranks are padded to the spec rank with zero columns/rows and the LoRA
    ``alpha/r`` scale is folded into B at load time (host-side, once), so
    the traced math is a scale-free pair of batched einsums whose rows are
    bitwise independent of batch composition — the property the engine's
    mixed-batch-vs-solo parity gates rely on.
  * Attention projections (``qkv_w``) are NOT adaptable, by construction:
    adapted Q/K/V would put the delta GEMM inside the attention inner loop
    and make even LAYER-0 keys adapter-dependent. Note the residual stream
    still carries the out/up/down deltas into every LATER layer's K/V, so
    an adapted request's prompt pages depend on its delta bits regardless —
    the engine therefore salts adapted requests' prefix-cache keys with
    (adapter id, content version) while base traffic (id 0) keeps unsalted
    keys shared across every tenant. That per-content keying — not KV
    independence — is what lets ``Engine.load_adapter`` / ``evict_adapter``
    / ``swap_adapter`` skip the prefix-cache flush that base-weight swaps
    require (see ``Engine.swap_params``): ops on one adapter cannot
    invalidate base pages or another adapter's pages, and a swap merely
    strands the old version's entries to age out of the LRU.

Under tensor parallelism the B slabs shard with their OUTPUT channels
(``P(None, None, None, "mp")`` — same placement rule as the PR 14
quantization scales) while A slabs replicate, so the delta is computed
locally against the local column block and joins the base product BEFORE
the all-gather; the gather stays pure data movement and the single-chip
bitwise contract is preserved.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

# Projections that may carry a delta. qkv_w is deliberately absent — see
# the module docstring (no delta GEMM inside the attention inner loop) —
# and load() rejects it by name.
TARGETS = ("out_w", "up_w", "down_w")


class UnknownAdapterError(ValueError):
    """Request or registry op named an adapter id outside the configured
    capacity (or adapters are disabled on this engine). Carries
    ``adapter_id`` so a router can surface WHICH id was bad."""

    def __init__(self, adapter_id, message=None):
        super().__init__(
            message or f"unknown adapter id {adapter_id!r}")
        self.adapter_id = adapter_id


@dataclass(frozen=True)
class AdapterSpec:
    """STATIC adapter capacity: the only adapter axis that can retrace.

    ``slots`` loadable adapters (ids 1..slots; id 0 is the pinned base
    row), every rank padded up to ``rank``. Changing capacity changes slab
    shapes — that is a restart-class reconfiguration, exactly like
    ``num_slots`` or ``page_size``, and it keys ``_make_paged_step`` and
    the snapshot meta. Everything else (which adapters are resident, their
    weights, their true ranks) is data."""

    slots: int
    rank: int
    targets: tuple = TARGETS

    def __post_init__(self):
        if int(self.slots) < 1:
            raise ValueError(f"adapter slots must be >= 1, got {self.slots}")
        if int(self.rank) < 1:
            raise ValueError(f"adapter rank must be >= 1, got {self.rank}")
        bad = [t for t in self.targets if t not in TARGETS]
        if bad:
            raise ValueError(
                f"unsupported adapter targets {bad}; attention projections "
                f"cannot be adapted (no delta GEMM in the attention inner "
                f"loop) — supported: {TARGETS}")
        object.__setattr__(self, "slots", int(self.slots))
        object.__setattr__(self, "rank", int(self.rank))
        object.__setattr__(self, "targets", tuple(self.targets))

    def key(self):
        """Hashable identity for jit cache keys and snapshot meta."""
        return (self.slots, self.rank, self.targets)

    @staticmethod
    def resolve(slots, rank, targets=TARGETS):
        """None when adapters are off (slots in {0, None}) — mirrors
        ``QuantSpec.resolve`` so call sites read ``if spec is None``."""
        if not slots:
            return None
        return AdapterSpec(slots=int(slots), rank=int(rank),
                           targets=tuple(targets))


class AdapterRegistry:
    """Residency manager for the stacked delta slabs of ONE engine.

    The HOST numpy mirrors are the source of truth; every mutation
    rewrites the mirror rows and re-places the device slabs (same shapes,
    same dtypes — content-only, so downstream jits never retrace). The
    mirrors also make snapshots trivial: ``state_dict`` is a copy of the
    mirrors plus the residency table."""

    def __init__(self, config, spec, mesh=None):
        if spec is None:
            raise ValueError("AdapterRegistry needs a resolved AdapterSpec")
        self.spec = spec
        H = int(config.hidden_size)
        I = int(config.ffn_mult * config.hidden_size)
        L = int(config.num_layers)
        dims = {"out_w": (H, H), "up_w": (H, I), "down_w": (I, H)}
        self._dims = {t: dims[t] for t in spec.targets}
        self._mesh = mesh
        cap = spec.slots + 1                       # + pinned base row 0
        self._host = {}
        for name, (K, F) in self._dims.items():
            self._host[name] = (
                np.zeros((L, cap, K, spec.rank), np.float32),
                np.zeros((L, cap, spec.rank, F), np.float32))
        # aid -> {"rank": true rank, "alpha": float|None, "version": int}
        self._resident = {}
        self._vc = 0
        self._push()

    # -- device placement ----------------------------------------------------
    def _push(self):
        """(Re)place device slabs from the host mirrors. A replicates; B
        shards with its output channels under mp (the quant-scale rule),
        so the per-chip delta lands on the same column block as the local
        base product."""
        slabs = {}
        for name, (a, b) in self._host.items():
            A, B = jnp.asarray(a), jnp.asarray(b)
            if self._mesh is not None:
                A = jax.device_put(A, NamedSharding(self._mesh, P()))
                B = jax.device_put(
                    B, NamedSharding(self._mesh, P(None, None, None, "mp")))
            slabs[name] = (A, B)
        self._slabs = slabs

    def device_slabs(self):
        """{target: (A [L,cap,K,r], B [L,cap,r,F])} — the traced operands
        a forward pass consumes (leading L rides the layer scan)."""
        return self._slabs

    # -- residency -----------------------------------------------------------
    def _check_id(self, adapter_id):
        aid = int(adapter_id)
        if not 1 <= aid <= self.spec.slots:
            raise UnknownAdapterError(
                adapter_id,
                f"adapter id {adapter_id!r} outside capacity 1.."
                f"{self.spec.slots} (id 0 is the base model and is not "
                f"loadable)")
        return aid

    def load(self, adapter_id, tree, alpha=None, *, replace=False):
        """Make ``adapter_id`` resident from ``tree``: a dict mapping an
        adapted projection name to ``(A [L, K, r_true], B [L, r_true, F])``.
        Targets absent from ``tree`` keep zero deltas. ``alpha`` folds the
        LoRA ``alpha/r_true`` scale into B here, once, on the host — the
        device math is scale-free. Loading over a resident id requires
        ``replace=True`` (the ``swap_adapter`` path) so an accidental id
        collision is an error, not a silent overwrite."""
        aid = self._check_id(adapter_id)
        if aid in self._resident and not replace:
            raise ValueError(
                f"adapter {aid} is already resident; use swap_adapter to "
                f"replace it or evict_adapter first")
        if "qkv_w" in tree:
            raise ValueError(
                "adapter adapts qkv_w: attention projections cannot be "
                "adapted — that would put the delta GEMM inside the "
                "attention inner loop (see serving/adapters.py)")
        bad = [n for n in tree if n not in self._dims]
        if bad:
            raise ValueError(
                f"adapter {aid} adapts unknown/unsupported projections "
                f"{bad}; supported targets: {tuple(self._dims)}")
        L = next(iter(self._host.values()))[0].shape[0]
        true_rank = 0
        staged = {}
        for name, (A, B) in tree.items():
            K, F = self._dims[name]
            A = np.asarray(A, np.float32)
            B = np.asarray(B, np.float32)
            rt = A.shape[-1]
            if A.shape != (L, K, rt) or B.shape != (L, rt, F):
                raise ValueError(
                    f"adapter {aid} target {name}: expected A [L={L}, "
                    f"K={K}, r] and B [L, r, F={F}], got A {A.shape} / "
                    f"B {B.shape}")
            if rt > self.spec.rank:
                raise ValueError(
                    f"adapter {aid} target {name} rank {rt} exceeds "
                    f"configured max rank {self.spec.rank}")
            if alpha is not None:
                B = B * (float(alpha) / float(rt))
            staged[name] = (A, B, rt)
            true_rank = max(true_rank, rt)
        for name, (hA, hB) in self._host.items():
            hA[:, aid] = 0.0
            hB[:, aid] = 0.0
            if name in staged:
                A, B, rt = staged[name]
                hA[:, aid, :, :rt] = A
                hB[:, aid, :rt, :] = B
        self._vc += 1
        self._resident[aid] = {"rank": true_rank,
                               "alpha": None if alpha is None
                               else float(alpha),
                               "version": self._vc}
        self._push()
        return self._vc

    def evict(self, adapter_id):
        """Zero ``adapter_id``'s rows and free its residency slot. The id
        becomes loadable again; queued requests bound to it wait at
        admission until it is reloaded."""
        aid = self._check_id(adapter_id)
        if aid not in self._resident:
            raise UnknownAdapterError(
                adapter_id, f"adapter {aid} is not resident; nothing to "
                            f"evict")
        for hA, hB in self._host.values():
            hA[:, aid] = 0.0
            hB[:, aid] = 0.0
        del self._resident[aid]
        self._push()

    def resident(self, adapter_id):
        aid = int(adapter_id)
        return aid == 0 or aid in self._resident

    def resident_ids(self):
        return tuple(sorted(self._resident))

    def version(self, adapter_id):
        """Monotonic per-adapter content version (0 for the base row) —
        the adapter analogue of ``Engine.params_version``, stamped onto
        requests at admission so a result names exactly which delta bits
        produced it."""
        aid = int(adapter_id)
        if aid == 0:
            return 0
        if aid not in self._resident:
            raise UnknownAdapterError(
                adapter_id, f"adapter {aid} is not resident")
        return self._resident[aid]["version"]

    # -- accounting ----------------------------------------------------------
    def row_bytes(self):
        """HBM bytes one resident adapter occupies (rank-padded rows
        across every adapted projection and layer)."""
        total = 0
        for hA, hB in self._host.values():
            L = hA.shape[0]
            total += L * (hA.shape[2] * hA.shape[3]
                          + hB.shape[2] * hB.shape[3]) * hA.itemsize
        return total

    def delta_bytes(self):
        """Bytes attributable to RESIDENT adapters."""
        return len(self._resident) * self.row_bytes()

    def slab_bytes(self):
        """Total slab capacity bytes ((slots+1) rows, paid up front)."""
        return (self.spec.slots + 1) * self.row_bytes()

    # -- snapshot ------------------------------------------------------------
    def state_dict(self):
        return {
            "spec": self.spec.key(),
            "resident": {int(a): dict(m) for a, m in
                         self._resident.items()},
            "vc": self._vc,
            "host": {n: (a.copy(), b.copy())
                     for n, (a, b) in self._host.items()},
        }

    def load_state_dict(self, state):
        if tuple(state["spec"][2]) != self.spec.targets or \
                (int(state["spec"][0]), int(state["spec"][1])) != \
                (self.spec.slots, self.spec.rank):
            raise ValueError(
                f"adapter capacity mismatch: snapshot "
                f"{tuple(state['spec'])} vs engine {self.spec.key()}")
        for n, (a, b) in state["host"].items():
            hA, hB = self._host[n]
            hA[...] = np.asarray(a, np.float32)
            hB[...] = np.asarray(b, np.float32)
        self._resident = {int(a): dict(m)
                          for a, m in state["resident"].items()}
        self._vc = int(state["vc"])
        self._push()
