"""Block-paged KV pool for the serving engine (vLLM-style PagedAttention
bookkeeping, host side).

The device arrays are ``[L, P, page_size, nh, d]`` — P physical pages shared
by every slot — plus a host-authoritative slot->page table ``[B, MP]``
(uploaded as a traced operand each step, like every other per-slot
quantity). This module owns everything that is pure bookkeeping:

* **free-page allocator** — refcounted physical pages. Page 0 is the
  reserved TRASH page: never handed out, the write target for padding
  lanes and inactive slots, and the read target of unmapped table entries
  (always masked out by the causal mask, so its garbage is never observed).
* **prefix cache** — hash-matched prompt prefixes map the SAME physical
  pages (refcount++) instead of recomputing their KV. Two entry kinds:
  cumulative full-page hashes (``prompt[:k*page_size]`` -> page) and an
  exact-prompt entry (whole prompt -> all its pages, including a partial
  last page). LRU entries are evicted when admission needs pages.
* **copy-on-write** — a slot may only WRITE a page it exclusively owns.
  ``make_writable`` copies any shared page in the write range to a fresh
  page first (the engine executes the device copy); sharing therefore
  never lets one request's decode corrupt another's prefix.

Sharing is bitwise-safe because the KV of a token depends only on the
token prefix before it: two requests whose prompts agree on ``m`` tokens
compute bit-identical K/V for those positions, so reading the cached pages
is indistinguishable from recomputing them. When the engine serves
per-slot adapters (serving/adapters.py) that premise needs one more
input: the adapted out/up/down projections feed the residual stream the
NEXT layer's K/V is computed from, so an adapted request's prompt KV
depends on its delta bits too. The engine therefore passes a ``salt``
(adapter id + content version) into ``lookup``/``register`` — base
traffic (id 0) keeps the unsalted keys and stays shared across every
tenant, while adapted entries only ever match the exact delta content
that produced them (a ``swap_adapter`` strands the old version's
entries, which age out of the LRU; no flush needed).

Quantized pool (``kv_dtype`` int8/fp8, serving/quant.py): the pool
additionally owns per-PAGE dequant scales ``k_scale``/``v_scale``
``[L, P]`` float32, stored host-side beside the page table and uploaded
as traced operands each step. Pages are the quantization block: a CoW
split copies the source page's scale entries with its bytes, prefix
sharing shares a page and its scale, and the trash page keeps scale 1.0
(its garbage is never read unmasked). The values come from calibrated
per-layer |K|/|V| clip ranges divided by the dtype's qmax. All the
sharing arguments above carry over verbatim — two requests with the same
prefix quantize bit-identical pages (same values, same scales).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np


class PagePoolExhausted(RuntimeError):
    """No free physical page available (after cache eviction)."""


def pages_for(tokens, page_size):
    """Number of pages covering `tokens` positions."""
    return -(-int(tokens) // int(page_size))


class PagedKVPool:
    """Host-side page bookkeeping: allocator + slot page table + prefix
    cache. Device KV arrays live in the engine; this class only decides
    WHICH physical page each (slot, logical page) maps to."""

    def __init__(self, num_slots, max_seq_len, page_size, num_pages=0,
                 prefix_cache=True, kv_dtype="bf16", num_layers=0,
                 k_clip=None, v_clip=None, qmax=127.0):
        self.page_size = int(page_size)
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.slot_pages = pages_for(max_seq_len, self.page_size)  # MP
        self.num_slots = int(num_slots)
        auto = self.num_slots * self.slot_pages + 1
        self.num_pages = int(num_pages) or auto
        if self.num_pages < 2:
            raise ValueError("need at least 2 pages (one is the trash page)")
        P = self.num_pages
        # quantized pool: per-PAGE dequant scales beside the table (the
        # page is the quantization block). Static calibration seeds every
        # page of a layer with clip/qmax; the trash page keeps 1.0.
        self.kv_dtype = str(kv_dtype)
        self.k_scale = self.v_scale = None
        if self.kv_dtype != "bf16":
            if not num_layers or k_clip is None or v_clip is None:
                raise ValueError(
                    "a quantized pool needs num_layers and per-layer "
                    "k_clip/v_clip ranges (calibrate via serving.quant)")
            from .quant import page_scales
            k_clip = np.broadcast_to(np.asarray(k_clip, np.float64),
                                     (int(num_layers),))
            v_clip = np.broadcast_to(np.asarray(v_clip, np.float64),
                                     (int(num_layers),))
            self.k_scale = page_scales(k_clip, P, qmax)
            self.v_scale = page_scales(v_clip, P, qmax)
        # slot -> physical page, logical order; 0 = unmapped/trash
        self.table = np.zeros((self.num_slots, self.slot_pages), np.int32)
        self.ref = np.zeros(P, np.int64)
        self.ref[0] = 1                      # trash page pinned forever
        self._free = list(range(P - 1, 0, -1))   # LIFO; pops ascending ids
        self._spare = [None] * self.num_slots    # per-slot CoW reserve page
        self.prefix_cache_enabled = bool(prefix_cache)
        # LRU: key -> page id (full-page entries, key=(b"P", bytes)) or
        # (tuple(pages), plen) (exact entries, key=(b"E", bytes))
        self._cache = OrderedDict()
        # staged pages: request_id -> [pages] held for an incoming KV
        # transfer that has not been seated into a slot yet (disaggregated
        # decode worker). Ref-held like slot pages; adopt_staged moves
        # them into map_slot without touching the refcounts.
        self._staged = {}
        # audit counters (the leak gate sums these)
        self.allocated = 0
        self.freed = 0

    # -- allocator -----------------------------------------------------------
    @property
    def free_count(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return self.num_pages - 1 - len(self._free)

    def _alloc_one(self):
        if not self._free:
            self._evict_until(1)
        if not self._free:
            raise PagePoolExhausted(
                f"no free KV page ({self.num_pages - 1} pages all in use)")
        p = self._free.pop()
        assert self.ref[p] == 0
        self.ref[p] = 1
        self.allocated += 1
        return p

    def try_alloc(self, n):
        """Allocate n pages (evicting LRU cache entries if needed) or None
        if the pool can't cover them; all-or-nothing."""
        if self.free_count < n:
            self._evict_until(n)
        if self.free_count < n:
            return None
        return [self._alloc_one() for _ in range(n)]

    def can_alloc(self, n):
        """Non-destructive capacity check: could ``try_alloc(n)`` succeed?
        True when free pages plus the pages the LRU cache COULD release
        (pages whose every reference is a cache pin) cover ``n``. Unlike
        ``try_alloc`` this never evicts — capacity PROBES (the engine's
        preemption policy polls one per boundary) must not churn the hot
        cache entries they are trying to preserve."""
        n = int(n)
        if self.free_count >= n:
            return True
        cache_refs = {}
        for key, val in self._cache.items():
            for p in ([val] if key[0] == b"P" else list(val[0])):
                cache_refs[p] = cache_refs.get(p, 0) + 1
        reclaimable = sum(1 for p, c in cache_refs.items()
                          if self.ref[p] == c)
        return self.free_count + reclaimable >= n

    def incref(self, pages):
        for p in pages:
            assert p != 0
            self.ref[p] += 1

    def decref(self, pages):
        for p in pages:
            assert p != 0 and self.ref[p] > 0
            self.ref[p] -= 1
            if self.ref[p] == 0:
                self._free.append(int(p))
                self.freed += 1

    # -- slot mapping --------------------------------------------------------
    def map_slot(self, b, pages, spare=None):
        """Bind `pages` (already ref-held by the caller) to slot b's logical
        pages 0..len-1; optionally park a pre-allocated CoW spare page."""
        self.table[b] = 0
        self.table[b, :len(pages)] = pages
        self._spare[b] = spare

    def release_slot(self, b):
        """Unmap slot b: decref every mapped page and the CoW spare."""
        mapped = [int(p) for p in self.table[b] if p != 0]
        self.table[b] = 0
        self.decref(mapped)
        if self._spare[b] is not None:
            self.decref([self._spare[b]])
            self._spare[b] = None

    # -- transfer staging ----------------------------------------------------
    def stage(self, rid, n=1):
        """Allocate ``n`` pages for an in-flight KV transfer and park them
        under ``rid`` until the request is seated. Returns the new pages
        (appended to any already staged) or None when the pool can't cover
        them right now — the transfer waits for the next boundary."""
        got = self.try_alloc(n)
        if got is None:
            return None
        self._staged.setdefault(rid, []).extend(got)
        return got

    def staged_pages(self, rid):
        return list(self._staged.get(rid, ()))

    def adopt_staged(self, rid):
        """Hand the staged pages to the caller for ``map_slot`` — the ref
        each page carries from ``stage`` becomes the slot-table ref."""
        return self._staged.pop(rid, [])

    def release_staged(self, rid):
        """Drop a transfer's staged pages (abort/failure path)."""
        pages = self._staged.pop(rid, None)
        if pages:
            self.decref(pages)

    def clear_staged(self):
        for rid in list(self._staged):
            self.release_staged(rid)

    def make_writable(self, b, start, end):
        """Ensure slot b exclusively owns every page covering positions
        [start, end): any page with refcount > 1 (shared with another slot
        or pinned by the prefix cache) is remapped to a fresh page. Returns
        [(src, dst), ...] physical copies the engine must execute BEFORE
        the step that writes this range (the CoW split)."""
        ps = self.page_size
        copies = []
        for li in range(start // ps, (end - 1) // ps + 1):
            phys = int(self.table[b, li])
            assert phys != 0, f"slot {b} writing unmapped logical page {li}"
            if self.ref[phys] == 1:
                continue
            if self._spare[b] is not None:
                dst = self._spare[b]
                self._spare[b] = None
            else:
                dst = self._alloc_one()
            copies.append((phys, dst))
            self.table[b, li] = dst
            self.decref([phys])
            if self.k_scale is not None:
                # the CoW destination inherits the source page's dequant
                # scales with its bytes (identical under static
                # calibration; the invariant is maintained regardless)
                self.k_scale[:, dst] = self.k_scale[:, phys]
                self.v_scale[:, dst] = self.v_scale[:, phys]
        return copies

    # -- prefix cache --------------------------------------------------------
    def lookup(self, prompt, salt=b""):
        """Longest cached prefix of `prompt` (np.int32 [plen]). Returns
        (matched_tokens, pages, exact): `pages` cover logical pages
        0..ceil(matched/page_size)-1 and are NOT ref-held yet (caller
        increfs). exact=True when the whole prompt matched an exact entry
        (prefill reduces to re-forwarding the last prompt token).
        ``salt`` namespaces the keys (adapter id + version for adapted
        requests — see the module docstring); b"" is the shared base."""
        if not self.prefix_cache_enabled:
            return 0, [], False
        raw = salt + prompt.tobytes()
        hit = self._cache.get((b"E", raw))
        if hit is not None:
            self._cache.move_to_end((b"E", raw))
            pages, plen = hit
            return plen, list(pages), True
        ps = self.page_size
        pages = []
        for j in range(1, len(prompt) // ps + 1):
            key = (b"P", salt + prompt[:j * ps].tobytes())
            page = self._cache.get(key)
            if page is None:
                break
            self._cache.move_to_end(key)
            pages.append(page)
        return len(pages) * ps, pages, False

    def peek_coverage(self, prompt, salt=b""):
        """Longest cached prefix of ``prompt`` in TOKENS, without touching
        LRU recency or refcounts. The supervisor's affinity router probes
        every decode replica with this — a probe that bumped recency would
        let routing traffic keep cold entries pinned hot."""
        if not self.prefix_cache_enabled:
            return 0
        hit = self._cache.get((b"E", salt + prompt.tobytes()))
        if hit is not None:
            return hit[1]
        ps = self.page_size
        n = 0
        for j in range(1, len(prompt) // ps + 1):
            if (b"P", salt + prompt[:j * ps].tobytes()) not in self._cache:
                break
            n += 1
        return n * ps

    def register(self, prompt, b, min_free_frac=0.25, salt=b""):
        """Publish slot b's prompt pages into the cache (cumulative
        full-page hashes + the exact-prompt entry). The engine calls this
        on slot RELEASE (cache-on-free): the prompt KV is complete on
        device and the slot will never write these pages again, so
        registration never forces a copy-on-write against its own owner.
        Already-cached keys are left untouched.

        Under page pressure (free < min_free_frac of the pool) new
        registrations are SKIPPED: pinning a one-off prompt's pages when
        the allocator is tight just evicts hotter entries (the shared
        system prompts every request re-reads) in an endless churn. Hot
        entries registered at low pressure survive — every lookup hit
        refreshes their LRU recency."""
        if not self.prefix_cache_enabled:
            return
        if self.free_count < max(1, int((self.num_pages - 1)
                                        * min_free_frac)):
            return
        ps = self.page_size
        row = self.table[b]
        for j in range(1, len(prompt) // ps + 1):
            key = (b"P", salt + prompt[:j * ps].tobytes())
            if key not in self._cache:
                page = int(row[j - 1])
                self._cache[key] = page
                self.incref([page])
        ekey = (b"E", salt + prompt.tobytes())
        if ekey not in self._cache:
            pages = tuple(int(p) for p in
                          row[:pages_for(len(prompt), ps)])
            self._cache[ekey] = (pages, len(prompt))
            self.incref(pages)

    def _evict_until(self, need_free):
        """Drop LRU cache entries until `need_free` pages are free (or the
        cache is empty). Pages still mapped by running slots survive the
        decref — eviction only forgets the cache's pin."""
        while self._cache and self.free_count < need_free:
            key, val = self._cache.popitem(last=False)
            pages = [val] if key[0] == b"P" else list(val[0])
            self.decref(pages)

    def clear_cache(self):
        self._evict_until(self.num_pages)

    @property
    def cache_entries(self):
        return len(self._cache)

    # -- snapshot ------------------------------------------------------------
    def _meta(self):
        return {"page_size": self.page_size,
                "num_pages": self.num_pages,
                "num_slots": self.num_slots,
                "slot_pages": self.slot_pages,
                "prefix_cache": self.prefix_cache_enabled,
                "kv_dtype": self.kv_dtype}

    def state_dict(self):
        """Serializable snapshot of the WHOLE allocator: slot->page table,
        refcounts, free list, CoW spares, prefix-cache entries (in LRU
        order) and the leak-audit counters. Paired with the engine's device
        KV arrays this reconstructs the paged pool exactly."""
        state = {
            "meta": self._meta(),
            "table": self.table.copy(),
            "ref": self.ref.copy(),
            "free": list(self._free),
            "spare": list(self._spare),
            "cache": [(k, v) for k, v in self._cache.items()],
            "staged": {rid: list(pp) for rid, pp in self._staged.items()},
            "allocated": int(self.allocated),
            "freed": int(self.freed),
        }
        if self.k_scale is not None:
            state["k_scale"] = self.k_scale.copy()
            state["v_scale"] = self.v_scale.copy()
        return state

    def load_state_dict(self, state):
        """Restore a ``state_dict()`` snapshot. The pool geometry must
        match — a snapshot indexes PHYSICAL pages, so restoring into a
        differently-sized pool would alias them."""
        meta = dict(state["meta"])
        meta.setdefault("kv_dtype", "bf16")   # pre-quant snapshots
        mine = self._meta()
        if meta != mine:
            raise ValueError(
                f"paged-pool snapshot geometry {meta} does not match this "
                f"pool {mine}")
        if self.k_scale is not None:
            self.k_scale = np.asarray(state["k_scale"], np.float32).copy()
            self.v_scale = np.asarray(state["v_scale"], np.float32).copy()
        self.table = np.asarray(state["table"], np.int32).copy()
        self.ref = np.asarray(state["ref"], np.int64).copy()
        self._free = [int(p) for p in state["free"]]
        self._spare = [None if s is None else int(s) for s in state["spare"]]
        self._cache = OrderedDict(
            (tuple(k), v) for k, v in state["cache"])
        # pre-disagg snapshots carry no staged pages
        self._staged = {rid: [int(p) for p in pp]
                        for rid, pp in state.get("staged", {}).items()}
        self.allocated = int(state["allocated"])
        self.freed = int(state["freed"])

    # -- audit ---------------------------------------------------------------
    def balance(self):
        """Allocator conservation snapshot for the leak gate: free + in-use
        must always equal num_pages - 1, and refcounts must account for
        every mapped/cached pin."""
        slot_refs = np.zeros(self.num_pages, np.int64)
        for b in range(self.num_slots):
            for p in self.table[b]:
                if p != 0:
                    slot_refs[p] += 1
            if self._spare[b] is not None:
                slot_refs[self._spare[b]] += 1
        for pages in self._staged.values():
            for p in pages:
                slot_refs[p] += 1
        cache_refs = np.zeros(self.num_pages, np.int64)
        for key, val in self._cache.items():
            for p in ([val] if key[0] == b"P" else val[0]):
                cache_refs[p] += 1
        accounted = bool((self.ref[1:] ==
                          (slot_refs + cache_refs)[1:]).all())
        return {
            "num_pages": self.num_pages,
            "free": self.free_count,
            "in_use": self.pages_in_use,
            "conserved": self.free_count + self.pages_in_use
            == self.num_pages - 1,
            "refcounts_accounted": accounted,
            "cache_entries": len(self._cache),
            "allocated": self.allocated,
            "freed": self.freed,
        }
