"""Serving observability counters (profiler counter pattern of
dispatch/comm/mp_comm/fault: a module-level ledger, snapshot via
`profiler.serving_counters()`, one-line `profiler.serving_summary()`).

The two trace counters are the engine's no-recompile audit trail: each jitted
body bumps its counter only when actually TRACED, so after warmup
(one prefill trace per bucket + one decode trace) the counts must freeze —
admission, eviction and sampling-param changes reuse the cached executables.
"""
from __future__ import annotations

import threading
from collections import deque

import numpy as np

_lock = threading.Lock()


def _zero():
    return {
        # request lifecycle
        "submitted": 0, "admitted": 0, "completed": 0, "rejected": 0,
        "expired": 0, "cancelled": 0,
        "finished_stop": 0, "finished_length": 0,
        # executables
        "prefill_calls": 0, "prefill_traces": 0,
        "decode_steps": 0, "decode_traces": 0,
        # paged engine: fused chunk/decode dispatches. paged_traces freezes
        # after warmup at 1 (the [B,1] decode shape) + one [1,rung] trace
        # per chunk-ladder rung actually used; copy_traces at <= 1.
        "paged_steps": 0, "paged_traces": 0,
        "chunk_steps": 0, "prefill_chunks": 0,
        "cow_copies": 0, "copy_traces": 0,
        # prefix cache
        "prefix_lookups": 0, "prefix_hits": 0, "prefix_tokens_reused": 0,
        # page occupancy observed at step boundaries
        "pages_inuse_sum": 0, "pages_inuse_max": 0, "pages_total": 0,
        "page_boundaries": 0,
        # per-prefill padded-token waste: bucket - prompt_len (pooled) or
        # n_chunks*chunk - prefilled_tokens (paged; < chunk per request)
        "prefill_padded_tokens": 0, "prefill_padded_reqs": 0,
        "prefill_padded_max": 0,
        # self-healing: engine snapshots + drain/replay recovery ledger.
        # "dropped" must stay 0 through any preemption/kill/rolling-restart
        # story — every in-flight request either completes or is replayed.
        "snapshots": 0, "snapshot_restores": 0, "preempt_drains": 0,
        "requeued": 0, "replayed": 0, "respawns": 0,
        "stale_failovers": 0, "rolling_restarts": 0, "dropped": 0,
        # serving anomaly guard (FLAGS_serving_anomaly_policy=quarantine):
        # slots whose logits went non-finite, resolved "error" at the
        # boundary with neighbors bitwise-stable
        "anomalies_quarantined": 0,
        # SLO traffic management (serving/slo.py): queued work shed under
        # sustained overload, running slots preempted for an interactive
        # deadline, router-side rate-limit refusals, autoscale actions and
        # hot weight swaps. Queue-wait sums make shed/expired traffic
        # visible: how long refused work sat in queue before the verdict.
        "shed": 0, "preempted": 0, "rate_limited": 0,
        "scale_ups": 0, "scale_downs": 0, "weight_swaps": 0,
        # queue-wait is recorded only for requests refused FROM THE QUEUE
        # (up-front ShedError refusals and mid-flight expiries carry no
        # queue wait), so the means divide by these sample counts, not by
        # the total shed/expired tallies
        "shed_queue_wait_s": 0.0, "shed_queue_waits": 0,
        "expired_queue_wait_s": 0.0, "expired_queue_waits": 0,
        # quantized serving (serving/quant.py): scale-table footprint,
        # per-chip KV bytes one token costs at the engine's dtype config
        # (the capacity-per-chip gauge), and the max logit drift the gate
        # harness measured against the fp engine (0.0 until a harness
        # runs). Dtype LABELS live in _quant_info (counters stay numeric
        # so the Prometheus family export is untouched).
        "quant_scale_bytes": 0, "quant_kv_bytes_per_token": 0,
        "quant_logit_drift_max": 0.0,
        # tensor-parallel serving (serving/mp_forward.py): per-dispatch
        # STATIC collective schedule of the mp rung — wire bytes moved,
        # collectives issued, Pallas fused-kernel dispatches (fused rung
        # only). The same records also feed the training-shared
        # profiler.mp_comm_counters() ledger.
        "mp_steps": 0, "mp_collectives": 0, "mp_wire_bytes": 0,
        "mp_fused_dispatches": 0,
        # disaggregated serving (serving/kv_transfer.py): prefill-worker
        # handoffs, decode-worker transfer installs/seats, wire bytes at
        # the pool's storage dtype, and the router's prefix-affinity
        # decisions. A routed affinity hit means the transfer was SKIPPED
        # — the decode replica's cache already held the pages.
        "prefill_handoffs": 0, "transfers": 0, "transfer_pages": 0,
        "transfer_bytes": 0, "transfer_installs": 0,
        "transfer_time_s": 0.0,
        # KV wire integrity (FLAGS_kv_transfer_crc): payloads whose bytes
        # failed the stamped CRC32 at install time — refused, never seated
        "transfer_crc_refusals": 0,
        "affinity_hits": 0, "disagg_fallbacks": 0, "role_rebalances": 0,
        # page read/write executables for the transfer path (memoized like
        # every other builder — frozen after warmup)
        "read_traces": 0, "write_traces": 0,
        # speculative decoding (FLAGS_serving_speculate_k): draft/verify
        # dispatch tallies, proposed vs accepted draft tokens, and the
        # tokens every speculative boundary actually emitted. The two
        # trace counters are the spec engine's no-recompile audit trail
        # (one draft + one verify executable, memoized per config); on a
        # plain engine the whole family stays 0 — the flags-off gate.
        "draft_dispatches": 0, "verify_dispatches": 0,
        "spec_proposed": 0, "spec_accepted": 0, "spec_tokens_out": 0,
        "spec_draft_traces": 0, "spec_verify_traces": 0,
        # many-model serving (serving/adapters.py): adapter residency ops
        # (hot load / evict / in-place swap — all zero-retrace), admission
        # boundaries a request spent blocked on a non-resident adapter,
        # and the residency gauges (resident count, HBM bytes their
        # rank-padded delta rows occupy). Capacity labels (slots/rank/
        # per-adapter row bytes) live in _adapter_info.
        "adapter_loads": 0, "adapter_evicts": 0, "adapter_swaps": 0,
        "adapter_admit_blocked": 0,
        "adapters_resident": 0, "adapter_delta_bytes": 0,
        # tokens / time
        "tokens_out": 0,
        "decode_time_s": 0.0, "prefill_time_s": 0.0,
        # occupancy: sum of active slots over decode steps / (steps * slots)
        "active_slot_steps": 0, "slot_steps": 0,
        # queue depth observed at step boundaries
        "queue_depth_sum": 0, "queue_depth_max": 0, "boundaries": 0,
    }


_C = _zero()
# mp rung labels (summary display only — counters stay numeric so the
# Prometheus family export is untouched): set by the last mp engine built
_mp_info = {}
# quant dtype labels (summary display): set by the last quantized engine
_quant_info = {}
# adapter capacity labels (summary display + registry export): slot count,
# padded rank, per-adapter row bytes — engine CONFIGURATION like _mp_info,
# set once at build and surviving reset_serving_counters
_adapter_info = {}
# per-adapter token tally (lazy: an adapter id appears once a request it
# served frees its slot) — feeds the per-adapter token-share gauges that
# make WFQ-across-adapters fairness observable
_adapter_tokens = {}
# ring buffers: percentiles track the LAST window of traffic, not the
# first — a long-running server must surface a late latency regression
_MAX_SAMPLES = 65536
_ttft = deque(maxlen=_MAX_SAMPLES)      # seconds
_tok_lat = deque(maxlen=_MAX_SAMPLES)   # per-token decode latency (seconds)
# per-priority-class TTFT rings (lazy: a class appears once it has a
# sample) — the SLO story is per-class: the chaos gate holds the
# INTERACTIVE p99 while best_effort visibly degrades
_ttft_cls = {}


def bump(name, n=1):
    with _lock:
        _C[name] += n


def set_mp_info(mp, backend):
    """Record the mp rung shape for ``serving_summary()`` display (kept
    out of the counters dict: labels are strings, counters numeric)."""
    with _lock:
        _mp_info["mp"] = int(mp)
        _mp_info["backend"] = str(backend)


def set_quant_info(weight_dtype, kv_dtype, scale_bytes=0,
                   kv_bytes_per_token=0):
    """Record the serving dtype config (labels) plus its numeric gauges
    (scale-table bytes, per-chip KV bytes/token) — set at engine build,
    visible in ``serving_summary()`` and, numerically, through the
    registry/Prometheus export."""
    with _lock:
        _quant_info["weight_dtype"] = str(weight_dtype)
        _quant_info["kv_dtype"] = str(kv_dtype)
        _C["quant_scale_bytes"] = int(scale_bytes)
        _C["quant_kv_bytes_per_token"] = int(kv_bytes_per_token)


def set_adapter_info(slots, rank, row_bytes):
    """Record the adapter-capacity config (serving/adapters.py) — slot
    count, padded rank, per-adapter delta row bytes — set once at engine
    build. Configuration labels like ``_mp_info``: they survive
    ``reset_serving_counters`` so a benchmark resetting counters between
    rungs keeps the summary's capacity context."""
    with _lock:
        _adapter_info["slots"] = int(slots)
        _adapter_info["rank"] = int(rank)
        _adapter_info["row_bytes"] = int(row_bytes)


def set_adapter_residency(resident, delta_bytes):
    """Residency gauges, rewritten after every load/evict/swap: how many
    adapters are resident and how many HBM bytes their (rank-padded)
    delta rows actually occupy."""
    with _lock:
        _C["adapters_resident"] = int(resident)
        _C["adapter_delta_bytes"] = int(delta_bytes)


def observe_adapter_tokens(adapter_id, n):
    """Tally ``n`` emitted tokens against ``adapter_id`` (0 = base model)
    — recorded when a slot frees, so the per-adapter token-share gauges
    reflect work actually delivered per model."""
    with _lock:
        _adapter_tokens[int(adapter_id)] = (
            _adapter_tokens.get(int(adapter_id), 0) + int(n))


def observe_logit_drift(drift):
    """Max-track the logit drift a gate harness measured (fp engine vs
    the quantized engine on the same input) — the ``serving_summary()``
    "quant:" segment surfaces it next to the dtype config."""
    with _lock:
        _C["quant_logit_drift_max"] = max(_C["quant_logit_drift_max"],
                                          float(drift))


def add_time(name, dt):
    with _lock:
        _C[name] += dt


def observe_boundary(queue_depth, active, slots):
    with _lock:
        _C["boundaries"] += 1
        _C["queue_depth_sum"] += queue_depth
        _C["queue_depth_max"] = max(_C["queue_depth_max"], queue_depth)
        _C["active_slot_steps"] += active
        _C["slot_steps"] += slots


def observe_pages(in_use, total):
    with _lock:
        _C["page_boundaries"] += 1
        _C["pages_inuse_sum"] += in_use
        _C["pages_inuse_max"] = max(_C["pages_inuse_max"], in_use)
        _C["pages_total"] = total


def observe_prefill_waste(padded_tokens):
    with _lock:
        _C["prefill_padded_reqs"] += 1
        _C["prefill_padded_tokens"] += padded_tokens
        _C["prefill_padded_max"] = max(_C["prefill_padded_max"],
                                       padded_tokens)


def observe_ttft(seconds, priority=None):
    with _lock:
        _ttft.append(seconds)
        if priority is not None:
            _ttft_cls.setdefault(priority,
                                 deque(maxlen=_MAX_SAMPLES)).append(seconds)


def observe_queue_wait(seconds, outcome):
    """Queue-wait of a request refused from the QUEUE (``outcome`` is
    "shed" or "expired"): the ledger shows how long refused traffic sat
    before the verdict, so shed/expired work is visible in
    ``serving_summary()`` instead of vanishing."""
    with _lock:
        _C[f"{outcome}_queue_wait_s"] += max(0.0, seconds)
        _C[f"{outcome}_queue_waits"] += 1


def observe_token_latency(seconds, n=1):
    with _lock:
        _tok_lat.append(seconds / max(n, 1))


def recent_ttft_p50(n=256):
    """p50 over the last ``n`` TTFT samples (None when empty) — the cheap
    live estimate the preemption margin derives from, without computing
    the full serving_counters() snapshot every boundary."""
    with _lock:
        if not _ttft:
            return None
        tail = list(_ttft)[-int(n):]
    return float(np.percentile(tail, 50))


def recent_ttft_p99(n=512):
    """p99 over the last ``n`` TTFT samples (None when empty) — the live
    latency gauge the autoscaler compares against its SLO."""
    with _lock:
        if not _ttft:
            return None
        tail = list(_ttft)[-int(n):]
    return float(np.percentile(tail, 99))


def serving_counters():
    """Snapshot of the serving ledger plus derived rates: ttft p50/p99,
    per-token latency, tokens/s over decode time, slot occupancy, mean
    queue depth."""
    with _lock:
        out = dict(_C)
        ttft = list(_ttft)
        lat = list(_tok_lat)
        cls_samples = {c: list(v) for c, v in _ttft_cls.items()}
        ad_tokens = dict(_adapter_tokens)
    out["ttft_p50"] = float(np.percentile(ttft, 50)) if ttft else None
    out["ttft_p99"] = float(np.percentile(ttft, 99)) if ttft else None
    for c, v in cls_samples.items():
        out[f"ttft_p50_{c}"] = float(np.percentile(v, 50))
        out[f"ttft_p99_{c}"] = float(np.percentile(v, 99))
    out["shed_queue_wait_mean"] = (
        out["shed_queue_wait_s"] / out["shed_queue_waits"]
        if out["shed_queue_waits"] else 0.0)
    out["expired_queue_wait_mean"] = (
        out["expired_queue_wait_s"] / out["expired_queue_waits"]
        if out["expired_queue_waits"] else 0.0)
    out["token_latency_p50"] = float(np.percentile(lat, 50)) if lat else None
    # tokens_out counts prefill-emitted first tokens too, so the rate
    # divides by total executable time (prefill + decode), not decode alone
    exec_t = out["decode_time_s"] + out["prefill_time_s"]
    out["tokens_per_s"] = out["tokens_out"] / exec_t if exec_t > 0 else 0.0
    out["occupancy"] = (out["active_slot_steps"] / out["slot_steps"]
                        if out["slot_steps"] else 0.0)
    out["queue_depth_mean"] = (out["queue_depth_sum"] / out["boundaries"]
                               if out["boundaries"] else 0.0)
    out["page_occupancy"] = (
        out["pages_inuse_sum"] / (out["page_boundaries"] * out["pages_total"])
        if out["page_boundaries"] and out["pages_total"] else 0.0)
    out["prefix_hit_rate"] = (out["prefix_hits"] / out["prefix_lookups"]
                              if out["prefix_lookups"] else 0.0)
    out["prefill_waste_mean"] = (
        out["prefill_padded_tokens"] / out["prefill_padded_reqs"]
        if out["prefill_padded_reqs"] else 0.0)
    # speculative decoding: what fraction of proposed draft tokens the
    # verify pass accepted, and how many tokens ONE dispatch buys on
    # average (draft + verify both count — the honest amortization; the
    # plain engine's equivalent is exactly 1.0)
    out["accept_rate"] = (out["spec_accepted"] / out["spec_proposed"]
                          if out["spec_proposed"] else 0.0)
    spec_disp = out["draft_dispatches"] + out["verify_dispatches"]
    out["tokens_per_dispatch"] = (out["spec_tokens_out"] / spec_disp
                                  if spec_disp else 0.0)
    # many-model serving: per-adapter token counts and shares (fraction of
    # all adapter-attributed tokens, base id 0 included) — the WFQ
    # fairness gauges. Keys appear only for adapters that emitted tokens.
    ad_total = sum(ad_tokens.values())
    for aid, n in sorted(ad_tokens.items()):
        out[f"adapter_tokens_{aid}"] = n
        out[f"adapter_token_share_{aid}"] = (n / ad_total if ad_total
                                             else 0.0)
    return out


def reset_serving_counters():
    global _C
    with _lock:
        _C = _zero()
        _ttft.clear()
        _tok_lat.clear()
        _ttft_cls.clear()
        _adapter_tokens.clear()
        # _mp_info / _adapter_info survive on purpose: they are engine
        # CONFIGURATION (the live rung/degree/capacity labels), not
        # counters — a benchmark resetting counters between rungs must
        # not blank the summary's config labels


_PREFIX_KEYS = ("prefix_lookups", "prefix_hits", "prefix_tokens_reused")


def seed_prefix_counters(snapshot_counters):
    """Counter-lifecycle unification for prefix-cache stats across
    ``load_state_dict(restore_metrics=False)``: the restored engine brings
    its prefix-cache ENTRIES back (they live in the pool snapshot), but
    under restore_metrics=False the hit/reuse counters describing them
    stayed at whatever the live ledger holds — on a fresh respawn that is
    zero, so hit-rate reporting diverged from the recovery ledger (which
    does record the restore). Seed the prefix family from the snapshot
    ONLY when the live family is untouched — a warm engine restoring a
    snapshot (preempt-drain resume on the same process) keeps its own
    live counts exactly like every other serving counter. Returns True
    when seeding happened."""
    with _lock:
        if any(_C[k] for k in _PREFIX_KEYS):
            return False
        for k in _PREFIX_KEYS:
            _C[k] = snapshot_counters.get(k, 0)
        return True


def export_state():
    """Serializable snapshot of the raw ledger (counters + latency ring
    buffers) for ``Engine.state_dict()`` — a restored engine can carry its
    SLO history across a restart instead of reporting from zero."""
    with _lock:
        return {"counters": dict(_C), "ttft": list(_ttft),
                "token_latency": list(_tok_lat),
                "ttft_cls": {c: list(v) for c, v in _ttft_cls.items()},
                "adapter_tokens": dict(_adapter_tokens)}


def import_state(state):
    """Replace the ledger with an ``export_state()`` snapshot. Unknown
    keys from older snapshots are dropped; keys added since are zeroed."""
    global _C
    with _lock:
        _C = _zero()
        for k, v in state.get("counters", {}).items():
            if k in _C:
                _C[k] = v
        _ttft.clear()
        _ttft.extend(state.get("ttft", ()))
        _tok_lat.clear()
        _tok_lat.extend(state.get("token_latency", ()))
        _ttft_cls.clear()
        for c, v in state.get("ttft_cls", {}).items():
            _ttft_cls[c] = deque(v, maxlen=_MAX_SAMPLES)
        _adapter_tokens.clear()
        for aid, n in state.get("adapter_tokens", {}).items():
            # JSON round-trips stringify int keys; normalize back
            _adapter_tokens[int(aid)] = int(n)


def serving_summary():
    """One-line human-readable serving report."""
    c = serving_counters()
    ttft = ("n/a" if c["ttft_p50"] is None
            else f"{c['ttft_p50'] * 1e3:.1f}/{c['ttft_p99'] * 1e3:.1f}ms")
    paged = ""
    if c["paged_steps"]:
        paged = (f"  pages: {c['page_occupancy'] * 100:.1f}% of "
                 f"{c['pages_total']} used "
                 f"(max {c['pages_inuse_max']})  "
                 f"prefix-hit: {c['prefix_hit_rate'] * 100:.1f}% "
                 f"({c['prefix_tokens_reused']} tok reused)  "
                 f"chunk-interleaved: {c['chunk_steps']}/{c['paged_steps']} "
                 f"steps  cow: {c['cow_copies']}")
    waste = ""
    if c["prefill_padded_reqs"]:
        waste = (f"  prefill-waste: {c['prefill_waste_mean']:.1f} "
                 f"avg/{c['prefill_padded_max']} max pad tok")
    heal = ""
    if any(c[k] for k in ("snapshots", "snapshot_restores", "preempt_drains",
                          "requeued", "replayed", "respawns",
                          "stale_failovers", "rolling_restarts", "dropped",
                          "anomalies_quarantined")):
        heal = (f"  self-heal: {c['snapshots']} snap / "
                f"{c['snapshot_restores']} restore  "
                f"drains: {c['preempt_drains']}  "
                f"requeued/replayed: {c['requeued']}/{c['replayed']}  "
                f"respawns: {c['respawns']} "
                f"({c['stale_failovers']} stale-hb)  "
                f"dropped: {c['dropped']}"
                + (f"  anomalies-quarantined: {c['anomalies_quarantined']}"
                   if c["anomalies_quarantined"] else ""))
    quant = ""
    with _lock:
        qinfo = dict(_quant_info)
    if qinfo:
        drift = (f"  drift-max: {c['quant_logit_drift_max']:.2e}"
                 if c["quant_logit_drift_max"] else "")
        quant = (f"  quant: w={qinfo.get('weight_dtype', '?')} "
                 f"kv={qinfo.get('kv_dtype', '?')}  "
                 f"scales: {c['quant_scale_bytes']}B  "
                 f"kv-bytes/tok: {c['quant_kv_bytes_per_token']}{drift}")
    spec = ""
    if c["verify_dispatches"]:
        spec = (f"  spec: accept: {c['accept_rate'] * 100:.1f}% "
                f"({c['spec_accepted']}/{c['spec_proposed']})  "
                f"tok/dispatch: {c['tokens_per_dispatch']:.2f}  "
                f"draft/verify: {c['draft_dispatches']}/"
                f"{c['verify_dispatches']}")
    mp = ""
    if c["mp_steps"]:
        with _lock:
            info = dict(_mp_info)
        mp = (f"  mp: {info.get('backend', '?')}x{info.get('mp', '?')}  "
              f"wire: {c['mp_wire_bytes'] / 1e6:.2f}MB over "
              f"{c['mp_collectives']} collectives in {c['mp_steps']} "
              f"dispatches  fused-dispatches: {c['mp_fused_dispatches']}")
    disagg = ""
    if any(c[k] for k in ("prefill_handoffs", "transfers", "affinity_hits",
                          "disagg_fallbacks", "role_rebalances")):
        disagg = (f"  disagg: {c['prefill_handoffs']} handoffs / "
                  f"{c['transfers']} transfers "
                  f"({c['transfer_pages']} pages, "
                  f"{c['transfer_bytes'] / 1e6:.2f}MB, "
                  f"{c['transfer_time_s'] * 1e3:.0f}ms)  "
                  f"affinity-hits: {c['affinity_hits']}  "
                  f"fallbacks: {c['disagg_fallbacks']}  "
                  f"role-rebalances: {c['role_rebalances']}")
    slo = ""
    if any(c[k] for k in ("shed", "preempted", "rate_limited", "scale_ups",
                          "scale_downs", "weight_swaps")):
        cls_p99 = "  ".join(
            f"{k[len('ttft_p99_'):]}-p99: {c[k] * 1e3:.1f}ms"
            for k in sorted(c) if k.startswith("ttft_p99_"))
        slo = (f"  slo: {c['shed']} shed "
               f"({c['shed_queue_wait_mean'] * 1e3:.0f}ms avg wait)  "
               f"preempted: {c['preempted']}  "
               f"rate-limited: {c['rate_limited']}  "
               f"scale: +{c['scale_ups']}/-{c['scale_downs']}  "
               f"weight-swaps: {c['weight_swaps']}"
               + (f"  {cls_p99}" if cls_p99 else ""))
    adapters = ""
    with _lock:
        ainfo = dict(_adapter_info)
        ad_tokens = dict(_adapter_tokens)
    if ainfo and (c["adapters_resident"] or c["adapter_loads"]
                  or c["adapter_evicts"] or c["adapter_swaps"]
                  or c["adapter_admit_blocked"]):
        ad_total = sum(ad_tokens.values())
        top = sorted(ad_tokens.items(), key=lambda kv: -kv[1])[:4]
        share = " ".join(
            f"a{aid}:{n / ad_total * 100:.0f}%" for aid, n in top
            if ad_total) if top else ""
        adapters = (f"  adapters: {c['adapters_resident']}/"
                    f"{ainfo.get('slots', '?')} resident "
                    f"(r{ainfo.get('rank', '?')}, "
                    f"{c['adapter_delta_bytes'] / 1e6:.2f}MB delta)  "
                    f"load/evict/swap: {c['adapter_loads']}/"
                    f"{c['adapter_evicts']}/{c['adapter_swaps']}  "
                    f"admit-blocked: {c['adapter_admit_blocked']}"
                    + (f"  tok-share: {share}" if share else ""))
    sdc = ""
    from ..distributed import integrity as _integrity
    s = _integrity.sdc_counters()
    if s["audits"] or s["crc_checks"] or c["transfer_crc_refusals"]:
        sdc = (f"  sdc: audits: {s['audits']} "
               f"({s['audit_failures']} failed)  "
               f"crc: {s['crc_checks']} checked / "
               f"{s['crc_refusals']} refused")
    return (f"requests: {c['submitted']} submitted / {c['completed']} done "
            f"({c['expired']} expired, {c['rejected']} rejected)  "
            f"tokens: {c['tokens_out']}  tokens/s: {c['tokens_per_s']:.1f}  "
            f"ttft p50/p99: {ttft}  occupancy: {c['occupancy'] * 100:.1f}%  "
            f"queue: {c['queue_depth_mean']:.1f} avg/{c['queue_depth_max']} max  "
            f"executables: {c['prefill_traces']} prefill + "
            f"{c['decode_traces']} decode + {c['paged_traces']} paged"
            f"{paged}{quant}{spec}{mp}{adapters}{disagg}{waste}{slo}{heal}"
            f"{sdc}")
