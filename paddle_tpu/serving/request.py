"""Serving request/result types.

A `Request` is one user generation job; the engine assigns it a slot in the
fixed decode batch, streams tokens to `on_token` as they are produced, and
resolves it into a `GenerationResult`. Sampling params (temperature/top_p,
per-request seed) are TRACED per-slot operands of the shared decode
executable, so any mix of greedy and sampled requests batches together
without recompiling.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field

import numpy as np

from ..models.generation import _normalize_stop

_req_ids = itertools.count()

# request lifecycle states
QUEUED = "queued"
RUNNING = "running"
FINISHED = "finished"

# finish reasons
STOP = "stop"          # produced a stop token
LENGTH = "length"      # hit max_new_tokens
EXPIRED = "expired"    # deadline passed before/while running
CANCELLED = "cancelled"
DROPPED = "dropped"    # supervisor had no live replica left to replay on
SHED = "shed"          # load-shed under sustained overload (retry_after set)
ERROR = "error"        # anomaly guard quarantined the slot (non-finite
                       # logits — bad weights / corrupted KV / flaky chip)


@dataclass(eq=False)  # identity equality: deque.remove/cancel compare BY
class Request:        # OBJECT, and field-wise eq would compare numpy prompts
    """One generation job. ``prompt`` is a 1-D int sequence. ``eos_token_id``
    is the scalar alias for ``stop_token_ids`` (both accepted, merged).
    ``top_k`` must match the engine's static top_k (it shapes the top_k
    kernel and would recompile per value). ``deadline_s`` is a relative
    deadline from submit time: expired requests are failed at the next step
    boundary instead of occupying a slot."""
    prompt: object
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_p: float | None = None
    top_k: int | None = None
    eos_token_id: int | None = None
    stop_token_ids: object = None
    seed: int = 0
    deadline_s: float | None = None
    on_token: object = None          # callback(request, token_id)
    # SLO class + tenant (serving/slo.py). Policy-only: with
    # FLAGS_serving_priority_classes off both are carried but never read,
    # so default traffic is byte-identical to the pre-SLO engine. Classes:
    # "interactive" (rank 0, may preempt), "batch" (default),
    # "best_effort" (preempted and shed first).
    priority: str = "batch"
    tenant: str = "default"
    # speculative-decode opt-out ("auto" | "off"). Policy-only, like
    # priority: on a speculative engine, "off" pins this request to plain
    # one-token decode (nprop=0 inside the SAME fused verify dispatch —
    # a latency-sensitive tenant trades throughput for the tightest
    # inter-token gap); on a plain engine it is carried but never read.
    speculate: str = "auto"
    # adapter id to serve this request with (serving/adapters.py): 0 = the
    # base model, 1..slots = a loaded low-rank delta, None = "resolve from
    # the tenant mapping at submit" (FLAGS_serving_tenant_adapters;
    # unmapped tenants get the base model). Submit raises a typed
    # UnknownAdapterError for ids outside the engine's capacity; a merely
    # non-resident id queues and blocks at admission until loaded.
    adapter: int | None = None

    # -- engine-managed state ------------------------------------------------
    request_id: int = field(default_factory=lambda: next(_req_ids))
    state: str = field(default=QUEUED)
    tokens: list = field(default_factory=list)
    slot: int | None = field(default=None)
    submit_t: float | None = field(default=None)
    first_token_t: float | None = field(default=None)
    finish_t: float | None = field(default=None)
    finish_reason: str | None = field(default=None)
    callback_error: object = field(default=None)  # first on_token exception
    requeue_count: int = field(default=0)         # drain/replay round trips
    # weight version this request's tokens were produced under (stamped at
    # admission; re-stamped when a requeue recomputes from scratch on a
    # swapped replica, so the RESULT is always single-version consistent)
    params_version: int | None = field(default=None)
    # per-adapter content version the tokens were produced under (stamped
    # at admission from AdapterRegistry.version; 0 for the base model) —
    # the adapter analogue of params_version
    adapter_version: int | None = field(default=None)
    # retry-after hint attached when load shedding resolves this request
    # (seconds until the shed backlog should have drained)
    retry_after: float | None = field(default=None)
    # span trace context (observability.RequestTrace) — attached by the
    # engine when FLAGS_serving_trace is on, None otherwise (untraced
    # requests pay one attribute check per recording site)
    trace: object = field(default=None)

    def __post_init__(self):
        self.prompt = np.asarray(
            self.prompt._data if hasattr(self.prompt, "_data") else self.prompt,
            np.int32).reshape(-1)
        if self.prompt.shape[0] == 0:
            # an empty prompt would read logits at the pad token (the
            # prefill's last_index clamps to 0) — plausible-looking output
            # conditioned on nothing the user sent
            raise ValueError("prompt must be non-empty")
        if self.max_new_tokens < 0:
            raise ValueError(
                f"max_new_tokens must be >= 0, got {self.max_new_tokens}")
        if self.do_sample and self.temperature <= 0:
            # _mask_logits divides by the (clamped) temperature — a 0/neg
            # value would push every logit to +/-inf and sample garbage;
            # greedy requests never touch it, so they pass through
            raise ValueError(
                f"temperature must be > 0 for sampled requests, got "
                f"{self.temperature} (use do_sample=False for greedy)")
        self.stop_token_ids = _normalize_stop(
            self.eos_token_id, self.stop_token_ids) or ()
        if self.top_k == 0:            # generate's "disabled" spelling
            self.top_k = None
        from .slo import class_rank
        class_rank(self.priority)      # validate eagerly: fail at submit
        self.tenant = str(self.tenant)
        if self.speculate not in ("auto", "off"):
            raise ValueError(
                f"speculate must be 'auto' or 'off', got "
                f"{self.speculate!r}")
        if self.adapter is not None:
            self.adapter = int(self.adapter)
            if self.adapter < 0:
                from .adapters import UnknownAdapterError
                raise UnknownAdapterError(
                    self.adapter,
                    f"adapter id must be >= 0 (0 = base model), got "
                    f"{self.adapter}")

    @property
    def prompt_len(self):
        return int(self.prompt.shape[0])

    @property
    def deadline(self):
        """Absolute deadline (perf_counter clock), or None."""
        if self.deadline_s is None or self.submit_t is None:
            return None
        return self.submit_t + self.deadline_s

    def expired(self, now):
        """THE deadline-boundary predicate: a request is expired from the
        first instant ``now >= deadline`` (the deadline itself is outside
        the allowed window). Every site — queue expiry, admission,
        mid-flight eviction — routes through here, so the boundary
        semantics cannot drift between call sites again."""
        dl = self.deadline
        return dl is not None and now >= dl

    @property
    def class_rank(self):
        from .slo import class_rank
        return class_rank(self.priority)

    def _emit(self, token):
        self.tokens.append(int(token))
        if self.first_token_t is None:
            self.first_token_t = time.perf_counter()
        if self.on_token is not None:
            try:
                self.on_token(self, int(token))
            except Exception as e:    # noqa: BLE001 — user callback
                # A broken client stream must not unwind step(): the KV
                # cache and PRNG keys advanced BEFORE this emission, so an
                # escaping error would leave host _tok/_pos stale and the
                # next step() would re-feed old tokens at old positions
                # (duplicated token, diverged sampled stream). Disable the
                # callback, record the error, finish the request normally.
                self.callback_error = e
                self.on_token = None
                import warnings
                warnings.warn(
                    f"request {self.request_id}: on_token callback raised "
                    f"{type(e).__name__}: {e}; streaming disabled for this "
                    f"request (see GenerationResult.callback_error)")

    def _finish(self, reason):
        self.state = FINISHED
        self.finish_reason = reason
        self.finish_t = time.perf_counter()

    # -- drain / replay ------------------------------------------------------
    def _requeue(self):
        """Reset generation progress for a drain/preemption requeue. The
        ORIGINAL ``submit_t`` (arrival) is kept, so the deadline keeps
        ticking from first submission and TTFT never restarts; emitted
        tokens are cleared — a replay recomputes them deterministically
        (same seed / per-slot stream), so streaming is at-least-once but the
        final token list is bitwise what an uninterrupted run produces.
        ``first_token_t`` survives when a token was already streamed (the
        user saw it); otherwise TTFT spans the recovery gap too."""
        self.state = QUEUED
        self.slot = None
        self.tokens = []
        self.finish_t = None
        self.finish_reason = None
        self.requeue_count += 1
        if self.trace is not None:
            self.trace.instant("requeue", round=self.requeue_count)

    def replay_copy(self):
        """Fresh QUEUED copy for replaying on ANOTHER engine after its
        owner died: same ``request_id``, prompt, sampling params, seed,
        ``on_token`` callback and — critically — the ORIGINAL ``submit_t``
        and relative deadline (a replayed request must not be granted a
        fresh deadline, and its TTFT counts from first submission)."""
        r = Request(self.prompt.copy(), max_new_tokens=self.max_new_tokens,
                    do_sample=self.do_sample, temperature=self.temperature,
                    top_p=self.top_p, top_k=self.top_k,
                    stop_token_ids=self.stop_token_ids, seed=self.seed,
                    deadline_s=self.deadline_s, on_token=self.on_token,
                    priority=self.priority, tenant=self.tenant,
                    speculate=self.speculate, adapter=self.adapter)
        r.request_id = self.request_id
        r.submit_t = self.submit_t
        r.first_token_t = self.first_token_t
        r.requeue_count = self.requeue_count + 1
        if self.trace is not None:
            # the replay inherits the whole span history (queue wait and
            # any tokens the dead owner already produced are part of THIS
            # request's latency story) plus a failover hop marker
            r.trace = self.trace.copy()
            r.trace.instant("replay", round=r.requeue_count)
        return r

    # -- snapshot ------------------------------------------------------------
    def to_state(self):
        """Serializable snapshot of the request (engine state_dict leaf).
        ``on_token`` callbacks are NOT serialized (arbitrary closures don't
        survive a process boundary); a restored request finishes without
        streaming — its result still carries every token."""
        return {
            "prompt": self.prompt.copy(),
            "max_new_tokens": int(self.max_new_tokens),
            "do_sample": bool(self.do_sample),
            "temperature": float(self.temperature),
            "top_p": None if self.top_p is None else float(self.top_p),
            "top_k": None if self.top_k is None else int(self.top_k),
            "stop_token_ids": tuple(self.stop_token_ids or ()),
            "seed": int(self.seed),
            "deadline_s": (None if self.deadline_s is None
                           else float(self.deadline_s)),
            "priority": self.priority,
            "tenant": self.tenant,
            "speculate": self.speculate,
            "adapter": None if self.adapter is None else int(self.adapter),
            "adapter_version": (None if self.adapter_version is None
                                else int(self.adapter_version)),
            "params_version": (None if self.params_version is None
                               else int(self.params_version)),
            "request_id": int(self.request_id),
            "state": self.state,
            "tokens": list(self.tokens),
            "slot": None if self.slot is None else int(self.slot),
            "submit_t": self.submit_t,
            "first_token_t": self.first_token_t,
            "finish_t": self.finish_t,
            "finish_reason": self.finish_reason,
            "requeue_count": int(self.requeue_count),
            "trace": None if self.trace is None else self.trace.to_state(),
        }

    @classmethod
    def from_state(cls, state):
        """Rebuild a request from ``to_state()`` output. Bumps the global
        request-id counter past the restored id so requests created AFTER a
        cross-process restore can never collide with restored ones."""
        r = cls(state["prompt"], max_new_tokens=state["max_new_tokens"],
                do_sample=state["do_sample"], temperature=state["temperature"],
                top_p=state["top_p"], top_k=state["top_k"],
                stop_token_ids=state["stop_token_ids"], seed=state["seed"],
                deadline_s=state["deadline_s"],
                priority=state.get("priority", "batch"),
                tenant=state.get("tenant", "default"),
                speculate=state.get("speculate", "auto"),
                adapter=state.get("adapter"))
        r.params_version = state.get("params_version")
        r.adapter_version = state.get("adapter_version")
        r.request_id = int(state["request_id"])
        global _req_ids
        floor = next(_req_ids)
        if floor <= r.request_id:
            _req_ids = itertools.count(r.request_id + 1)
        r.state = state["state"]
        r.tokens = list(state["tokens"])
        r.slot = state["slot"]
        r.submit_t = state["submit_t"]
        r.first_token_t = state["first_token_t"]
        r.finish_t = state["finish_t"]
        r.finish_reason = state["finish_reason"]
        r.requeue_count = int(state.get("requeue_count", 0))
        if state.get("trace") is not None:
            from ..observability import RequestTrace
            r.trace = RequestTrace.from_state(r.request_id, state["trace"])
        return r

    def result(self):
        if self.state != FINISHED:
            raise RuntimeError(
                f"request {self.request_id} not finished (state={self.state})")
        return GenerationResult(
            request_id=self.request_id,
            prompt=self.prompt,
            tokens=list(self.tokens),
            finish_reason=self.finish_reason,
            ttft=(None if self.first_token_t is None or self.submit_t is None
                  else self.first_token_t - self.submit_t),
            latency=(None if self.finish_t is None or self.submit_t is None
                     else self.finish_t - self.submit_t),
            callback_error=self.callback_error,
            priority=self.priority,
            tenant=self.tenant,
            params_version=self.params_version,
            adapter=0 if self.adapter is None else self.adapter,
            adapter_version=self.adapter_version,
            retry_after=self.retry_after,
        )


@dataclass
class GenerationResult:
    """Resolved output of one Request. ``tokens`` are the NEW tokens only
    (stop token included when one fired, matching `generate`'s output);
    ``sequence`` is prompt + tokens."""
    request_id: int
    prompt: np.ndarray
    tokens: list
    finish_reason: str
    ttft: float | None = None
    latency: float | None = None
    callback_error: object = None    # first on_token exception, if any
    priority: str = "batch"
    tenant: str = "default"
    # weight version the tokens were produced under (hot-swap audit trail);
    # None when the request never reached a slot
    params_version: int | None = None
    # adapter id the request was served with (0 = base model) and the
    # per-adapter content version its tokens were produced under
    adapter: int = 0
    adapter_version: int | None = None
    # seconds-until-retry hint on finish_reason == "shed"
    retry_after: float | None = None

    @property
    def sequence(self):
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def tokens_per_s(self):
        if not self.tokens or not self.latency:
            return 0.0
        return len(self.tokens) / self.latency
