"""Tensor-parallel (mp-sharded) serving forward for the paged engine.

The training stack shards the mp axis Megatron-style: column-parallel
qkv/up, ROW-parallel out/down with a cross-chip reduction per block. A
reduction re-associates the contraction sum, so its result is only
numerically — not bitwise — equal to the single-chip matmul. Serving's
contract is stronger: engine output must be BITWISE identical to
single-chip ``generate_from_params`` for any admission order, greedy and
sampled. This module therefore runs a GATHER-ONLY schedule:

* every GEMM shards its OUTPUT dim (column-parallel with head-major qkv,
  ``out_w``/``down_w``/``head_w`` column-sharded too) and keeps the FULL
  contraction — each chip's block is bitwise equal to a column slice of
  the unsharded GEMM;
* the only collectives are all-gathers (pure data movement): the
  attention context and FFN activation before their full-contraction
  projections, each projection's output blocks, the feature-sharded
  embedding, and (vocab-divisible) the logits;
* the paged KV pool shards its HEAD axis — per chip ``[L, P, page,
  nh/mp, d]``, ~1/mp of the KV bytes — while the host-authoritative page
  table stays global: a page id addresses ``(chip, page)`` implicitly
  through the head shard, so the allocator, prefix cache and CoW
  machinery are untouched.

The exactness premium is bounded: per block the schedule moves one extra
activation-sized gather versus the two all-reduces of the Megatron
schedule, while per-chip GEMM FLOPs and KV-read bytes are 1/mp either
way — and per-token decode activations are tiny next to the weight and
KV traffic the sharding removes.

Three collective rungs (``FLAGS_comm_backend``, "mp=..."), all
bitwise-identical because the backend only moves bytes differently:

* ``gspmd`` (default) — whole ``lax.all_gather`` collectives, the
  schedule the partitioner would emit for this gather-only program;
* ``ring`` — each all-gather decomposes into mp-1 ``ppermute`` hops;
* ``fused`` — Pallas in-kernel rings: the column-parallel projections
  ride ``fused_gemm_ag`` (the GEMM's output blocks enter the ring
  straight from the epilogue, no HBM round trip) and the data gathers
  ride ``fused_ag_bucket``. CPU tier-1 runs the SAME kernels in
  interpret mode on the 8-virtual-device mesh
  (``dist_env.create_single_axis_mesh('mp', n)``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..distributed.env import shard_map_compat
from ..models.generation import _final_ln
from ..models.gpt import ln_fp32
from ..ops.pallas_kernels.quant_gemm import lora_delta, compose_delta
from .paged_attention import paged_attention_read, paged_kv_scatter

KV_SPEC = P(None, None, None, "mp", None)   # [L, P, page, nh@mp, d]


def serving_param_specs(mp_cfg, quant_weights=False):
    """Per-leaf PartitionSpecs of the serving layout (init_gpt_params
    structure, stacked [L, ...] blocks, HEAD-MAJOR qkv storage so a
    contiguous column shard is whole heads). Every matmul weight shards
    its OUTPUT dim; norms and the biases added after an output gather
    stay replicated. With ``quant_weights`` the int8/fp8 leaves carry
    per-output-channel ``<name>_s`` fp32 scales that shard WITH their
    channels — a chip's scale shard dequantizes exactly its own weight
    columns, which is what keeps mp quantized output bitwise identical
    to single-chip quantized output."""
    mpx = "mp"
    blocks = {
        "ln1_g": P(None, None), "ln1_b": P(None, None),
        "qkv_w": P(None, None, mpx), "qkv_b": P(None, mpx),
        "out_w": P(None, None, mpx), "out_b": P(None, None),
        "ln2_g": P(None, None), "ln2_b": P(None, None),
        "up_w": P(None, None, mpx), "up_b": P(None, mpx),
        "down_w": P(None, None, mpx), "down_b": P(None, None),
    }
    out = {
        "wte": P(None, mpx),            # feature-sharded: local lookup + AG
        "wpe": P(None, None),
        "lnf_g": P(None), "lnf_b": P(None),
        "head_w": P(None, mpx) if mp_cfg.shard_vocab else P(None, None),
        "blocks": blocks,
    }
    if quant_weights:
        for name in ("qkv_w", "out_w", "up_w", "down_w"):
            blocks[name + "_s"] = P(None, mpx)
        out["head_w_s"] = P(mpx) if mp_cfg.shard_vocab else P(None)
    return out


def shard_serving_params(params, config, mesh, mp_cfg, quant_spec=None):
    """Place a GPT param tree onto the serving mp layout. Accepts the
    LOGICAL qkv layout (permuted to head-major here) or params already in
    head-major storage (``config.qkv_head_major`` — what HybridTrainStep
    trains under the explicit mp schedule): those are device_put straight
    to the serving shardings, so an already-mp-sharded trained tree moves
    chip-to-chip without a host gather + re-shard round trip.

    ``quant_spec`` (serving/quant.py) quantizes the GEMM weights BEFORE
    placement: per-output-channel quantization is column-independent, so
    quantize-then-shard equals shard-then-quantize and the mp engine
    serves bit-identical int8/fp8 blocks to the single-chip engine's
    column slices. Pinned calibration scales (recorded on the logical
    layout) relabel head-major together with the qkv columns."""
    perm = None
    if not getattr(config, "qkv_head_major", False):
        from ..distributed.tp_overlap import (qkv_head_major_perm,
                                              to_qkv_head_major)
        params = {**params,
                  "blocks": to_qkv_head_major(params["blocks"],
                                              config.hidden_size,
                                              config.num_heads)}
        perm = qkv_head_major_perm(config.hidden_size, config.num_heads)
    quant_weights = quant_spec is not None and quant_spec.quantizes_weights
    if quant_weights:
        from . import quant as _sq
        if perm is None and getattr(config, "qkv_head_major", False):
            # already-head-major tree: pinned calibration scales (logical
            # layout) still need the column relabeling
            from ..distributed.tp_overlap import qkv_head_major_perm
            perm = qkv_head_major_perm(config.hidden_size,
                                       config.num_heads)
        params = _sq.quantize_params(params, config, quant_spec,
                                     qkv_perm=perm)
    specs = serving_param_specs(mp_cfg, quant_weights=quant_weights)
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(jnp.asarray(a), NamedSharding(mesh, s)),
        params, specs)


# ---------------------------------------------------------------------------
# per-device collective helpers (inside the full-manual shard_map; every
# one is an exact gather — chip-order concat, no arithmetic)


def _ring_ag_last(x, axis, n):
    """ppermute ring all-gather along the LAST axis."""
    idx = lax.axis_index(axis)
    F = x.shape[-1]
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = jnp.zeros(x.shape[:-1] + (n * F,), x.dtype)
    chunk = x
    for t in range(n):
        src = (idx - t) % n
        out = lax.dynamic_update_slice_in_dim(out, chunk, src * F,
                                              axis=x.ndim - 1)
        if t < n - 1:
            chunk = lax.ppermute(chunk, axis, perm)
    return out


def ag_last(x, axis, n, backend, meta):
    """Exact all-gather along the last axis: [..., F/n] -> [..., F] with
    blocks in chip (= logical) order."""
    if n == 1:
        return x
    if backend == "fused":
        from ..ops.pallas_kernels import fused_collectives as _fc
        out = _fc.fused_ag_bucket(meta, x.reshape(-1))       # [n, numel]
        out = out.reshape((n,) + x.shape)
        return jnp.moveaxis(out, 0, -2).reshape(
            x.shape[:-1] + (n * x.shape[-1],))
    if backend == "ring":
        return _ring_ag_last(x, axis, n)
    return lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)


def gemm_ag(x, w, axis, n, backend, meta, scale=None, epilogue=None):
    """Column-parallel projection: full-contraction local block
    ``x @ w_shard`` + all-gather of the output blocks. Bitwise equal to
    ``x @ w_full`` on every rung (the fused rung's GEMM epilogue feeds
    the ring directly — ``fused_collectives.fused_gemm_ag``).

    ``scale`` (quantized serving): ``w`` is the raw int8/fp8 shard and
    ``scale`` its per-output-channel fp32 dequant shard — the dequant
    multiply rides the local GEMM epilogue (inside the Pallas kernel on
    the fused rung), so the mp engine never materializes an fp weight
    copy, and the scaled block equals the column slice of the single-chip
    quantized product bitwise.

    ``epilogue`` (adapter serving): element-wise function applied to the
    LOCAL output block BEFORE the gather — the per-slot LoRA delta
    compose. Element-wise maps commute with the pure-data-movement
    gather, so composing pre-gather equals composing on the full product:
    the bitwise contract survives. With an epilogue the fused rung routes
    its gather through ``fused_ag_bucket`` (the epilogue has to land
    between the GEMM and the ring, so the in-kernel fused_gemm_ag path
    is skipped for that projection — still an exact gather)."""
    if n == 1:
        if scale is not None:
            y = (x @ w.astype(x.dtype)) * scale.astype(x.dtype)
        else:
            y = x @ w
        return y if epilogue is None else epilogue(y)
    if backend == "fused" and epilogue is None:
        from ..ops.pallas_kernels import fused_collectives as _fc
        return _fc.fused_gemm_ag(meta, x, w, scale=scale)
    if scale is not None:
        y = (x @ w.astype(x.dtype)) * scale.astype(x.dtype)
    else:
        y = x @ w
    if epilogue is not None:
        y = epilogue(y)
    if backend == "fused":
        return ag_last(y, axis, n, backend, meta)
    if backend == "ring":
        return _ring_ag_last(y, axis, n)
    return lax.all_gather(y, axis, axis=y.ndim - 1, tiled=True)


# ---------------------------------------------------------------------------
# the per-device block + forward


def _local_proj(h, p, name):
    """Local column-block projection (output stays sharded): fp leaf, or
    int8/fp8 leaf + per-channel scale shard with the dequant multiply in
    the epilogue — the scaled block is bitwise the column slice of the
    single-chip quantized GEMM."""
    s = p.get(name + "_s")
    if s is None:
        return h @ p[name].astype(h.dtype)
    return (h @ p[name].astype(h.dtype)) * s.astype(h.dtype)


def _mp_block(p, h, kc_l, vc_l, table, pos, valid, nh, n, eps, page_size,
              use_kernel, axis, backend, meta, ksc_l=None, vsc_l=None,
              aid=None, ad_l=None):
    """One transformer block on PER-CHIP shards: h [B, T, H] replicated,
    weights column-sharded (qkv head-major: the local contiguous shard is
    nh/n whole heads), KV pool holding the local heads only. Every op is
    either replicated elementwise math, a full-contraction GEMM block, a
    per-head attention (head subsets are bitwise-independent), or an
    exact gather — so the block output is bitwise identical to
    paged_attention._layer_paged on one chip, at EVERY dtype config
    (quantized weights dequantize in the epilogue against their own
    column-scale shard; the quantized KV pool's per-page scales are
    replicated and head-independent).

    Adapters (aid [B] + this layer's slab rows ``ad_l``): A slabs are
    replicated and B slabs shard with their OUTPUT channels, so each
    chip's delta is exactly the column slice of the single-chip delta
    (the rank-r intermediate ``x @ A[aid]`` is replicated-identical
    everywhere, full contraction). The delta composes onto the LOCAL
    base block before each gather — element-wise, so it commutes with
    the gather and the single-chip bitwise contract is untouched."""
    B, T, H = h.shape
    nh_l = nh // n
    d = H // nh

    def _delta_epi(x, name):
        """compose-epilogue for the local column block of ``name``, or
        None when the layer carries no delta for it."""
        if ad_l is None or name not in ad_l:
            return None
        A_l, B_l = ad_l[name]
        dlt = lora_delta(x, A_l, B_l, aid)
        return lambda y: compose_delta(y, dlt, aid)

    h1 = ln_fp32(h, p["ln1_g"], p["ln1_b"], eps)
    qkv = _local_proj(h1, p, "qkv_w") + p["qkv_b"].astype(h.dtype)
    qkv4 = qkv.reshape(B, T, nh_l, 3, d)        # head-major local columns
    q, k, v = qkv4[..., 0, :], qkv4[..., 1, :], qkv4[..., 2, :]

    kc_l, vc_l = paged_kv_scatter(kc_l, vc_l, k, v, table, pos, valid,
                                  page_size, ksc_l, vsc_l)
    ctx = paged_attention_read(q, kc_l, vc_l, table, pos, page_size,
                               use_kernel, h.dtype, ksc_l,
                               vsc_l)                           # [B,T,nh_l,d]
    # gather the context heads (chip order == logical head order), then
    # the out projection keeps the FULL contraction against its column
    # shard — the one arrangement that is bitwise under sharding
    ctx_full = ag_last(ctx.reshape(B, T, nh_l * d), axis, n, backend, meta)
    out_s = p.get("out_w_s")
    attn = gemm_ag(ctx_full,
                   p["out_w"] if out_s is not None
                   else p["out_w"].astype(h.dtype),
                   axis, n, backend, meta, scale=out_s,
                   epilogue=_delta_epi(ctx_full, "out_w")) + \
        p["out_b"].astype(h.dtype)
    h = h + attn
    h2 = ln_fp32(h, p["ln2_g"], p["ln2_b"], eps)
    up = _local_proj(h2, p, "up_w")
    up_epi = _delta_epi(h2, "up_w")
    if up_epi is not None:
        up = up_epi(up)
    up = up + p["up_b"].astype(h.dtype)
    up = jax.nn.gelu(up, approximate=True)
    act = ag_last(up, axis, n, backend, meta)                   # [B, T, I]
    down_s = p.get("down_w_s")
    down = gemm_ag(act,
                   p["down_w"] if down_s is not None
                   else p["down_w"].astype(h.dtype),
                   axis, n, backend, meta, scale=down_s,
                   epilogue=_delta_epi(act, "down_w"))
    return h + down + p["down_b"].astype(h.dtype), kc_l, vc_l


def mp_paged_forward(params, config, ids, kc, vc, start, valid, table,
                     page_size, use_kernel, mesh, mp_cfg, kv_scales=None,
                     adapters=None):
    """Fused chunk/decode forward over the mp-sharded engine: same
    signature and semantics as ``paged_attention.paged_forward`` but with
    params/KV sharded over ``mesh``'s 1-D mp axis. Returns replicated
    logits [B, V] plus the updated head-sharded pools. ``kv_scales`` =
    (k_scale, v_scale) [L, P] per-page dequant scales of a quantized
    pool, replicated (a page's scale applies to every head shard).
    ``adapters`` = (aid [B], slabs) per-slot adapter operands: aid and
    the A slabs replicate; B slabs shard with their output channels
    (the quant-scale placement rule) so the per-chip delta lands on the
    local column block before the gather."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    n, axis, backend = mp_cfg.n, mp_cfg.axis, mp_cfg.backend
    meta = mp_cfg.kernel_meta(mesh)
    nh = config.num_heads
    eps = config.layer_norm_epsilon
    quant_weights = "head_w_s" in params

    def device_fn(params, kc, vc, ids, start, valid, table, *extra):
        extra = list(extra)
        if kv_scales is not None:
            scales = (extra.pop(0), extra.pop(0))
        else:
            scales = ()
        if adapters is not None:
            aid_d, slabs_d = extra
        else:
            aid_d = slabs_d = None
        B, T = ids.shape
        pos = start[:, None] + jnp.arange(T)[None, :]           # [B, T]
        x = ag_last(params["wte"].astype(compute)[ids], axis, n, backend,
                    meta) + \
            jnp.take(params["wpe"].astype(compute), pos, axis=0)

        def layer_fn(h, xs):
            if adapters is not None:
                xs, ad_l = xs[:-1], xs[-1]
            else:
                ad_l = None
            if scales:
                p_l, kc_l, vc_l, ksc_l, vsc_l = xs
            else:
                p_l, kc_l, vc_l = xs
                ksc_l = vsc_l = None
            h, kc_l, vc_l = _mp_block(p_l, h, kc_l, vc_l, table, pos,
                                      valid, nh, n, eps, page_size,
                                      use_kernel, axis, backend, meta,
                                      ksc_l, vsc_l, aid_d, ad_l)
            return h, (kc_l, vc_l)

        xs = (params["blocks"], kc, vc) + tuple(scales)
        if adapters is not None:
            xs = xs + (slabs_d,)
        x, (kc2, vc2) = jax.lax.scan(layer_fn, x, xs)
        idx = jnp.maximum(valid - 1, 0)
        xlast = jax.vmap(
            lambda xb, i: jax.lax.dynamic_slice_in_dim(xb, i, 1, axis=0))(
                x, idx)[:, 0]                                   # [B, H]
        xn = _final_ln(params, config, xlast)
        head_s = params.get("head_w_s")
        if mp_cfg.shard_vocab:
            logits = gemm_ag(xn,
                             params["head_w"] if head_s is not None
                             else params["head_w"].astype(jnp.float32),
                             axis, n, backend, meta, scale=head_s)
        elif head_s is not None:
            logits = (xn @ params["head_w"].astype(jnp.float32)) * \
                head_s.astype(jnp.float32)
        else:
            logits = xn @ params["head_w"].astype(jnp.float32)
        return logits, kc2, vc2

    in_specs = [serving_param_specs(mp_cfg, quant_weights), KV_SPEC,
                KV_SPEC, P(None, None), P(None), P(None), P(None, None)]
    args = [params, kc, vc, ids, start, valid, table]
    if kv_scales is not None:
        in_specs += [P(None, None), P(None, None)]
        args += [kv_scales[0], kv_scales[1]]
    if adapters is not None:
        aid_arr, slabs = adapters
        in_specs += [P(None),
                     {name: (P(None, None, None, None),
                             P(None, None, None, "mp"))
                      for name in slabs}]
        args += [aid_arr, slabs]
    mapped = shard_map_compat(
        device_fn, mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(None, None), KV_SPEC, KV_SPEC))
    return mapped(*args)


def replica_mesh(mp, devices=None):
    """A 1-D ('mp',) mesh over ``mp`` devices — the shape one serving
    replica (= one mp group) runs on. Does NOT touch the process-global
    mesh (a supervisor runs several replicas, each on its own devices)."""
    from jax.sharding import Mesh
    devices = list(jax.devices() if devices is None else devices)
    mp = int(mp)
    if mp > len(devices):
        raise ValueError(f"serving mp={mp} needs {mp} devices, only "
                         f"{len(devices)} available")
    return Mesh(np.array(devices[:mp]), ("mp",))
