"""SLO policy for multi-tenant serving: priority classes, load shedding,
per-tenant rate limits and telemetry-driven autoscaling.

Everything here is host-side POLICY over mechanisms earlier PRs built —
class-aware admission and preemption ride the PR 7 requeue machinery
(original arrival kept, replays bitwise), shedding and autoscaling read
the PR 9 gauges (queue depth, slot occupancy, TTFT percentiles). No
object in this module touches a traced operand or an executable: with the
FLAGS_serving_priority_classes / FLAGS_serving_shed /
FLAGS_serving_autoscale family off, the serving path is byte-identical to
the pre-SLO engine.

Priority classes
----------------
Three classes, best first::

    interactive   rank 0   user-facing; may preempt lower classes
    batch         rank 1   default; throughput traffic
    best_effort   rank 2   preempted first, shed first

Within a class, admission is weighted-fair across tenants (deficit
round-robin over per-tenant FCFS queues) so one tenant's burst cannot
starve another's steady trickle; across classes, admission is strictly
best-class-first. A request never changes class after submit.
"""
from __future__ import annotations

import time

# rank order IS the policy order: lower rank = better class = admitted
# first, preempted/shed last
CLASSES = ("interactive", "batch", "best_effort")
_RANK = {c: i for i, c in enumerate(CLASSES)}
DEFAULT_CLASS = "batch"


def class_rank(priority):
    """Rank of a priority class (0 best). Raises on unknown classes so a
    typo'd class fails at submit, not silently as best-effort."""
    try:
        return _RANK[priority]
    except KeyError:
        raise ValueError(
            f"unknown priority class {priority!r}; expected one of "
            f"{CLASSES}") from None


def resolve_tenant_adapters(flags):
    """Tenant -> default adapter id mapping from
    ``FLAGS_serving_tenant_adapters`` (many-model serving,
    serving/adapters.py): requests that don't name an adapter explicitly
    are served with their tenant's mapped delta; unmapped tenants get the
    base model (id 0). Accepts a dict ({"acme": 1}) or the flag-file
    string spelling ("acme:1,beta:2"). Ids are validated against engine
    capacity at Engine construction, not here."""
    raw = flags.get("FLAGS_serving_tenant_adapters", {}) or {}
    if isinstance(raw, str):
        mapping = {}
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            tenant, _, aid = part.partition(":")
            if not _ or not aid.strip():
                raise ValueError(
                    f"FLAGS_serving_tenant_adapters entry {part!r} is not "
                    f"'tenant:adapter_id'")
            mapping[tenant.strip()] = int(aid)
        return mapping
    return {str(t): int(a) for t, a in dict(raw).items()}


class TokenBucket:
    """Per-tenant token bucket: ``rate`` sustained requests/second with a
    ``burst`` allowance. ``take()`` returns 0.0 when a token was consumed,
    else the exact seconds until the next token accrues (the retry-after
    hint a router hands back). Deterministic given a clock: tests drive it
    with an explicit ``now``."""

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._t = None                      # lazily anchored to first take

    def take(self, now=None):
        now = time.perf_counter() if now is None else now
        if self._t is None:
            self._t = now
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return 0.0
        return (1.0 - self._tokens) / self.rate if self.rate > 0 else 1.0

    def idle_full(self, now):
        """True when the bucket has (or by ``now`` will have) refilled to
        its burst — indistinguishable from a freshly-created one, so a
        long-lived router can drop it from its per-tenant map."""
        if self._t is None:
            return True
        return self._tokens + (now - self._t) * self.rate >= self.burst


class DrainRate:
    """EWMA of queue-drain throughput (requests resolved per second),
    observed at step boundaries. Feeds the shed retry-after hint: a client
    told to come back in ``excess / drain_rate`` seconds arrives when the
    backlog has actually drained, instead of blind exponential backoff."""

    def __init__(self, alpha=0.3):
        self.alpha = float(alpha)
        self.rate = None                    # requests / second
        self._last_t = None
        self._last_done = None

    def observe(self, done_total, now=None):
        now = time.perf_counter() if now is None else now
        if self._last_t is not None:
            dt = now - self._last_t
            if dt > 0:
                inst = max(0.0, done_total - self._last_done) / dt
                self.rate = (inst if self.rate is None
                             else self.alpha * inst
                             + (1 - self.alpha) * self.rate)
        self._last_t = now
        self._last_done = done_total

    def retry_after(self, excess, floor=0.05, ceil=60.0):
        """Seconds until ``excess`` queued requests should have drained."""
        if excess <= 0:
            return floor
        rate = self.rate if self.rate else 1.0
        return float(min(ceil, max(floor, excess / rate)))


class ShedPolicy:
    """Sustained-overload detector with hysteresis: the queue must sit at
    or above ``high`` for ``window`` CONSECUTIVE boundaries before
    shedding starts (a burst the drain absorbs never sheds), and shedding
    targets ``low`` so the fleet exits overload with headroom instead of
    oscillating on the high watermark. ``shedding`` stays latched until
    the queue next drops below ``low`` — while latched, new lowest-class
    submissions are refused up front (ShedError) rather than queued and
    shed a boundary later."""

    def __init__(self, max_queue, high=0.75, low=0.5, window=4):
        self.high = max(1, int(float(high) * max_queue))
        self.low = int(float(low) * max_queue)
        self.window = max(1, int(window))
        self._over = 0
        self.shedding = False
        self.drain = DrainRate()

    def observe(self, qsize, done_total, now=None):
        """Record one boundary; returns the shed target (queue length to
        shed down to) when shedding should happen NOW, else None."""
        self.drain.observe(done_total, now)
        if qsize >= self.high:
            self._over += 1
            if self._over >= self.window:
                self.shedding = True
                return self.low
        else:
            self._over = 0
            # strict: shedding itself lands the queue AT `low`, which must
            # not count as recovered — the latch holds until the backlog
            # actually drains below the watermark (or empties)
            if qsize < self.low or qsize == 0:
                self.shedding = False
        return None

    def retry_after(self, qsize):
        return self.drain.retry_after(qsize - self.low)


class Autoscaler:
    """Hysteresis + cooldown policy over the fleet gauges the PR 9
    telemetry already exports: mean waiting requests per live replica,
    mean slot occupancy, and (optionally) the ledger's TTFT p99 against an
    SLO. ``decide()`` is pure policy — it returns "grow"/"shrink"/None and
    the supervisor applies the action through its existing spawn/drain
    machinery at a step boundary, so scaling can never tear an engine
    mid-dispatch."""

    def __init__(self, min_replicas=1, max_replicas=4, up_queue=4.0,
                 down_queue=0.5, up_occupancy=0.9, down_occupancy=0.3,
                 ttft_slo_s=0.0, window=4, cooldown_s=2.0):
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.up_queue = float(up_queue)
        self.down_queue = float(down_queue)
        self.up_occupancy = float(up_occupancy)
        self.down_occupancy = float(down_occupancy)
        self.ttft_slo_s = float(ttft_slo_s)
        self.window = max(1, int(window))
        self.cooldown_s = float(cooldown_s)
        self._over = 0
        self._under = 0
        self._last_action_t = None
        self.last_reason = None

    def decide(self, alive, queue_depth, active_slots, total_slots,
               ttft_p99=None, now=None):
        """One evaluation: fleet-wide waiting requests, busy slots and
        capacity, plus the live TTFT p99. Hysteresis counts consecutive
        over/under evaluations separately; one boundary inside the dead
        band resets both streaks."""
        now = time.perf_counter() if now is None else now
        if alive <= 0:
            return None
        per_rep = queue_depth / alive
        occupancy = active_slots / total_slots if total_slots else 0.0
        over = (per_rep >= self.up_queue or occupancy >= self.up_occupancy
                or (self.ttft_slo_s > 0 and ttft_p99 is not None
                    and ttft_p99 > self.ttft_slo_s))
        under = (per_rep <= self.down_queue
                 and occupancy <= self.down_occupancy)
        self._over = self._over + 1 if over else 0
        self._under = self._under + 1 if under else 0
        if self._last_action_t is not None \
                and now - self._last_action_t < self.cooldown_s:
            return None
        if self._over >= self.window and alive < self.max_replicas:
            self._over = self._under = 0
            self._last_action_t = now
            self.last_reason = (f"queue/rep {per_rep:.1f} occ "
                                f"{occupancy:.2f} ttft_p99 {ttft_p99}")
            return "grow"
        if self._under >= self.window and alive > self.min_replicas:
            self._over = self._under = 0
            self._last_action_t = now
            self.last_reason = (f"queue/rep {per_rep:.1f} occ "
                                f"{occupancy:.2f}")
            return "shrink"
        return None

    @classmethod
    def from_flags(cls, flags):
        return cls(
            min_replicas=flags.get("FLAGS_serving_min_replicas", 1),
            max_replicas=flags.get("FLAGS_serving_max_replicas", 4),
            up_queue=flags.get("FLAGS_serving_autoscale_up_queue", 4.0),
            down_queue=flags.get("FLAGS_serving_autoscale_down_queue", 0.5),
            up_occupancy=flags.get(
                "FLAGS_serving_autoscale_up_occupancy", 0.9),
            down_occupancy=flags.get(
                "FLAGS_serving_autoscale_down_occupancy", 0.3),
            ttft_slo_s=flags.get("FLAGS_serving_autoscale_ttft_slo", 0.0),
            window=flags.get("FLAGS_serving_autoscale_window", 4),
            cooldown_s=flags.get("FLAGS_serving_autoscale_cooldown_s", 2.0))
