"""Version info (ref: python/paddle/version.py, generated at build time)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"rc: {rc}")
    print(f"commit: {commit}")


def cuda():
    return False


def cudnn():
    return False


def nccl():
    return False


def xpu():
    return False


def xpu_xccl():
    return False


def cinn():
    return False  # XLA plays the compiler role
