"""paddle_tpu — a TPU-native deep learning framework.

A ground-up rebuild of the reference framework's capabilities
(PaddlePaddle-compatible user API) designed for TPU: jax/XLA is the kernel
library and executor, GSPMD/shard_map over `jax.sharding.Mesh` is the
distributed runtime, and pallas provides fused kernels for the hot ops.

Top-level surface mirrors python/paddle/__init__.py.
"""
from __future__ import annotations

import jax as _jax
import jax.numpy as _jnp

# Enable 64-bit types for paddle parity (int64 indices, optional float64).
# Python scalars stay weakly typed, so f32 compute paths are unaffected.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

# dtypes
float16 = _jnp.float16
bfloat16 = _jnp.bfloat16
float32 = _jnp.float32
float64 = _jnp.float64
int8 = _jnp.int8
int16 = _jnp.int16
int32 = _jnp.int32
int64 = _jnp.int64
uint8 = _jnp.uint8
bool = _jnp.bool_
complex64 = _jnp.complex64
complex128 = _jnp.complex128

from .tensor_impl import Tensor, Parameter  # noqa: E402,F401
from .framework import (  # noqa: E402,F401
    no_grad, enable_grad, set_grad_enabled, set_default_dtype, get_default_dtype,
    seed, CPUPlace, TPUPlace, CUDAPlace,
)
from .framework import random as _fw_random  # noqa: E402
from .framework import device  # noqa: E402,F401
from .tensor import *  # noqa: E402,F401,F403
from .tensor import einsum  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from .autograd import grad  # noqa: E402,F401
from . import nn  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import static  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import models  # noqa: E402,F401
from . import framework  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from . import observability  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import inference  # noqa: E402,F401
from . import serving  # noqa: E402,F401
from . import fft  # noqa: E402,F401
from . import signal  # noqa: E402,F401
from . import linalg  # noqa: E402,F401
from . import geometric  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import audio  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from . import hub  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401
from . import reader  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from . import version  # noqa: E402,F401
from . import cost_model  # noqa: E402,F401
from .framework.io import save, load  # noqa: E402,F401
from .hapi import Model, summary  # noqa: E402,F401
from .hapi import callbacks  # noqa: E402,F401

from .framework.device import (  # noqa: E402,F401
    set_device, get_device, is_compiled_with_cuda,
)
from .framework.extras import (  # noqa: E402,F401
    get_rng_state, set_rng_state, get_cuda_rng_state, set_cuda_rng_state,
    set_printoptions, disable_signal_handler, LazyGuard, DataParallel,
    create_parameter, flops, batch, check_shape,
)
from .nn import ParamAttr  # noqa: E402,F401

# `paddle.dtype` is the dtype type itself (VarType analog)
dtype = _jnp.dtype


class CUDAPinnedPlace:  # host-staging place: meaningless on TPU, API parity
    def __repr__(self):
        return "CUDAPinnedPlace"


def disable_static(place=None):
    """Dygraph is the default and only eager mode; kept for API parity."""


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is dygraph-first; use paddle_tpu.jit.to_static for compiled "
        "execution (XLA Programs replace static-graph Programs).")


def in_dynamic_mode():
    return True


def is_grad_enabled():
    from .framework.state import grad_enabled
    return grad_enabled()


def get_flags(flags=None):
    from . import flags as _flags
    return _flags.get_flags(flags)


def set_flags(flags):
    from . import flags as _flags
    return _flags.set_flags(flags)


# bind the remaining reference tensor_method_func names as Tensor methods
# (they live outside tensor/math|manipulation|... and need the full
# namespace assembled first)
import sys as _sys
from .tensor import install_method_parity as _imp
_imp(_sys.modules[__name__])
del _imp, _sys
