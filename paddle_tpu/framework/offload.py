"""Host-offload placement helpers shared by jit.TrainStep and
models.gpt_hybrid.HybridTrainStep (ref: fleet/meta_parallel/sharding/
group_sharded_stage3.py:84 cpu offload -> memory_kind='pinned_host').

Each train-step class supplies its own device-sharding tree (its slot
placement policy); everything else — host-kind derivation, the in-jit vs
around-the-jit transfer decision, and the tree moves — lives here once.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def in_jit_transfers_supported():
    """Only the TPU backend implements the annotate_device_placement
    custom-call that in-jit `device_put`-to-memory-kind lowers to; other
    backends must move the buffers around the compiled call instead."""
    return jax.default_backend() == "tpu"


def with_memory_kind(sharding, kind):
    """Sharding in the given memory space; works with or without a mesh."""
    if sharding is not None:
        return sharding.with_memory_kind(kind)
    from jax.sharding import SingleDeviceSharding
    return SingleDeviceSharding(jax.devices()[0], memory_kind=kind)


def host_shardings(opt_state, dev_shardings):
    """pinned_host placements for every non-scalar leaf (the scalar step
    counter stays on device — transferring it buys nothing)."""
    return jax.tree_util.tree_map(
        lambda a, s: with_memory_kind(s, "pinned_host")
        if jnp.ndim(a) > 0 else s,
        opt_state, dev_shardings)


def move_opt(opt_state, shardings):
    """device_put a state tree onto a matching sharding tree (works both
    eagerly and inside a traced step on TPU)."""
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, s) if s is not None else a,
        opt_state, shardings)


def fetch_stash(enabled, dev_tree, host_tree):
    """(fetch, stash) closures for the compiled step: host->device before
    the optimizer update, device->host after (XLA overlaps the copies with
    compute). Identity when offload is off or unsupported in-jit."""
    if not enabled:
        ident = lambda o: o  # noqa: E731
        return ident, ident
    return (lambda o: move_opt(o, dev_tree),
            lambda o: move_opt(o, host_tree))


def streamed_apply_gradients(optimizer, params, grads, state, lr, wd_mask,
                             stacked, to_dev=None, to_host=None,
                             transfer_params=False):
    """Offloaded optimizer update that streams stacked [L, ...] slot arrays
    through device memory one leading-dim slice at a time (ref:
    fleet/meta_parallel/sharding/group_sharded_stage3.py:84 cpu offload).

    The bulk fetch/update/stash alternative puts the whole moment set back
    in HBM for the update — exactly the residency offload exists to avoid
    (for a 2.7B model m+v is ~10.8G bf16 against a 15.75G chip, an OOM even
    before activations). Streaming caps peak HBM at params + grads + ONE
    layer's slots.

    params/grads: dict[name -> array]. state: {"step", "slots"} with slot
    arrays host-resident. stacked: leaf names whose leading dim is the
    layer axis. to_dev/to_host: per-array transfer closures (None =
    identity — used by backends without in-jit transfers and by the CPU
    math-parity tests; the loop structure is backend-agnostic).

    Non-stacked leaves do one bulk fetch/update/stash (their slots are the
    small embedding/norm tail). Stacked leaves run a lax.fori_loop whose
    carry is (device param arrays, host slot arrays): each iteration DMAs
    one layer's slots in, updates, and DMAs them back. The loop-carried
    dependency is what serializes the copies — an unrolled chain lets XLA
    hoist every copy-start and re-create the bulk residency.

    transfer_params=True: params AND grads are host-resident too (stage-3
    full offload) — they get the same per-slice fetch, and updated params
    stash back to host, so peak HBM holds one layer's p/g/m/v.
    """
    import jax.lax as lax
    ident = lambda a: a  # noqa: E731
    to_dev = to_dev or ident
    to_host = to_host or ident
    slots = state["slots"]
    if not getattr(optimizer, "_elementwise_update", False):
        # norm/history-based updates (Lamb trust ratio, LARS local_lr,
        # LBFGS) are not slice-equivariant: updating layer slices would
        # silently change the math vs the bulk update. Bulk-transfer those.
        stacked = ()
    stk = [n for n in params if n in stacked and grads.get(n) is not None]
    # frozen leaves (no grad) keep their slots host-resident untouched —
    # routing them through the bulk fetch would transfer whole [L, ...]
    # moment sets just to pass them through unchanged
    frozen = [n for n in params if n not in stk and grads.get(n) is None]
    small = [n for n in params if n not in stk and n not in frozen]

    fetch_p = to_dev if transfer_params else (lambda a: a)
    stash_p = to_host if transfer_params else (lambda a: a)
    small_state = {"step": state["step"],
                   "slots": {n: {k: to_dev(v) if jnp.ndim(v) else v
                                 for k, v in slots[n].items()}
                             for n in small}}
    new_params, small_out = optimizer.apply_gradients(
        {n: fetch_p(params[n]) for n in small},
        {n: fetch_p(grads[n]) for n in small},
        small_state, lr, wd_mask=wd_mask)
    new_params = {n: stash_p(v) for n, v in new_params.items()}
    new_step = small_out["step"]  # apply_gradients returns step+1 even
    # when the small dict is empty
    new_slots = {n: {k: to_host(v) if jnp.ndim(v) else v
                     for k, v in s.items()}
                 for n, s in small_out["slots"].items()}
    for n in frozen:
        new_params[n] = params[n]
        new_slots[n] = slots[n]

    if stk:
        num_layers = params[stk[0]].shape[0]
        mismatched = [n for n in stk if params[n].shape[0] != num_layers]
        if mismatched:
            # dynamic_index_in_dim clamps out-of-range indices, so a
            # leading-dim mismatch would silently corrupt the update
            raise ValueError(
                f"stacked leaves disagree on leading dim: {mismatched} "
                f"vs {num_layers}")

        def body(layer, carry):
            pstk, hslots = carry
            p_l = {n: fetch_p(lax.dynamic_index_in_dim(pstk[n], layer,
                                                       0, False))
                   for n in stk}
            g_l = {n: fetch_p(lax.dynamic_index_in_dim(grads[n], layer,
                                                       0, False))
                   for n in stk}
            s_l = {n: {k: to_dev(lax.dynamic_index_in_dim(v, layer, 0, False))
                       for k, v in hslots[n].items()} for n in stk}
            p_new, s_new = optimizer.apply_gradients(
                p_l, g_l, {"step": state["step"], "slots": s_l}, lr,
                wd_mask=wd_mask)
            pstk = {n: lax.dynamic_update_index_in_dim(
                        pstk[n], stash_p(p_new[n].astype(pstk[n].dtype)),
                        layer, 0)
                    for n in stk}
            hslots = {n: {k: lax.dynamic_update_index_in_dim(
                              v, to_host(s_new["slots"][n][k].astype(v.dtype)),
                              layer, 0)
                          for k, v in hslots[n].items()} for n in stk}
            return pstk, hslots

        pstk, hslots = lax.fori_loop(
            0, num_layers, body,
            ({n: params[n] for n in stk}, {n: dict(slots[n]) for n in stk}))
        new_params.update(pstk)
        new_slots.update(hslots)
    return new_params, {"step": new_step, "slots": new_slots}
