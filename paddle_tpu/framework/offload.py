"""Host-offload placement helpers shared by jit.TrainStep and
models.gpt_hybrid.HybridTrainStep (ref: fleet/meta_parallel/sharding/
group_sharded_stage3.py:84 cpu offload -> memory_kind='pinned_host').

Each train-step class supplies its own device-sharding tree (its slot
placement policy); everything else — host-kind derivation, the in-jit vs
around-the-jit transfer decision, and the tree moves — lives here once.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def in_jit_transfers_supported():
    """Only the TPU backend implements the annotate_device_placement
    custom-call that in-jit `device_put`-to-memory-kind lowers to; other
    backends must move the buffers around the compiled call instead."""
    return jax.default_backend() == "tpu"


def with_memory_kind(sharding, kind):
    """Sharding in the given memory space; works with or without a mesh."""
    if sharding is not None:
        return sharding.with_memory_kind(kind)
    from jax.sharding import SingleDeviceSharding
    return SingleDeviceSharding(jax.devices()[0], memory_kind=kind)


def host_shardings(opt_state, dev_shardings):
    """pinned_host placements for every non-scalar leaf (the scalar step
    counter stays on device — transferring it buys nothing)."""
    return jax.tree_util.tree_map(
        lambda a, s: with_memory_kind(s, "pinned_host")
        if jnp.ndim(a) > 0 else s,
        opt_state, dev_shardings)


def move_opt(opt_state, shardings):
    """device_put a state tree onto a matching sharding tree (works both
    eagerly and inside a traced step on TPU)."""
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, s) if s is not None else a,
        opt_state, shardings)


def fetch_stash(enabled, dev_tree, host_tree):
    """(fetch, stash) closures for the compiled step: host->device before
    the optimizer update, device->host after (XLA overlaps the copies with
    compute). Identity when offload is off or unsupported in-jit."""
    if not enabled:
        ident = lambda o: o  # noqa: E731
        return ident, ident
    return (lambda o: move_opt(o, dev_tree),
            lambda o: move_opt(o, host_tree))
