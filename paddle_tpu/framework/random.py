"""Seeded randomness with explicit key threading.

The reference uses a global stateful generator (ref: python/paddle/framework/random.py,
paddle/phi/core/generator.cc). On TPU/XLA, stateful RNG breaks trace purity, so we
keep a host-side splitting key for eager mode and a *fork* mechanism: functional
code (TrainStep / to_static) installs a traced base key, and every `next_key()`
inside the region derives from it deterministically via fold_in counters.
"""
from __future__ import annotations

import contextlib
import threading

import jax


class _RngState(threading.local):
    def __init__(self):
        self.seed = 0
        self.key = jax.random.key(0)
        self.forked = None  # (base_key, counter) while inside fork_rng
        self.philox_counter = 0
        # GLOBAL-STREAM position: keys drawn from the global generator
        # since the last seed(). The stream is a pure function of
        # (seed, draws) and is TOPOLOGY-INDEPENDENT — per-replica keys are
        # derived inside the compiled step by folding the replica index,
        # so a dp=8 -> dp=4 elastic resume that restores (seed, key,
        # draws) continues the exact key sequence of the source run.
        self.draws = 0


_rng = _RngState()


def seed(s: int):
    """paddle.seed parity: reset the global generator."""
    _rng.seed = int(s)
    _rng.key = jax.random.key(int(s))
    _rng.philox_counter = 0
    _rng.draws = 0
    return _rng


def get_seed() -> int:
    return _rng.seed


def next_key():
    """Return a fresh PRNG key. Inside fork_rng, derives from the forked base key
    (trace-safe: the sequence is a pure function of the base key)."""
    if _rng.forked is not None:
        base, counter = _rng.forked
        _rng.forked = (base, counter + 1)
        return jax.random.fold_in(base, counter)
    _rng.key, sub = jax.random.split(_rng.key)
    _rng.draws += 1
    return sub


def stream_position():
    """Keys drawn from the global stream since the last ``seed()`` — the
    stream position in GLOBAL terms (one draw per training step, whatever
    the mesh looks like). Recorded in ``state_dict`` so an elastic resume
    on a different topology can audit that the key sequence continues
    where the source run stopped."""
    return _rng.draws


def state_dict():
    """Serializable snapshot of the global RNG stream (exact-resume support:
    a checkpoint that captures this restores the *stream position*, so every
    post-restore ``next_key()`` returns exactly the key the uninterrupted
    run would have drawn). ``draws`` records the position in global-stream
    terms (it is topology-independent: per-replica keys fold the replica
    index inside the program). The transient ``fork_rng`` base is
    trace-local state and is deliberately not captured."""
    import numpy as np
    return {"seed": _rng.seed,
            "key": np.asarray(jax.random.key_data(_rng.key)),
            "philox_counter": _rng.philox_counter,
            "draws": _rng.draws}


def set_state_dict(state):
    """Restore a snapshot taken by ``state_dict``."""
    import numpy as np
    _rng.seed = int(state["seed"])
    _rng.key = jax.random.wrap_key_data(
        jax.numpy.asarray(np.asarray(state["key"], dtype=np.uint32)))
    _rng.philox_counter = int(state.get("philox_counter", 0))
    _rng.draws = int(state.get("draws", 0))


def advance(n):
    """Burn ``n`` keys from the global stream (fast-forward). Used by the
    anomaly-rollback policy to skip past a poison batch: after restoring a
    checkpoint's RNG state, advancing by the number of batches consumed
    since that checkpoint realigns the stream with the data position."""
    for _ in range(int(n)):
        next_key()


@contextlib.contextmanager
def fork_rng(base_key):
    """Install a (possibly traced) base key; next_key() becomes a pure function
    of it for the duration. Used by functional_call/TrainStep for dropout etc."""
    prev = _rng.forked
    _rng.forked = (base_key, 0)
    try:
        yield
    finally:
        _rng.forked = prev
