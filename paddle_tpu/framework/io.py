"""paddle.save / paddle.load (ref: python/paddle/framework/io.py).

Pickle-based state persistence. Tensors serialize as numpy arrays; nested
dicts/lists/state_dicts round-trip. Distributed arrays are fetched to host
(fully replicated view) before saving — sharded/async checkpointing lives in
paddle_tpu.incubate.checkpoint.
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax

from ..tensor_impl import Tensor


def _to_savable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(jax.device_get(obj._data)),
                "stop_gradient": obj.stop_gradient, "name": obj.name}
    if isinstance(obj, jax.Array):
        return {"__tensor__": True, "data": np.asarray(jax.device_get(obj)),
                "stop_gradient": True, "name": None}
    if isinstance(obj, dict):
        return {k: _to_savable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_savable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_savable(obj):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            t = Tensor(obj["data"], stop_gradient=obj.get("stop_gradient", True))
            t.name = obj.get("name")
            return t
        return {k: _from_savable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_savable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_savable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _from_savable(pickle.load(f))
