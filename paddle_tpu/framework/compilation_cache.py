"""Persistent XLA compilation cache wiring.

jax can serialize compiled executables to disk and reload them in later
processes (the TPU analog of the reference's cached CUDA kernel binaries +
cudnn autotune cache). We point it at `JAX_COMPILATION_CACHE_DIR` when set,
else `<cwd>/.jax_cache/`, the first time any paddle_tpu path creates a jitted
executable — so a fresh process re-running the same training script skips
XLA recompilation entirely.

Lazy by design: importing paddle_tpu must not create directories or mutate
jax config; the first dispatch-cache entry / TrainStep / to_static build
triggers it. `FLAGS_persistent_compilation_cache=False` (or an explicit
user-set jax_compilation_cache_dir) leaves the config untouched.
"""
from __future__ import annotations

import os
import threading

import jax

_lock = threading.Lock()
_initialized = False


def _flags_enabled():
    from .. import flags as _flags
    return bool(_flags._FLAGS.get("FLAGS_persistent_compilation_cache", True))


def ensure_persistent_cache():
    """Idempotent: enable jax's on-disk compilation cache once per process."""
    global _initialized
    if _initialized and _flags_enabled():
        return  # fast path only while the flag still agrees with the latch
    with _lock:
        from .. import flags as _flags
        enabled = _flags._FLAGS.get("FLAGS_persistent_compilation_cache", True)
        if _initialized:
            if not enabled:
                # flag turned off after we enabled the cache: undo it at the
                # next build point so the knob stays live both ways
                try:
                    jax.config.update("jax_compilation_cache_dir", None)
                except Exception:
                    pass
                _initialized = False
            return
        if not enabled:
            return  # latch NOT set: enabling the flag later still works
        _initialized = True
        try:
            current = jax.config.jax_compilation_cache_dir
        except AttributeError:
            return  # jax without the compilation-cache config
        if current:  # user (or autotune) already chose a directory
            return
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or \
            os.path.join(os.getcwd(), ".jax_cache")
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        except Exception:
            pass  # persistent cache is an optimization, never a hard dep


def cache_dir():
    """The active persistent-cache directory, or None when disabled."""
    try:
        return jax.config.jax_compilation_cache_dir
    except AttributeError:
        return None
