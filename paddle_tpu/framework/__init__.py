from .state import (  # noqa: F401
    no_grad,
    enable_grad,
    set_grad_enabled,
    grad_enabled,
    set_default_dtype,
    get_default_dtype,
    to_jnp_dtype,
    functional_trace,
    in_functional_trace,
)
from .random import seed, get_seed, next_key, fork_rng  # noqa: F401
from .device import (  # noqa: F401
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    Place,
    set_device,
    get_device,
    device_count,
)
