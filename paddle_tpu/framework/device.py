"""Device/place API (ref: python/paddle/device/__init__.py).

On TPU there is one accelerator type; jax manages placement. We keep Place
objects for API parity and route `set_device` to jax default-device selection.
"""
from __future__ import annotations

import jax


class Place:
    def __init__(self, kind: str, device_id: int = 0):
        self._kind = kind
        self._id = device_id

    def __repr__(self):
        return f"Place({self._kind}:{self._id})" if self._kind != "cpu" else "Place(cpu)"

    def __eq__(self, other):
        return isinstance(other, Place) and (self._kind, self._id) == (other._kind, other._id)


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu")


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPlace(TPUPlace):
    """Alias for scripts written against the reference's GPU API: maps to the
    local accelerator (ref CUDAPlace semantics -> accelerator device n)."""


_current = None


def _default_kind() -> str:
    return jax.default_backend()  # "tpu" | "cpu" | ...


def set_device(device: str):
    """paddle.device.set_device("tpu:0"|"cpu"|"gpu:0") parity; gpu maps to tpu."""
    global _current
    kind, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    kind = {"gpu": "tpu", "cuda": "tpu", "tpu": "tpu", "cpu": "cpu"}.get(kind, kind)
    try:
        dev = jax.devices(kind)[idx]
    except RuntimeError:
        dev = jax.devices()[0]
        kind = dev.platform
    jax.config.update("jax_default_device", dev)
    _current = f"{kind}:{idx}" if kind != "cpu" else "cpu"
    return _current


def get_device() -> str:
    if _current is not None:
        return _current
    kind = _default_kind()
    return "cpu" if kind == "cpu" else f"{kind}:0"


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:  # API parity; we are a TPU framework
    return False


def is_compiled_with_tpu() -> bool:
    return True


class XPUPlace(Place):
    def __init__(self, *a):
        raise NotImplementedError("XPU is out of scope on the TPU build")


class IPUPlace(Place):
    def __init__(self, *a):
        raise NotImplementedError("IPU is out of scope on the TPU build")


def get_cudnn_version():
    return None  # no cuDNN in an XLA/TPU stack


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False  # XLA plays CINN's role


def is_compiled_with_rocm():
    return False


def is_compiled_with_custom_device(device_type=None):
    return False


def get_all_device_type():
    return ["cpu", "tpu"]


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


class Stream:
    """XLA orders work internally; streams surface as no-op handles
    (ref: device/cuda/streams.py)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def set_stream(stream):
    global _current_stream
    _current_stream = stream
    return stream


import contextlib as _ctx


@_ctx.contextmanager
def stream_guard(stream):
    old = current_stream()
    set_stream(stream)
    try:
        yield
    finally:
        set_stream(old)


def synchronize(device=None):
    """Block until all queued device work completes."""
    import jax
    (jax.device_put(0) + 0).block_until_ready()
