"""Global framework state: grad mode, default dtype, AMP policy.

TPU-native re-design of the reference's global tracer/AMP state
(ref: python/paddle/fluid/framework.py, python/paddle/amp/auto_cast.py).
State is plain Python (consulted at op-dispatch time); nothing here is
traced into XLA programs.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.default_dtype = jnp.float32
        # AMP: level in {None, "O1", "O2"}; dtype is a jnp dtype
        self.amp_level = None
        self.amp_dtype = jnp.bfloat16
        self.amp_custom_white = set()
        self.amp_custom_black = set()
        # When true, op dispatch must not record tape nodes (functional tracing).
        self.functional_trace = False


_state = _State()


def grad_enabled() -> bool:
    return _state.grad_enabled and not _state.functional_trace


def set_grad_enabled(mode: bool):
    """Context manager / direct setter mirroring paddle.set_grad_enabled."""
    return _GradMode(mode)


class _GradMode(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = bool(mode)
        self._prev = _state.grad_enabled
        _state.grad_enabled = self._mode

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False


class no_grad(contextlib.ContextDecorator):
    """paddle.no_grad parity: context manager and decorator."""

    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = False
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _state.grad_enabled
        _state.grad_enabled = True
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self._prev
        return False


@contextlib.contextmanager
def functional_trace():
    """Mark region as functional tracing: no tape recording, pure ops only."""
    prev = _state.functional_trace
    _state.functional_trace = True
    try:
        yield
    finally:
        _state.functional_trace = prev


def in_functional_trace() -> bool:
    return _state.functional_trace


_DTYPE_ALIASES = {
    "float32": jnp.float32, "float64": jnp.float64, "float16": jnp.float16,
    "bfloat16": jnp.bfloat16, "fp32": jnp.float32, "fp16": jnp.float16,
    "bf16": jnp.bfloat16, "int32": jnp.int32, "int64": jnp.int64,
    "int16": jnp.int16, "int8": jnp.int8, "uint8": jnp.uint8,
    "bool": jnp.bool_, "complex64": jnp.complex64, "complex128": jnp.complex128,
}


def to_jnp_dtype(dtype):
    """Normalize a paddle-style dtype spec (str / jnp dtype / np dtype) to jnp."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower().replace("paddle.", "")
        if key in _DTYPE_ALIASES:
            return _DTYPE_ALIASES[key]
    return jnp.dtype(dtype).type if not hasattr(dtype, "dtype") else dtype


def set_default_dtype(dtype):
    d = to_jnp_dtype(dtype)
    if jnp.dtype(d).kind != "f":
        raise TypeError(f"default dtype must be floating, got {dtype}")
    _state.default_dtype = d


def get_default_dtype():
    return _state.default_dtype
