"""Framework-level long-tail APIs (ref: python/paddle/framework/__init__.py,
python/paddle/fluid/framework.py): RNG state, print options, LazyGuard,
DataParallel, create_parameter, flops, batch reader."""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from . import random as _random
from ..tensor_impl import Tensor, Parameter, as_tensor_data

__all__ = [
    "get_rng_state", "set_rng_state", "get_cuda_rng_state",
    "set_cuda_rng_state", "set_printoptions", "disable_signal_handler",
    "LazyGuard", "DataParallel", "create_parameter", "flops", "batch",
    "check_shape",
]


def get_rng_state(device=None):
    """The global PRNG key (TPU-native analog of the generator state list)."""
    return [_random._rng.key]


def set_rng_state(state_list, device=None):
    key = state_list[0] if isinstance(state_list, (list, tuple)) else state_list
    _random._rng.key = key


# single accelerator namespace on TPU: the "cuda" generator IS the generator
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor repr printing options (backed by numpy's printoptions)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op: jax/XLA installs no competing signal handlers (the reference
    needed this for its C++ runtime's SIGSEGV hooks)."""


class LazyGuard:
    """Parity shim for lazy (deferred) parameter init. Our initializers
    already run at first trace on-device, so materialization is inherently
    lazy with respect to host memory; the guard is a no-op context."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class DataParallel:
    """ref: paddle.DataParallel. Under single-controller SPMD, data
    parallelism is a mesh axis (GSPMD shards the batch), so this wrapper only
    needs to preserve the reference's interface: attribute passthrough,
    `scale_loss`/`no_sync` semantics."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss  # XLA's psum-of-mean handles scaling inside the step

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """ref: paddle.create_parameter (static/layer_helper path)."""
    from ..nn import initializer as I
    if default_initializer is None:
        default_initializer = I.Constant(0.0) if is_bias else I.XavierNormal()
    data = default_initializer(tuple(int(s) for s in shape), dtype)
    return Parameter(as_tensor_data(data) if not isinstance(data, jnp.ndarray)
                     else data, name=name)


def batch(reader, batch_size, drop_last=False):
    """ref: paddle.batch — wrap an item-reader into a batch-reader."""

    def batch_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def check_shape(shape):
    """Validate a shape argument (ref: fluid check_shape utility)."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if not isinstance(s, (int, np.integer)) and s is not None:
                raise TypeError(f"shape entries must be int, got {type(s)}")
    return True


_CONV_CLASSES = ("Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
                 "Conv2DTranspose", "Conv3DTranspose")


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Static FLOPs estimate by layer walk (ref: paddle.flops /
    hapi/dynamic_flops.py): counts multiply-adds of conv/linear plus norm and
    activation elementwise costs from a tracing forward."""
    import paddle_tpu as paddle

    total = [0]
    hooks = []

    def count(layer, inputs, output):
        cls = type(layer).__name__
        if custom_ops and type(layer) in custom_ops:
            total[0] += int(custom_ops[type(layer)](layer, inputs, output))
            return
        x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
        out = output[0] if isinstance(output, (tuple, list)) else output
        out_elems = int(np.prod(out.shape)) if hasattr(out, "shape") else 0
        if cls == "Linear":
            total[0] += 2 * out_elems * layer.weight.shape[0]
        elif cls in _CONV_CLASSES:
            w = layer.weight
            kernel_elems = int(np.prod(w.shape[1:]))
            total[0] += 2 * out_elems * kernel_elems
        elif cls in ("BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "LayerNorm",
                     "GroupNorm", "InstanceNorm2D"):
            total[0] += 2 * out_elems
        elif cls in ("ReLU", "GELU", "Sigmoid", "Tanh", "Softmax"):
            total[0] += out_elems

    for sub in net.sublayers(include_self=True):
        hooks.append(sub.register_forward_post_hook(count))
    try:
        x = paddle.zeros(list(input_size), "float32")
        net(x)
    finally:
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]}")
    return total[0]
