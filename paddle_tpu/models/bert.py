"""BERT — bidirectional encoder for MLM/NSP pretraining.

Capability target: the reference's BERT pretraining path (dygraph_to_static →
StandaloneExecutor benchmark config). Uses the shared transformer encoder
stack; attention runs the flash/blockwise kernel without causal masking.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..nn.layer_base import Layer
from ..nn import functional as F
from ..tensor_impl import Tensor
from ..tensor import manipulation as M


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0


BERT_CONFIGS = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(hidden_size=1024, num_hidden_layers=24,
                             num_attention_heads=16, intermediate_size=4096),
}


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, cfg.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=attr)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size, weight_attr=attr)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size, weight_attr=attr)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        S = input_ids.shape[1]
        pos = Tensor(jnp.arange(S, dtype=jnp.int32)[None, :])
        emb = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertModel(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation="gelu",
            attn_dropout=cfg.attention_probs_dropout_prob, normalize_before=False)
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_hidden_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B,S] 1/0 -> additive [B,1,1,S]
            am = (1.0 - attention_mask.astype("float32")) * -1e4
            attention_mask = am.unsqueeze(1).unsqueeze(1)
        seq = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForPretraining(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.mlm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size)
        self.nsp_head = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        mlm = self.mlm_head(self.mlm_norm(F.gelu(self.mlm_transform(seq))))
        nsp = self.nsp_head(pooled)
        return mlm, nsp

    def loss(self, outputs, mlm_labels, nsp_labels=None, ignore_index=-100):
        mlm, nsp = outputs
        V = mlm.shape[-1]
        loss = F.cross_entropy(M.reshape(mlm, [-1, V]),
                               M.reshape(mlm_labels, [-1]),
                               ignore_index=ignore_index)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(nsp, nsp_labels)
        return loss

    def pretraining_loss(self, input_ids, mlm_labels, token_type_ids=None,
                         attention_mask=None, nsp_labels=None,
                         ignore_index=-100):
        """MLM(+NSP) loss with the LM head fused into the cross-entropy —
        the ``[B, S, V]`` logits buffer never exists (a bf16 30k-vocab
        logits tensor alone is ~2 GB at bs64 seq512 and OOMs a 16 GB chip
        with its backward; capability target: the reference's fused
        softmax_with_cross_entropy, python/paddle/nn/functional/loss.py).
        Same value as ``loss(forward(...), ...)`` up to fp32 rounding."""
        from ..dispatch import apply as _apply
        from ..ops.fused_ce import fused_linear_cross_entropy

        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))

        def f(hd, w, b, labels):
            N = hd.shape[0] * hd.shape[1]
            losses = fused_linear_cross_entropy(
                hd.reshape(N, hd.shape[-1]), w.astype(hd.dtype),
                labels.reshape(-1).astype(jnp.int32), head_b=b.astype(hd.dtype))
            keep = labels.reshape(-1) != ignore_index
            n = jnp.maximum(jnp.sum(keep), 1)
            return jnp.sum(jnp.where(keep, losses, 0.0)) / n

        loss = _apply(f, h, self.mlm_head.weight, self.mlm_head.bias,
                      mlm_labels, op_name="fused_mlm_loss")
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(self.nsp_head(pooled), nsp_labels)
        return loss


class BertForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        return self.classifier(self.dropout(pooled))
