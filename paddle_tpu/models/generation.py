"""Autoregressive generation for the GPT family (capability parity with the
reference ecosystem's `model.generate`, ref PaddleNLP-class usage of
python/paddle — greedy/top-k/top-p sampling over a KV cache).

TPU-native design: ONE jitted XLA program runs prefill + the whole decode
loop (`lax.scan` over positions, static shapes, preallocated KV cache with
`dynamic_update_slice`). The eager alternative — one dispatch per token —
would pay a host->device round trip per step; here the host sees a single
call per generation.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .gpt import ln_fp32


def _delta_proj(x, w, aid, ad_l, name):
    """Base projection ``x @ w`` plus the per-row LoRA delta when this
    layer's adapter slab covers ``name`` — the SAME ops (take + batched
    einsum pair + masked compose, ops/pallas_kernels/quant_gemm.py) the
    serving engine runs, so a solo reference decode with ``adapters=`` is
    bitwise comparable to the engine's mixed-adapter batch rows."""
    base = x @ w.astype(x.dtype)
    if ad_l is None or name not in ad_l:
        return base
    from ..ops.pallas_kernels.quant_gemm import lora_delta, compose_delta
    A_l, B_l = ad_l[name]
    return compose_delta(base, lora_delta(x, A_l, B_l, aid), aid)


def _layer_cached(p, h, kc, vc, start, nh, eps, aid=None, ad_l=None):
    """One transformer block over h [B,T,H] with KV cache [B,Smax,nh,d].
    Positions [start, start+T) are written; attention keys are the cache
    prefix up to start+T (mask below). Mirrors gpt_block_fn math
    (models/gpt.py) plus cache read/write. ``aid``/``ad_l`` (serving
    adapters reference path): per-row adapter ids + this layer's slab
    rows, joined into the out/up/down projections — qkv stays un-adapted
    by construction (serving/adapters.py)."""
    B, T, H = h.shape
    d = H // nh

    def ln(x, g, b):
        return ln_fp32(x, g, b, eps)

    h1 = ln(h, p["ln1_g"], p["ln1_b"])
    qkv = h1 @ p["qkv_w"].astype(h.dtype) + p["qkv_b"].astype(h.dtype)
    q, k, v = jnp.split(qkv.reshape(B, T, 3, nh, d), 3, axis=2)
    q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]
    kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype), (0, start, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype), (0, start, 0, 0))
    Smax = kc.shape[1]
    # causal mask in absolute positions: query t attends keys <= start+t
    key_pos = jnp.arange(Smax)[None, :]
    q_pos = start + jnp.arange(T)[:, None]
    mask = key_pos <= q_pos                                   # [T, Smax]
    scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                        kc.astype(jnp.float32)) / (d ** 0.5)
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhts,bshd->bthd", probs,
                     vc.astype(jnp.float32)).astype(h.dtype)
    attn = _delta_proj(ctx.reshape(B, T, H), p["out_w"], aid, ad_l,
                       "out_w") + p["out_b"].astype(h.dtype)
    h = h + attn
    h2 = ln(h, p["ln2_g"], p["ln2_b"])
    up = _delta_proj(h2, p["up_w"], aid, ad_l, "up_w") + \
        p["up_b"].astype(h.dtype)
    up = jax.nn.gelu(up, approximate=True)
    return h + _delta_proj(up, p["down_w"], aid, ad_l, "down_w") + \
        p["down_b"].astype(h.dtype), kc, vc


def _final_ln(params, config, xlast):
    """Final LN (fp32) over last-position hidden states [B,H] — shared
    with the mp serving forward, which follows it with a vocab-SHARDED
    head matmul (serving/mp_forward.py) instead of the full one below."""
    xf = xlast.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    xn = (xf - mu) * jax.lax.rsqrt(var + config.layer_norm_epsilon)
    return xn * params["lnf_g"].astype(jnp.float32) + \
        params["lnf_b"].astype(jnp.float32)


def _final_logits(params, config, xlast):
    """Final LN (fp32) + LM head over last-position hidden states [B,H]."""
    return _final_ln(params, config, xlast) @ \
        params["head_w"].astype(jnp.float32)


def _forward_cached(params, config, ids, kc, vc, start, last_index=None,
                    adapters=None):
    """ids [B,T] at absolute positions [start, start+T); returns logits of
    the LAST position [B,V] and the updated cache. ``last_index`` (traced
    scalar) selects which position's logits to return instead of T-1 — the
    serving engine prefills prompts right-padded to a bucket length and
    reads logits at the true last prompt token. ``adapters`` = (aid [B],
    slabs) — the solo-reference adapter path (slabs ride the layer scan,
    exactly like the paged engine's fused step)."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    B, T = ids.shape
    pos = start + jnp.arange(T)
    x = params["wte"].astype(compute)[ids] + \
        jnp.take(params["wpe"].astype(compute), pos, axis=0)[None]
    nh = config.num_heads
    aid, slabs = adapters if adapters is not None else (None, None)

    def layer_fn(h, xs):
        if adapters is not None:
            xs, ad_l = xs[:-1], xs[-1]
        else:
            ad_l = None
        p_l, kc_l, vc_l = xs
        h, kc_l, vc_l = _layer_cached(p_l, h, kc_l, vc_l, start, nh,
                                      config.layer_norm_epsilon, aid, ad_l)
        return h, (kc_l, vc_l)

    xs = (params["blocks"], kc, vc)
    if adapters is not None:
        xs = xs + (slabs,)
    x, (kc, vc) = jax.lax.scan(layer_fn, x, xs)
    if last_index is None:
        xlast = x[:, -1]
    else:
        xlast = jax.lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)[:, 0]
    return _final_logits(params, config, xlast), kc, vc


def _layer_decode_slots(p, h, kc, vc, pos, nh, eps):
    """One transformer block over h [B,1,H] where each batch row is an
    independent serving SLOT at its own absolute position pos[b]. KV is
    scattered row-wise at pos[b]; attention masks keys per slot
    (key_pos <= pos[b]). Math mirrors _layer_cached exactly so a slot's
    token stream is bitwise identical to single-request decode."""
    B, T, H = h.shape
    d = H // nh

    def ln(x, g, b):
        return ln_fp32(x, g, b, eps)

    h1 = ln(h, p["ln1_g"], p["ln1_b"])
    qkv = h1 @ p["qkv_w"].astype(h.dtype) + p["qkv_b"].astype(h.dtype)
    q, k, v = jnp.split(qkv.reshape(B, T, 3, nh, d), 3, axis=2)
    q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]
    rows = jnp.arange(B)
    kc = kc.at[rows, pos].set(k[:, 0].astype(kc.dtype))
    vc = vc.at[rows, pos].set(v[:, 0].astype(vc.dtype))
    Smax = kc.shape[1]
    mask = jnp.arange(Smax)[None, :] <= pos[:, None]          # [B, Smax]
    scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                        kc.astype(jnp.float32)) / (d ** 0.5)
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhts,bshd->bthd", probs,
                     vc.astype(jnp.float32)).astype(h.dtype)
    attn = ctx.reshape(B, T, H) @ p["out_w"].astype(h.dtype) + \
        p["out_b"].astype(h.dtype)
    h = h + attn
    h2 = ln(h, p["ln2_g"], p["ln2_b"])
    up = h2 @ p["up_w"].astype(h.dtype) + p["up_b"].astype(h.dtype)
    up = jax.nn.gelu(up, approximate=True)
    return h + up @ p["down_w"].astype(h.dtype) + p["down_b"].astype(h.dtype), \
        kc, vc


def _forward_decode_slots(params, config, tok, kc, vc, pos):
    """One decode step over B independent slots: tok [B] is each slot's
    last token, fed at absolute position pos[b]. Returns logits [B,V] and
    the updated cache [L,B,Smax,nh,d]."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    x = params["wte"].astype(compute)[tok[:, None]] + \
        jnp.take(params["wpe"].astype(compute), pos, axis=0)[:, None]
    nh = config.num_heads

    def layer_fn(h, xs):
        p_l, kc_l, vc_l = xs
        h, kc_l, vc_l = _layer_decode_slots(p_l, h, kc_l, vc_l, pos, nh,
                                            config.layer_norm_epsilon)
        return h, (kc_l, vc_l)

    x, (kc, vc) = jax.lax.scan(layer_fn, x, (params["blocks"], kc, vc))
    return _final_logits(params, config, x[:, 0]), kc, vc


def _mask_logits(logits, temperature, top_k, top_p):
    """Sampling logits transform: temperature scale, static top-k cut,
    nucleus (top-p) cut. temperature/top_p are TRACED operands (scalar or
    per-row [B] — sweeping them never recompiles); top_k stays static (it
    changes the top_k kernel's shape). top_p=None skips the nucleus branch
    structurally (the old static `top_p in (None, 1.0)` contract)."""
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    if getattr(t, "ndim", 0) == logits.ndim - 1:
        t = t[..., None]
    logits = logits / t
    if top_k is not None and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        p = jnp.asarray(top_p, jnp.float32)
        if getattr(p, "ndim", 0) == logits.ndim - 1:
            p = p[..., None]
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < p          # always keeps the top token
        # p >= 1.0 must keep EVERY token (a traced 1.0 stands in for
        # "no nucleus cut" — the serving engine's per-slot top_p=None):
        # float32 cumsum saturates at 1.0 before the tail, so without this
        # the comparison would mask tiny-probability tail tokens and break
        # bitwise parity with the structural top_p=None skip.
        keep_sorted = keep_sorted | (p >= 1.0)
        inv = jnp.argsort(sort_idx, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def _select_token(logits, key, do_sample, temperature, top_k, top_p):
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, _mask_logits(logits, temperature, top_k, top_p)).astype(jnp.int32)


def _is_stop(tok, stop_token_ids):
    """Elementwise membership of tok in the static stop-id tuple."""
    hit = tok == stop_token_ids[0]
    for s in stop_token_ids[1:]:
        hit = hit | (tok == s)
    return hit


def _verify_accept(logits, ids_next, nprop, emit, do_sample, temperature,
                   top_p, key_data, top_k):
    """Speculative accept scan over the verify window's logits
    [B, T, V] (T = k+1). Lane i's logits score the token AT window
    position i, so its selected token is the TRUE next token after i;
    the slot keeps emitting while each selected token matches the draft's
    proposal for the next lane (ids_next [B, T], garbage in the last
    lane — never compared, since lane T-1 has ``i == nprop`` at most).

    PRNG discipline — the whole bitwise contract lives here: each slot's
    threefry key splits ONCE PER EMITTED token, greedy included, exactly
    like the plain fused step and ``_generate_jit``. Lanes past the
    accept point (``going`` False) select garbage greedily, split
    nothing, and advance nothing, so a sampled stream replays
    ``generate_from_params`` token-for-token no matter where rejection
    lands. temperature/top_p are per-slot traced operands; top_k is
    static (shape of the top_k cut).

    Returns (toks [B, T] — lanes >= n_emit[b] garbage, n_emit [B] int32
    with emit=False slots at 0, new key_data [B, 2] uint32)."""
    B, T, _ = logits.shape

    def step(carry, xs):
        key_data, going, n_emit = carry
        lg, nxt_prop, i = xs
        pair = jax.vmap(jax.random.split)(
            jax.random.wrap_key_data(key_data))
        greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        sampled = jax.vmap(jax.random.categorical)(
            pair[:, 1],
            _mask_logits(lg, temperature, top_k, top_p)).astype(jnp.int32)
        t = jnp.where(do_sample & going, sampled, greedy)
        new_kd = jnp.where(going[:, None],
                           jax.random.key_data(pair[:, 0]), key_data)
        n_emit = n_emit + going
        going = going & (i < nprop) & (t == nxt_prop)
        return (new_kd, going, n_emit), t

    (key_data, _, n_emit), toks = jax.lax.scan(
        step, (key_data, emit, jnp.zeros((B,), jnp.int32)),
        (jnp.swapaxes(logits, 0, 1), ids_next.T, jnp.arange(T)))
    return toks.T, n_emit, key_data


def _cfg_view(cfg):
    """cfg is a hashable static tuple (nh, L, H, eps, compute_dtype_str) —
    GPTConfig itself is a mutable dataclass and cannot key the jit cache."""
    class config:  # minimal view the helpers read
        num_heads, num_layers, hidden_size, layer_norm_epsilon = cfg[:4]
        compute_dtype = cfg[4]
    return config


def _alloc_cache(config, rows, total):
    nh = config.num_heads
    d = config.hidden_size // nh
    compute = jnp.dtype(config.compute_dtype or "float32")
    shape = (config.num_layers, rows, total, nh, d)
    return jnp.zeros(shape, compute), jnp.zeros(shape, compute)


# number of times _generate_jit has actually been TRACED (the body runs
# only on a cache miss) — the no-recompile evidence for traced sampling
# params. Tests measure deltas across sampling-config sweeps.
_gen_traces = 0


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "do_sample",
                                   "top_k", "stop_token_ids"))
def _generate_jit(params, ids, key, adapters=None, *, cfg, max_new_tokens,
                  do_sample, temperature, top_k, top_p, stop_token_ids):
    global _gen_traces
    _gen_traces += 1
    config = _cfg_view(cfg)
    B, P = ids.shape
    total = P + max_new_tokens
    kc, vc = _alloc_cache(config, B, total)

    logits, kc, vc = _forward_cached(params, config, ids, kc, vc, 0,
                                     adapters=adapters)
    key, sub = jax.random.split(key)
    tok = _select_token(logits, sub, do_sample, temperature, top_k, top_p)
    finished = jnp.zeros((B,), bool) if stop_token_ids is None else \
        _is_stop(tok, stop_token_ids)

    def step(carry, i):
        kc, vc, tok, finished, key = carry
        key, sub = jax.random.split(key)
        # tok was produced for absolute position P+i; feed it there
        logits, kc, vc = _forward_cached(params, config, tok[:, None],
                                         kc, vc, P + i, adapters=adapters)
        nxt = _select_token(logits, sub, do_sample, temperature, top_k, top_p)
        if stop_token_ids is not None:
            nxt = jnp.where(finished, stop_token_ids[0], nxt)
            finished = finished | _is_stop(nxt, stop_token_ids)
        return (kc, vc, nxt, finished, key), tok

    (kc, vc, last, finished, key), toks = jax.lax.scan(
        step, (kc, vc, tok, finished, key),
        jnp.arange(max_new_tokens - 1), length=max_new_tokens - 1)
    out = jnp.concatenate([toks.T, last[:, None]], axis=1)  # [B, new]
    return jnp.concatenate([ids, out], axis=1)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "num_beams",
                                   "eos_token_id"))
def _beam_search_jit(params, ids, *, cfg, max_new_tokens, num_beams,
                     length_penalty, eos_token_id):
    """Beam search in one XLA program (capability: the reference generate's
    beam_search mode). Beams live in the batch dim ([B*W, ...]); the KV
    cache is re-gathered along that dim on every beam reorder."""
    config = _cfg_view(cfg)
    B, P = ids.shape
    W = num_beams
    total = P + max_new_tokens
    NEG = jnp.float32(-1e9)

    # prefill ONCE per example ([B, P]), then fan the cache out to W beams
    # (the W beams are identical until the first expansion)
    kc1, vc1 = _alloc_cache(config, B, total)
    logits, kc1, vc1 = _forward_cached(params, config, ids, kc1, vc1, 0)
    kc = jnp.repeat(kc1, W, axis=1)
    vc = jnp.repeat(vc1, W, axis=1)
    first = jax.nn.log_softmax(logits, axis=-1)             # [B, V]
    V = first.shape[-1]
    scores, tok = jax.lax.top_k(first, W)                   # [B, W]
    tok = tok.astype(jnp.int32)
    finished = (tok == eos_token_id) if eos_token_id is not None else \
        jnp.zeros((B, W), bool)
    seqs = jnp.zeros((B, W, max_new_tokens), jnp.int32)
    seqs = seqs.at[:, :, 0].set(tok)

    def step(carry, i):
        kc, vc, tok, scores, finished, seqs = carry
        logits, kc, vc = _forward_cached(params, config,
                                         tok.reshape(B * W)[:, None],
                                         kc, vc, P + i)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, W, V)
        # finished beams extend only with eos at unchanged score
        if eos_token_id is not None:
            frozen = jnp.full((V,), NEG).at[eos_token_id].set(0.0)
            logp = jnp.where(finished[:, :, None], frozen[None, None], logp)
        cand = scores[:, :, None] + logp                    # [B, W, V]
        scores, idx = jax.lax.top_k(cand.reshape(B, W * V), W)
        beam = (idx // V).astype(jnp.int32)                 # [B, W]
        tok = (idx % V).astype(jnp.int32)
        # reorder beam state (incl. KV cache) along the B*W dim
        gidx = (jnp.arange(B)[:, None] * W + beam).reshape(B * W)
        kc = jnp.take(kc, gidx, axis=1)
        vc = jnp.take(vc, gidx, axis=1)
        seqs = jnp.take_along_axis(seqs, beam[:, :, None], axis=1)
        finished = jnp.take_along_axis(finished, beam, axis=1)
        if eos_token_id is not None:
            finished = finished | (tok == eos_token_id)
        seqs = seqs.at[:, :, i + 1].set(tok)
        return (kc, vc, tok, scores, finished, seqs), None

    (kc, vc, tok, scores, finished, seqs), _ = jax.lax.scan(
        step, (kc, vc, tok, scores, finished, seqs),
        jnp.arange(max_new_tokens - 1), length=max_new_tokens - 1)
    # pick the best beam under the GNMT length penalty
    if eos_token_id is not None:
        lengths = jnp.where(
            finished,
            1 + jnp.argmax((seqs == eos_token_id).astype(jnp.int32), axis=-1),
            max_new_tokens).astype(jnp.float32)
    else:
        lengths = jnp.full((B, W), float(max_new_tokens))
    norm = ((5.0 + lengths) / 6.0) ** length_penalty
    best = jnp.argmax(scores / norm, axis=-1)               # [B]
    best_seq = jnp.take_along_axis(
        seqs, best[:, None, None], axis=1)[:, 0]            # [B, new]
    return jnp.concatenate([ids, best_seq], axis=1)


def _normalize_stop(eos_token_id, stop_token_ids):
    """Merge the scalar eos alias with the stop-id list into one static
    tuple (eos first: it doubles as the pad id for finished rows, keeping
    the scalar form's output bitwise unchanged). Returns None when no stop
    condition was requested."""
    ids = []
    if eos_token_id is not None:
        ids.append(int(eos_token_id))
    if stop_token_ids is not None:
        if isinstance(stop_token_ids, (int, jnp.integer)):
            stop_token_ids = [stop_token_ids]
        for s in stop_token_ids:
            if int(s) not in ids:
                ids.append(int(s))
    return tuple(ids) if ids else None


def _collect_params(model):
    """GPTForCausalLM Layer -> the functional param layout
    (models/gpt_hybrid.py init_gpt_params)."""
    from .gpt import stack_block_params
    gpt = model.gpt
    head_w = (gpt.wte.weight._data.T if model.lm_head is None
              else model.lm_head.weight._data)
    return {
        "wte": gpt.wte.weight._data,
        "wpe": gpt.wpe.weight._data,
        "lnf_g": gpt.ln_f.weight._data,
        "lnf_b": gpt.ln_f.bias._data,
        "head_w": head_w,
        "blocks": stack_block_params(model),
    }


def _cfg_key(config):
    return (config.num_heads, config.num_layers, config.hidden_size,
            config.layer_norm_epsilon, config.compute_dtype)


def _logical_qkv(params, config):
    """Undo HybridTrainStep's head-major qkv storage (config.qkv_head_major,
    set under sequence parallelism — see tp_overlap.to_qkv_head_major).
    Decode always splits qkv as [3, nh, d], so head-major blocks must be
    permuted back to the logical layout or q/k/v columns interleave into
    the wrong heads. Pure relabeling, bitwise identical. Runs once per
    generate_from_params CALL (amortized over the whole decode); for
    repeated-generation loops pre-permute once — or use the serving
    Engine, which does this at construction."""
    if not getattr(config, "qkv_head_major", False):
        return params
    from ..distributed.tp_overlap import qkv_head_major_perm
    import numpy as np
    inv = np.argsort(qkv_head_major_perm(config.hidden_size,
                                         config.num_heads))
    blocks = dict(params["blocks"])
    blocks["qkv_w"] = jnp.asarray(blocks["qkv_w"])[..., inv]
    blocks["qkv_b"] = jnp.asarray(blocks["qkv_b"])[..., inv]
    return {**params, "blocks": blocks}


def _check_temperature(do_sample, temperature):
    """Sampled decoding divides logits by the temperature (_mask_logits);
    <= 0 would blow them up to +/-inf before the 1e-6 clamp makes the
    distribution a numerical accident. Greedy paths never read it."""
    if do_sample and temperature <= 0:
        raise ValueError(
            f"temperature must be > 0 when do_sample=True, got "
            f"{temperature} (use do_sample=False for greedy decoding)")


def generate_from_params(params, input_ids, config, max_new_tokens=32,
                         do_sample=False, temperature=1.0, top_k=None,
                         top_p=None, eos_token_id=None, seed=0,
                         stop_token_ids=None, adapters=None):
    """Generate from a FUNCTIONAL param tree (models/gpt_hybrid.py
    init_gpt_params layout) — the public decode entry for params produced
    by HybridTrainStep / the serving Engine, no Layer required.

    ``adapters=(adapter_id, slabs)`` is the solo-reference path for the
    adapter serving parity gates: slabs is an AdapterRegistry's
    ``device_slabs()`` dict and every row of this generation runs under
    ``adapter_id`` (0 = base) through the SAME take/einsum/compose ops
    the engine's mixed-adapter fused step uses — so engine rows are
    bitwise comparable against this for any batch composition."""
    from ..tensor_impl import Tensor
    ids = jnp.asarray(input_ids._data if isinstance(input_ids, Tensor)
                      else input_ids, jnp.int32)
    _check_temperature(do_sample, temperature)
    if max_new_tokens < 1:
        if max_new_tokens == 0:
            return Tensor(ids)
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    assert ids.shape[1] + max_new_tokens <= config.max_seq_len, \
        "prompt + max_new_tokens exceeds config.max_seq_len (wpe table)"
    params = _logical_qkv(params, config)
    if adapters is not None:
        aid, slabs = adapters
        adapters = (jnp.full((ids.shape[0],), int(aid), jnp.int32),
                    {n: tuple(s) for n, s in slabs.items()})
    out = _generate_jit(params, ids, jax.random.key(seed), adapters,
                        cfg=_cfg_key(config),
                        max_new_tokens=int(max_new_tokens),
                        do_sample=bool(do_sample),
                        temperature=float(temperature),
                        top_k=None if top_k in (None, 0)
                        else min(int(top_k), config.vocab_size),
                        top_p=None if top_p in (None, 1.0) else float(top_p),
                        stop_token_ids=_normalize_stop(eos_token_id,
                                                       stop_token_ids))
    return Tensor(out)


def generate(model, input_ids, max_new_tokens=32, do_sample=False,
             temperature=1.0, top_k=None, top_p=None, eos_token_id=None,
             seed=0, num_beams=1, length_penalty=1.0, stop_token_ids=None):
    """Generate from a GPTForCausalLM Layer. Collects its weights into the
    functional layout (models/gpt_hybrid.py init_gpt_params) and runs the
    single-program decode above."""
    from ..tensor_impl import Tensor
    config = model.config
    ids = jnp.asarray(input_ids._data if isinstance(input_ids, Tensor)
                      else input_ids, jnp.int32)
    _check_temperature(do_sample, temperature)
    if max_new_tokens < 1:
        if max_new_tokens == 0:
            return Tensor(ids)
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    assert ids.shape[1] + max_new_tokens <= config.max_seq_len, \
        "prompt + max_new_tokens exceeds config.max_seq_len (wpe table)"
    params = _collect_params(model)
    stop = _normalize_stop(eos_token_id, stop_token_ids)
    if num_beams > 1:
        if do_sample:
            raise ValueError("beam search is deterministic; do_sample=True "
                             "with num_beams > 1 is not supported")
        if stop is not None and len(stop) > 1:
            raise NotImplementedError(
                "beam search supports a single stop id (the frozen-beam "
                "rewrite needs one pad token); pass eos_token_id only")
        out = _beam_search_jit(params, ids, cfg=_cfg_key(config),
                               max_new_tokens=int(max_new_tokens),
                               num_beams=int(num_beams),
                               length_penalty=float(length_penalty),
                               eos_token_id=None if stop is None else stop[0])
        return Tensor(out)
    out = _generate_jit(params, ids, jax.random.key(seed), cfg=_cfg_key(config),
                        max_new_tokens=int(max_new_tokens),
                        do_sample=bool(do_sample),
                        temperature=float(temperature),
                        top_k=None if top_k in (None, 0)
                        else min(int(top_k), config.vocab_size),
                        top_p=None if top_p in (None, 1.0) else float(top_p),
                        stop_token_ids=stop)
    return Tensor(out)
