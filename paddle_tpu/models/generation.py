"""Autoregressive generation for the GPT family (capability parity with the
reference ecosystem's `model.generate`, ref PaddleNLP-class usage of
python/paddle — greedy/top-k/top-p sampling over a KV cache).

TPU-native design: ONE jitted XLA program runs prefill + the whole decode
loop (`lax.scan` over positions, static shapes, preallocated KV cache with
`dynamic_update_slice`). The eager alternative — one dispatch per token —
would pay a host->device round trip per step; here the host sees a single
call per generation.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .gpt import ln_fp32


def _layer_cached(p, h, kc, vc, start, nh, eps):
    """One transformer block over h [B,T,H] with KV cache [B,Smax,nh,d].
    Positions [start, start+T) are written; attention keys are the cache
    prefix up to start+T (mask below). Mirrors gpt_block_fn math
    (models/gpt.py) plus cache read/write."""
    B, T, H = h.shape
    d = H // nh

    def ln(x, g, b):
        return ln_fp32(x, g, b, eps)

    h1 = ln(h, p["ln1_g"], p["ln1_b"])
    qkv = h1 @ p["qkv_w"].astype(h.dtype) + p["qkv_b"].astype(h.dtype)
    q, k, v = jnp.split(qkv.reshape(B, T, 3, nh, d), 3, axis=2)
    q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]
    kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype), (0, start, 0, 0))
    vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype), (0, start, 0, 0))
    Smax = kc.shape[1]
    # causal mask in absolute positions: query t attends keys <= start+t
    key_pos = jnp.arange(Smax)[None, :]
    q_pos = start + jnp.arange(T)[:, None]
    mask = key_pos <= q_pos                                   # [T, Smax]
    scores = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                        kc.astype(jnp.float32)) / (d ** 0.5)
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = jnp.einsum("bhts,bshd->bthd", probs,
                     vc.astype(jnp.float32)).astype(h.dtype)
    attn = ctx.reshape(B, T, H) @ p["out_w"].astype(h.dtype) + \
        p["out_b"].astype(h.dtype)
    h = h + attn
    h2 = ln(h, p["ln2_g"], p["ln2_b"])
    up = h2 @ p["up_w"].astype(h.dtype) + p["up_b"].astype(h.dtype)
    up = jax.nn.gelu(up, approximate=True)
    return h + up @ p["down_w"].astype(h.dtype) + p["down_b"].astype(h.dtype), \
        kc, vc


def _forward_cached(params, config, ids, kc, vc, start):
    """ids [B,T] at absolute positions [start, start+T); returns logits of
    the LAST position [B,V] and the updated cache."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    B, T = ids.shape
    pos = start + jnp.arange(T)
    x = params["wte"].astype(compute)[ids] + \
        jnp.take(params["wpe"].astype(compute), pos, axis=0)[None]
    nh = config.num_heads

    def layer_fn(h, xs):
        p_l, kc_l, vc_l = xs
        h, kc_l, vc_l = _layer_cached(p_l, h, kc_l, vc_l, start, nh,
                                      config.layer_norm_epsilon)
        return h, (kc_l, vc_l)

    x, (kc, vc) = jax.lax.scan(layer_fn, x, (params["blocks"], kc, vc))
    xf = x[:, -1].astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    xn = (xf - mu) * jax.lax.rsqrt(var + config.layer_norm_epsilon)
    xn = xn * params["lnf_g"].astype(jnp.float32) + \
        params["lnf_b"].astype(jnp.float32)
    logits = xn @ params["head_w"].astype(jnp.float32)
    return logits, kc, vc


def _select_token(logits, key, do_sample, temperature, top_k, top_p):
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sort_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_p      # always keeps the top token
        inv = jnp.argsort(sort_idx, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return jax.random.categorical(key, logits).astype(jnp.int32)


def _cfg_view(cfg):
    """cfg is a hashable static tuple (nh, L, H, eps, compute_dtype_str) —
    GPTConfig itself is a mutable dataclass and cannot key the jit cache."""
    class config:  # minimal view the helpers read
        num_heads, num_layers, hidden_size, layer_norm_epsilon = cfg[:4]
        compute_dtype = cfg[4]
    return config


def _alloc_cache(config, rows, total):
    nh = config.num_heads
    d = config.hidden_size // nh
    compute = jnp.dtype(config.compute_dtype or "float32")
    shape = (config.num_layers, rows, total, nh, d)
    return jnp.zeros(shape, compute), jnp.zeros(shape, compute)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "do_sample",
                                   "top_k", "top_p", "eos_token_id"))
def _generate_jit(params, ids, key, *, cfg, max_new_tokens, do_sample,
                  temperature, top_k, top_p, eos_token_id):
    config = _cfg_view(cfg)
    B, P = ids.shape
    total = P + max_new_tokens
    kc, vc = _alloc_cache(config, B, total)

    logits, kc, vc = _forward_cached(params, config, ids, kc, vc, 0)
    key, sub = jax.random.split(key)
    tok = _select_token(logits, sub, do_sample, temperature, top_k, top_p)
    finished = jnp.zeros((B,), bool) if eos_token_id is None else \
        (tok == eos_token_id)

    def step(carry, i):
        kc, vc, tok, finished, key = carry
        key, sub = jax.random.split(key)
        # tok was produced for absolute position P+i; feed it there
        logits, kc, vc = _forward_cached(params, config, tok[:, None],
                                         kc, vc, P + i)
        nxt = _select_token(logits, sub, do_sample, temperature, top_k, top_p)
        if eos_token_id is not None:
            nxt = jnp.where(finished, eos_token_id, nxt)
            finished = finished | (nxt == eos_token_id)
        return (kc, vc, nxt, finished, key), tok

    (kc, vc, last, finished, key), toks = jax.lax.scan(
        step, (kc, vc, tok, finished, key),
        jnp.arange(max_new_tokens - 1), length=max_new_tokens - 1)
    out = jnp.concatenate([toks.T, last[:, None]], axis=1)  # [B, new]
    return jnp.concatenate([ids, out], axis=1)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "num_beams",
                                   "eos_token_id"))
def _beam_search_jit(params, ids, *, cfg, max_new_tokens, num_beams,
                     length_penalty, eos_token_id):
    """Beam search in one XLA program (capability: the reference generate's
    beam_search mode). Beams live in the batch dim ([B*W, ...]); the KV
    cache is re-gathered along that dim on every beam reorder."""
    config = _cfg_view(cfg)
    B, P = ids.shape
    W = num_beams
    total = P + max_new_tokens
    NEG = jnp.float32(-1e9)

    # prefill ONCE per example ([B, P]), then fan the cache out to W beams
    # (the W beams are identical until the first expansion)
    kc1, vc1 = _alloc_cache(config, B, total)
    logits, kc1, vc1 = _forward_cached(params, config, ids, kc1, vc1, 0)
    kc = jnp.repeat(kc1, W, axis=1)
    vc = jnp.repeat(vc1, W, axis=1)
    first = jax.nn.log_softmax(logits, axis=-1)             # [B, V]
    V = first.shape[-1]
    scores, tok = jax.lax.top_k(first, W)                   # [B, W]
    tok = tok.astype(jnp.int32)
    finished = (tok == eos_token_id) if eos_token_id is not None else \
        jnp.zeros((B, W), bool)
    seqs = jnp.zeros((B, W, max_new_tokens), jnp.int32)
    seqs = seqs.at[:, :, 0].set(tok)

    def step(carry, i):
        kc, vc, tok, scores, finished, seqs = carry
        logits, kc, vc = _forward_cached(params, config,
                                         tok.reshape(B * W)[:, None],
                                         kc, vc, P + i)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, W, V)
        # finished beams extend only with eos at unchanged score
        if eos_token_id is not None:
            frozen = jnp.full((V,), NEG).at[eos_token_id].set(0.0)
            logp = jnp.where(finished[:, :, None], frozen[None, None], logp)
        cand = scores[:, :, None] + logp                    # [B, W, V]
        scores, idx = jax.lax.top_k(cand.reshape(B, W * V), W)
        beam = (idx // V).astype(jnp.int32)                 # [B, W]
        tok = (idx % V).astype(jnp.int32)
        # reorder beam state (incl. KV cache) along the B*W dim
        gidx = (jnp.arange(B)[:, None] * W + beam).reshape(B * W)
        kc = jnp.take(kc, gidx, axis=1)
        vc = jnp.take(vc, gidx, axis=1)
        seqs = jnp.take_along_axis(seqs, beam[:, :, None], axis=1)
        finished = jnp.take_along_axis(finished, beam, axis=1)
        if eos_token_id is not None:
            finished = finished | (tok == eos_token_id)
        seqs = seqs.at[:, :, i + 1].set(tok)
        return (kc, vc, tok, scores, finished, seqs), None

    (kc, vc, tok, scores, finished, seqs), _ = jax.lax.scan(
        step, (kc, vc, tok, scores, finished, seqs),
        jnp.arange(max_new_tokens - 1), length=max_new_tokens - 1)
    # pick the best beam under the GNMT length penalty
    if eos_token_id is not None:
        lengths = jnp.where(
            finished,
            1 + jnp.argmax((seqs == eos_token_id).astype(jnp.int32), axis=-1),
            max_new_tokens).astype(jnp.float32)
    else:
        lengths = jnp.full((B, W), float(max_new_tokens))
    norm = ((5.0 + lengths) / 6.0) ** length_penalty
    best = jnp.argmax(scores / norm, axis=-1)               # [B]
    best_seq = jnp.take_along_axis(
        seqs, best[:, None, None], axis=1)[:, 0]            # [B, new]
    return jnp.concatenate([ids, best_seq], axis=1)


def generate_from_params(params, input_ids, config, max_new_tokens=32,
                         do_sample=False, temperature=1.0, top_k=None,
                         top_p=None, eos_token_id=None, seed=0):
    """Generate from a FUNCTIONAL param tree (models/gpt_hybrid.py
    init_gpt_params layout) — the public decode entry for params produced
    by HybridTrainStep / the Engine, no Layer required."""
    from ..tensor_impl import Tensor
    ids = jnp.asarray(input_ids._data if isinstance(input_ids, Tensor)
                      else input_ids, jnp.int32)
    assert ids.shape[1] + max_new_tokens <= config.max_seq_len, \
        "prompt + max_new_tokens exceeds config.max_seq_len (wpe table)"
    cfg_key = (config.num_heads, config.num_layers, config.hidden_size,
               config.layer_norm_epsilon, config.compute_dtype)
    out = _generate_jit(params, ids, jax.random.key(seed), cfg=cfg_key,
                        max_new_tokens=int(max_new_tokens),
                        do_sample=bool(do_sample),
                        temperature=float(temperature),
                        top_k=None if top_k in (None, 0)
                        else min(int(top_k), config.vocab_size),
                        top_p=None if top_p in (None, 1.0) else float(top_p),
                        eos_token_id=eos_token_id)
    return Tensor(out)


def generate(model, input_ids, max_new_tokens=32, do_sample=False,
             temperature=1.0, top_k=None, top_p=None, eos_token_id=None,
             seed=0, num_beams=1, length_penalty=1.0):
    """Generate from a GPTForCausalLM Layer. Collects its weights into the
    functional layout (models/gpt_hybrid.py init_gpt_params) and runs the
    single-program decode above."""
    from ..tensor_impl import Tensor
    from .gpt import stack_block_params
    config = model.config
    ids = jnp.asarray(input_ids._data if isinstance(input_ids, Tensor)
                      else input_ids, jnp.int32)
    if max_new_tokens < 1:
        if max_new_tokens == 0:
            return Tensor(ids)
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    assert ids.shape[1] + max_new_tokens <= config.max_seq_len, \
        "prompt + max_new_tokens exceeds config.max_seq_len (wpe table)"
    gpt = model.gpt
    head_w = (gpt.wte.weight._data.T if model.lm_head is None
              else model.lm_head.weight._data)
    params = {
        "wte": gpt.wte.weight._data,
        "wpe": gpt.wpe.weight._data,
        "lnf_g": gpt.ln_f.weight._data,
        "lnf_b": gpt.ln_f.bias._data,
        "head_w": head_w,
        "blocks": stack_block_params(model),
    }
    cfg_key = (config.num_heads, config.num_layers, config.hidden_size,
               config.layer_norm_epsilon, config.compute_dtype)
    if num_beams > 1:
        if do_sample:
            raise ValueError("beam search is deterministic; do_sample=True "
                             "with num_beams > 1 is not supported")
        out = _beam_search_jit(params, ids, cfg=cfg_key,
                               max_new_tokens=int(max_new_tokens),
                               num_beams=int(num_beams),
                               length_penalty=float(length_penalty),
                               eos_token_id=eos_token_id)
        return Tensor(out)
    out = _generate_jit(params, ids, jax.random.key(seed), cfg=cfg_key,
                        max_new_tokens=int(max_new_tokens),
                        do_sample=bool(do_sample),
                        temperature=float(temperature),
                        top_k=None if top_k in (None, 0)
                        else min(int(top_k), config.vocab_size),
                        top_p=None if top_p in (None, 1.0) else float(top_p),
                        eos_token_id=eos_token_id)
    return Tensor(out)
