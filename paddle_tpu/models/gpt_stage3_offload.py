"""Single-chip ZeRO-3-class FULL parameter offload for GPT training.

Capability target: the reference's group_sharded stage-3 with cpu offload
(ref: python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_stage3.py:84) — parameters, gradients AND optimizer moments
live in host memory; the accelerator holds only the small embedding/head
leaves, ONE transformer layer's weights, and activations. That is what
lets a 6.7B (and, at bs=1, a 13B-class) GPT train on a single 16 GB chip
backed by host RAM.

TPU-native design (no CUDA-style manual prefetch hooks):

- Block params are stacked ``[L, ...]`` arrays in ``pinned_host`` memory.
  The forward is a ``lax.scan`` over layers whose body fetches layer ``l``
  with ``device_put(dynamic_index(host_param, l))`` — one layer resident
  at a time. ``jax.checkpoint`` around the body makes the backward refetch
  instead of keeping all layers alive.
- The BACKWARD needs no hand-written stash: the transpose of the fetch is
  ``device_put`` back to the source (host) sharding, and the scan transpose
  accumulates the per-layer cotangents into a host-resident ``[L, ...]``
  gradient via per-iteration dynamic-update-slices (the same sliced-DMA
  pattern framework/offload.py streams optimizer moments with).
  NOTE the block gradients are INTERNAL values of the jitted step — only
  params/opt-state appear in ``out_shardings`` — so their host residency is
  not pinned by any output annotation: it relies on XLA propagating the
  memory space of the ``device_put`` transpose into the scan-transpose
  accumulator. That implicit placement is exactly what the on-chip smoke
  (``tools_stage3_smoke.py``) validates: at 6.7B the ``[L, ...]`` gradient
  alone exceeds HBM, so a refactor that lets XLA hoist the accumulator
  chip-side fails immediately with an OOM instead of silently regressing
  (the 2.7B streamed-offload run in TPU_SMOKE.log is the same guard at the
  scale already captured on hardware). Keep that in mind before touching
  the ``device_put`` placement in ``hidden``'s scan body.
- The optimizer update for block params runs over host-resident p/g/m/v in
  one of two modes:
    * ``update="stream"`` — a per-layer loop round-trips each layer's
      p/g/m/v through HBM once (3D matrix leaves; the tiny 2D bias/norm
      leaves bulk-transfer, both because their total is ~0.4% of params
      and because [1, H] host-DMA slices trip the TPU sublane-tiling
      check — see framework/offload.py);
    * ``update="host"`` — jax host-offload compute (``compute_on``): the
      elementwise AdamW math executes on the host CPU next to the data,
      no DMA at all (preferred on TPU when the runtime supports it).
- Small leaves (wte/wpe/lnf/head) stay device-resident with device slots.

Single-device only by design: multi-chip scale-out uses the mesh paths
(HybridTrainStep ZeRO-3 shards params across chips instead of offloading).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .gpt import GPTConfig, gpt_block_fn
from .gpt_hybrid import init_gpt_params
from ..framework import offload as _ol


@dataclass
class Stage3OffloadTrainStep:
    config: GPTConfig
    optimizer: object
    param_dtype: object = jnp.bfloat16
    seed: int = 0
    update: str = "stream"        # "stream" (proven) or "host" (compute_on)
    offload_enabled: bool = True  # False = device-resident (CPU math tests)

    def __post_init__(self):
        if self.update not in ("stream", "host"):
            raise ValueError(f"update={self.update!r}")
        if getattr(self.optimizer, "_grad_clip", None) is not None:
            # global-norm clip needs every gradient before any update —
            # with host-resident grads that is a full extra 2x DMA sweep;
            # rely on Adam's per-parameter normalization instead
            raise ValueError(
                "Stage3OffloadTrainStep does not support grad_clip: the "
                "global norm would force a full gradient sweep through "
                "HBM; construct the optimizer without grad_clip")
        if not getattr(self.optimizer, "_elementwise_update", False):
            # same guard as framework/offload.streamed_apply_gradients:
            # per-layer slices change the math of norm/history updates
            raise ValueError(
                "Stage3OffloadTrainStep streams per-layer slices, which "
                "only equals the bulk update for elementwise optimizers "
                "(Adam/AdamW/SGD/...); Lamb/LARS/LBFGS are not supported")
        if self.update == "host" and self.offload_enabled \
                and not _ol.in_jit_transfers_supported():
            raise ValueError(
                "update='host' needs TPU in-jit memory transfers "
                "(compute_on host offload); use update='stream' here")
        if self.offload_enabled and not _ol.in_jit_transfers_supported():
            # silently training device-resident would defeat the class's
            # purpose (and OOM outright at the 6.7B scale it exists for)
            raise ValueError(
                "this backend has no in-jit memory-kind transfers, so "
                "stage-3 offload cannot run; pass offload_enabled=False "
                "for a device-resident (test) instance")
        self._real = bool(self.offload_enabled
                          and _ol.in_jit_transfers_supported())
        if self._real:
            # init the block weights HOST-side: init_gpt_params would
            # materialize all [L, ...] leaves in HBM first (13.4G at 6.7B
            # — an OOM before training starts). numpy generates straight
            # into host memory; only the small leaves touch the device.
            self.small, self.blocks = self._init_host(self.config,
                                                      self.seed,
                                                      self.param_dtype)
        else:
            key = jax.random.key(self.seed)
            params = init_gpt_params(self.config, key, self.param_dtype)
            self.blocks = params.pop("blocks")   # {name: [L, ...]}
            self.small = params                  # wte/wpe/lnf/head_w
        self.opt_small = self.optimizer.init_state(self.small)
        self.opt_blocks = self.optimizer.init_state(self.blocks)
        if self._real:
            host = _ol.with_memory_kind(None, "pinned_host")
            self.opt_blocks = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, host) if jnp.ndim(a) else a,
                self.opt_blocks)
        self._jitted = None

    @staticmethod
    def _init_host(config, seed, param_dtype):
        """Same shapes/distributions as init_gpt_params, but block leaves
        are generated with numpy and placed directly in pinned host
        memory — device transient is one SMALL leaf at most."""
        H, L, V = config.hidden_size, config.num_layers, config.vocab_size
        Ienv = config.ffn_mult * H
        std = config.initializer_range
        rng = np.random.default_rng(seed)
        # ml_dtypes gives numpy a native bfloat16, so the cast happens in
        # host memory — jnp casts would round-trip the (huge) f32 array
        # through the device
        import ml_dtypes  # noqa: F401  (registers 'bfloat16' with numpy)
        np_dtype = np.dtype(jnp.dtype(param_dtype).name)

        def norm_np(shape):
            return (rng.standard_normal(shape, dtype=np.float32)
                    * std).astype(np_dtype)

        host = _ol.with_memory_kind(None, "pinned_host")

        def to_h(a):
            return jax.device_put(np.asarray(a, np_dtype), host)

        blocks = {
            "ln1_g": to_h(np.ones((L, H), np.float32)),
            "ln1_b": to_h(np.zeros((L, H), np.float32)),
            "qkv_w": to_h(norm_np((L, H, 3 * H))),
            "qkv_b": to_h(np.zeros((L, 3 * H), np.float32)),
            "out_w": to_h(norm_np((L, H, H))),
            "out_b": to_h(np.zeros((L, H), np.float32)),
            "ln2_g": to_h(np.ones((L, H), np.float32)),
            "ln2_b": to_h(np.zeros((L, H), np.float32)),
            "up_w": to_h(norm_np((L, H, Ienv))),
            "up_b": to_h(np.zeros((L, Ienv), np.float32)),
            "down_w": to_h(norm_np((L, Ienv, H))),
            "down_b": to_h(np.zeros((L, H), np.float32)),
        }
        small = {
            "wte": jnp.asarray(norm_np((V, H))),
            "wpe": jnp.asarray(norm_np((config.max_seq_len, H))),
            "lnf_g": jnp.ones((H,), param_dtype),
            "lnf_b": jnp.zeros((H,), param_dtype),
            "head_w": jnp.asarray(norm_np((H, V))),
        }
        return small, blocks

    # -- compiled step -------------------------------------------------------
    def _build(self):
        config = self.config
        optimizer = self.optimizer
        compute = jnp.dtype(config.compute_dtype or "float32")
        block = gpt_block_fn(config)
        L = config.num_layers
        real = self._real
        dev = _ol.with_memory_kind(None, "device") if real else None
        host = _ol.with_memory_kind(None, "pinned_host") if real else None
        ident = lambda a: a  # noqa: E731
        to_dev = (lambda a: jax.device_put(a, dev)) if real else ident
        to_host = (lambda a: jax.device_put(a, host)) if real else ident

        def hidden(small, blocks, ids):
            B, S = ids.shape
            x = small["wte"].astype(compute)[ids] + \
                small["wpe"].astype(compute)[None, :S]
            # only 3D matrix leaves stream per layer: [1, H] host-DMA
            # slices of the 2D bias/norm leaves are the sublane-tiling
            # pattern the TPU dynamic-index emitter rejects (and their
            # BACKWARD would dynamic-update-slice host arrays the same
            # way — the observed compiler crash). The 2D leaves are
            # ~0.4% of params: bulk-fetch them once, index on device.
            big = {k: v for k, v in blocks.items() if v.ndim >= 3}
            small2d = {k: to_dev(v) for k, v in blocks.items()
                       if v.ndim < 3}

            def body(h, l):
                p_l = {k: to_dev(
                    jax.lax.dynamic_index_in_dim(v, l, 0, keepdims=False))
                    for k, v in big.items()}
                p_l.update({k: jax.lax.dynamic_index_in_dim(
                    v, l, 0, keepdims=False) for k, v in small2d.items()})
                return block(p_l, h), None

            x, _ = jax.lax.scan(jax.checkpoint(body), x, jnp.arange(L))
            from .gpt_hybrid import final_ln_fp32
            return final_ln_fp32(x, small["lnf_g"], small["lnf_b"],
                                 config.layer_norm_epsilon).astype(compute)

        def loss_fn(small, blocks, ids):
            from ..ops.fused_ce import fused_lm_loss
            h = hidden(small, blocks, ids)
            return fused_lm_loss(h, small["head_w"].astype(h.dtype), ids)

        small_mask = {n: not (n.endswith("_b") or "ln" in n or n == "wpe")
                      for n in self.small}
        block_mask = {n: not (n.endswith("_b") or "ln" in n)
                      for n in self.blocks}

        def step_fn(small, blocks, opt_small, opt_blocks, ids, lr):
            loss, (g_small, g_blocks) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(small, blocks, ids)
            new_small, new_opt_small = optimizer.apply_gradients(
                small, g_small, opt_small, lr, wd_mask=small_mask)
            if self.update == "host" and real:
                from jax.experimental.compute_on import compute_on

                def host_update(blocks, g_blocks, opt_blocks, lr):
                    return optimizer.apply_gradients(
                        blocks, g_blocks, opt_blocks, lr,
                        wd_mask=block_mask)
                with compute_on("device_host"):
                    new_blocks, new_opt_blocks = host_update(
                        blocks, g_blocks, opt_blocks, lr)
            else:
                # shared streamed loop; transfer_params routes the
                # host-resident p/g through the same per-slice fetch the
                # moments use (2D leaves bulk-transfer via its small path)
                new_blocks, new_opt_blocks = _ol.streamed_apply_gradients(
                    self.optimizer, blocks, g_blocks, opt_blocks, lr,
                    block_mask,
                    stacked={n for n, a in blocks.items() if a.ndim >= 3},
                    to_dev=to_dev if real else None,
                    to_host=to_host if real else None,
                    transfer_params=real)
            return loss, new_small, new_blocks, new_opt_small, new_opt_blocks

        kwargs = {"donate_argnums": (0, 1, 2, 3)}
        if real:
            hostish = lambda a: host if jnp.ndim(a) else None  # noqa: E731
            kwargs["out_shardings"] = (
                None,                                            # loss
                None,                                            # small
                jax.tree_util.tree_map(lambda a: host, self.blocks),
                None,                                            # opt_small
                jax.tree_util.tree_map(hostish, self.opt_blocks),
            )
        return jax.jit(step_fn, **kwargs)

    def __call__(self, ids):
        if self._jitted is None:
            self._jitted = self._build()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        out = self._jitted(self.small, self.blocks, self.opt_small,
                           self.opt_blocks,
                           jnp.asarray(ids, jnp.int32), lr)
        loss, self.small, self.blocks, self.opt_small, self.opt_blocks = out
        return loss

    def num_params(self):
        leaves = (list(jax.tree_util.tree_leaves(self.small)) +
                  list(jax.tree_util.tree_leaves(self.blocks)))
        return int(sum(np.prod(l.shape) for l in leaves))
