"""GPT — flagship decoder-only LM.

Capability target: the reference's Fleet GPT-3 pretraining stack
(PaddleNLP GPT + fleet hybrid parallel; ref distributed surface:
python/paddle/distributed/fleet/meta_parallel). Design is TPU-first:

  * pre-LN transformer blocks; QKV fused column-parallel matmul, row-parallel
    output/down projections (GSPMD 'mp' specs from fleet.mp_layers);
  * attention via the pallas flash kernel on TPU (blockwise XLA elsewhere);
  * weights created in fp32, compute dtype bf16 via a config switch (MXU path);
  * `gpt_block_fn` exposes the block as a pure (params, x) function so the
    same weights drive eager, jit, and the pipeline/scan hybrid path.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from .. import nn
from ..nn.layer_base import Layer
from ..nn import functional as F
from ..tensor_impl import Tensor
from ..tensor import manipulation as M
from ..dispatch import apply as _apply
from ..distributed.fleet.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)
from ..ops.blockwise_attention import blockwise_attention


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    max_seq_len: int = 2048
    ffn_mult: int = 4
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_flash: bool = True
    compute_dtype: str = "bfloat16"
    remat: bool = True
    # remat policy preset (distributed/recompute.py POLICIES): "full"
    # recomputes the whole block in backward; "dots"/"dots_no_batch" keep
    # MXU outputs resident and recompute only elementwise ops — faster when
    # HBM has headroom
    remat_policy: str = "full"
    # pallas flash attention tile sizes (the MFU autotune surface)
    flash_block_q: int = 256
    flash_block_k: int = 256
    tie_embeddings: bool = False
    # pipeline-parallel schedule: "1f1b" (O(stages) activation residency,
    # ref fleet/meta_parallel/pipeline_parallel.py:230) or "gpipe"
    pp_schedule: str = "1f1b"
    # virtual pipeline stages per device (interleaved 1F1B / VPP,
    # ref fleet/meta_parallel/pipeline_parallel.py:613)
    pp_interleave: int = 1
    # True when stacked block params are stored in vpp_storage_perm order
    # (set by HybridTrainStep after permuting; callers passing logical-order
    # params to gpt_forward must leave it False)
    vpp_stage_major: bool = False
    # True when qkv_w/qkv_b columns are stored head-major ([nh, 3, d] order
    # instead of [3, nh, d]) — set by HybridTrainStep when the sequence-
    # parallel schedule activates, so a contiguous 1/mp column shard is
    # exactly the q/k/v projections of nh/mp heads (the [3, nh, d] layout
    # interleaves head groups across shard boundaries). Pure storage
    # relabeling: compute is bitwise identical, but params/checkpoints and
    # this flag must travel together.
    qkv_head_major: bool = False


# headline model family (GPT-3 sizes; ref benchmark configs)
GPT_CONFIGS = {
    "gpt3-125M": GPTConfig(hidden_size=768, num_layers=12, num_heads=12),
    "gpt3-345M": GPTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt3-760M": GPTConfig(hidden_size=1536, num_layers=24, num_heads=16),
    "gpt3-1.3B": GPTConfig(hidden_size=2048, num_layers=24, num_heads=16),
    "gpt3-2.7B": GPTConfig(hidden_size=2560, num_layers=32, num_heads=32),
    "gpt3-6.7B": GPTConfig(hidden_size=4096, num_layers=32, num_heads=32),
    "gpt3-13B": GPTConfig(hidden_size=5120, num_layers=40, num_heads=40),
}


def _attention(q, k, v, use_flash, causal=True, block_q=256, block_k=256):
    """q,k,v arrays [B,S,H,D] -> [B,S,H,D]. Routed by the same logged
    predicate as nn.functional (flash_supported) so gating can't drift."""
    from ..ops.pallas_kernels.flash_attention import flash_supported
    if use_flash and flash_supported(q.shape, kv_seq=k.shape[1], why="gpt"):
        from ..ops.pallas_kernels.flash_attention import flash_attention_bshd
        return flash_attention_bshd(q, k, v, causal,
                                    block_q=block_q, block_k=block_k)
    return blockwise_attention(q, k, v, causal=causal)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.cfg = config
        h = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)

    def forward(self, x):
        cfg = self.cfg
        B, S, Hd = x.shape
        nh = cfg.num_heads
        d = Hd // nh
        qkv = self.qkv_proj(x)
        use_flash = cfg.use_flash

        def attn(qkv_arr):
            q, k, v = jnp.split(qkv_arr.reshape(B, S, 3, nh, d), 3, axis=2)
            out = _attention(q[:, :, 0], k[:, :, 0], v[:, :, 0], use_flash)
            return out.reshape(B, S, nh * d)

        ctx = _apply(attn, qkv, op_name="flash_attention")
        return self.out_proj(ctx)


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        inner = config.ffn_mult * h
        self.up_proj = ColumnParallelLinear(h, inner, gather_output=False)
        self.down_proj = RowParallelLinear(inner, h, input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.gelu(self.up_proj(x), approximate=True))


class GPTBlock(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        x = x + self.dropout(self.attn(self.ln_1(x)))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        return x


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.wte = VocabParallelEmbedding(config.vocab_size, config.hidden_size,
                                          weight_attr=nn.ParamAttr(initializer=init))
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.drop = nn.Dropout(config.dropout)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids):
        B, S = input_ids.shape
        pos = Tensor(jnp.arange(S, dtype=jnp.int32)[None, :])
        x = self.wte(input_ids) + self.wpe(pos)
        cd = self.config.compute_dtype
        if cd:
            x = x.astype(cd)
        x = self.drop(x)
        from ..distributed.recompute import recompute as _recompute
        for block in self.h:
            if self.config.remat:
                x = _recompute(block, x, policy="dots_no_batch")
            else:
                x = block(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_embeddings:
            self.lm_head = None
        else:
            init = nn.initializer.Normal(0.0, config.initializer_range)
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False,
                weight_attr=nn.ParamAttr(initializer=init))

    def forward(self, input_ids):
        hidden = self.gpt(input_ids)
        hidden = hidden.astype("float32")
        if self.lm_head is None:  # tied embeddings
            return F.linear(hidden, M.transpose(self.gpt.wte.weight, [1, 0]))
        return self.lm_head(hidden)

    def loss(self, logits, labels):
        """Next-token LM loss; logits [B,S,V], labels [B,S]."""
        V = logits.shape[-1]
        lg = M.reshape(logits[:, :-1, :], [-1, V])
        lb = M.reshape(labels[:, 1:], [-1])
        return F.cross_entropy(lg, lb)

    def fused_loss(self, input_ids):
        """Next-token LM loss straight from hidden states — the LM-head
        matmul and softmax-CE are fused so the fp32 [B,S,V] logits buffer
        never exists (ref fused softmax_with_cross_entropy capability,
        python/paddle/nn/functional/loss.py). Routed through dispatch.apply
        so eager ``loss.backward()`` records the op (via its custom_vjp) on
        the tape. Under tensor parallelism (mp>1) the head is vocab-sharded
        and the chunked scan would defeat that sharding, so this falls back
        to the plain sharded-logits path — same guard as gpt_hybrid."""
        from ..ops.fused_ce import fused_lm_loss
        from ..distributed import env as dist_env
        from ..tensor_impl import as_tensor_data
        mesh = dist_env.get_mesh()
        if mesh is not None and mesh.shape.get("mp", 1) > 1:
            return self.loss(self(input_ids), input_ids)
        hidden = self.gpt(input_ids)
        w = self.gpt.wte.weight if self.lm_head is None else self.lm_head.weight
        ids = as_tensor_data(input_ids)
        transpose = self.lm_head is None

        def f(h, w_):
            if transpose:
                w_ = w_.T
            return fused_lm_loss(h, w_.astype(h.dtype), ids)

        return _apply(f, hidden, w, op_name="fused_lm_loss")

    def num_params(self):
        return sum(p.size for p in self.parameters())

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=None, top_p=None, eos_token_id=None,
                 seed=0, num_beams=1, length_penalty=1.0,
                 stop_token_ids=None):
        """Single-XLA-program autoregressive decode with a static KV cache;
        num_beams > 1 switches to beam search (see models/generation.py)."""
        from .generation import generate as _generate
        return _generate(self, input_ids, max_new_tokens, do_sample,
                         temperature, top_k, top_p, eos_token_id, seed,
                         num_beams, length_penalty, stop_token_ids)


def gpt_loss_fn(logits, labels):
    V = logits.shape[-1]
    lg = M.reshape(logits[:, :-1, :], [-1, V])
    lb = M.reshape(labels[:, 1:], [-1])
    return F.cross_entropy(lg, lb)


# ---------------------------------------------------------------------------
# Pure-pytree block function for the pipeline/scan hybrid path: the same math
# as GPTBlock.forward over a {name: array} dict with full logical shapes.
def ln_fp32(x, g, b, eps):
    """fp32 LayerNorm cast back to x.dtype — shared by the block fn and the
    KV-cache decode path (models/generation.py)."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * g.astype(
        x.dtype) + b.astype(x.dtype)


def gpt_block_prelude_fn(config: GPTConfig):
    """The block minus its final down-projection: (p, x) -> (resid, gact)
    where resid is the post-attention residual stream and gact the gelu
    activation — the (r, x) operands of the boundary GEMM the fused pp
    backend runs in-kernel (fused_collectives.fused_gemm_ppsend). The
    full block is prelude + ``resid + (gact @ down_w + down_b)``."""
    nh = config.num_heads
    eps = config.layer_norm_epsilon

    def ln(x, g, b):
        return ln_fp32(x, g, b, eps)

    def prelude(p, x):
        B, S, H = x.shape
        d = H // nh
        h1 = ln(x, p["ln1_g"], p["ln1_b"])
        qkv = h1 @ p["qkv_w"].astype(x.dtype) + p["qkv_b"].astype(x.dtype)
        if getattr(config, "qkv_head_major", False):
            qkv4 = qkv.reshape(B, S, nh, 3, d)
            q, k, v = qkv4[..., 0, :], qkv4[..., 1, :], qkv4[..., 2, :]
        else:
            q3, k3, v3 = jnp.split(qkv.reshape(B, S, 3, nh, d), 3, axis=2)
            q, k, v = q3[:, :, 0], k3[:, :, 0], v3[:, :, 0]
        ctx = _attention(q, k, v, config.use_flash,
                         block_q=getattr(config, "flash_block_q", 256),
                         block_k=getattr(config, "flash_block_k", 256))
        # named residual: remat_policy="save_attn" keeps ctx so the backward
        # pass skips the flash-forward rerun (flash bwd recomputes its own
        # tiles from q/k/v; rerunning fwd for ctx would be pure waste)
        from jax.ad_checkpoint import checkpoint_name
        ctx = checkpoint_name(ctx, "attn_ctx")
        attn_out = ctx.reshape(B, S, H) @ p["out_w"].astype(x.dtype) + \
            p["out_b"].astype(x.dtype)
        x = x + attn_out
        h2 = ln(x, p["ln2_g"], p["ln2_b"])
        up = h2 @ p["up_w"].astype(x.dtype) + p["up_b"].astype(x.dtype)
        up = jax.nn.gelu(up, approximate=True)
        return x, up

    return prelude


def gpt_block_fn(config: GPTConfig):
    prelude = gpt_block_prelude_fn(config)

    def block(p, x):
        x, up = prelude(p, x)
        down = up @ p["down_w"].astype(x.dtype) + p["down_b"].astype(x.dtype)
        return x + down

    return block


def gpt_fused_boundary(config: GPTConfig, meta, rdma):
    """``boundary(last_layer_params, h)`` for ``run_pipeline(boundary=...)``
    (FLAGS_comm_backend='pp=fused'): the stage's LAST block runs with its
    down-projection GEMM fused with the boundary RDMA — the kernel's
    epilogue puts the stage output on the wire to the down-ring neighbor
    directly, returning (stage output, received up-neighbor output)."""
    prelude = gpt_block_prelude_fn(config)
    from ..ops.pallas_kernels import fused_collectives as _fc

    def boundary(p, h):
        B, S, H = h.shape
        resid, gact = prelude(p, h)
        inner = gact.shape[-1]
        y, recv = _fc.fused_gemm_ppsend(
            meta, rdma, (B, S), gact.reshape(B * S, inner),
            p["down_w"].astype(h.dtype), p["down_b"].astype(h.dtype),
            resid.reshape(B * S, H))
        return y.reshape(B, S, H), recv.reshape(B, S, H)

    return boundary


# functional block-param key -> submodule path inside one GPT block. THE
# name table: stack_block_params walks it as attributes, inference's
# _gpt_functional_params as capture_params qualified names ("gpt.h.{i}.{p}")
# — one place to touch when a block parameter is added or renamed.
BLOCK_PARAM_PATHS = {
    "ln1_g": "ln_1.weight", "ln1_b": "ln_1.bias",
    "qkv_w": "attn.qkv_proj.weight", "qkv_b": "attn.qkv_proj.bias",
    "out_w": "attn.out_proj.weight", "out_b": "attn.out_proj.bias",
    "ln2_g": "ln_2.weight", "ln2_b": "ln_2.bias",
    "up_w": "mlp.up_proj.weight", "up_b": "mlp.up_proj.bias",
    "down_w": "mlp.down_proj.weight", "down_b": "mlp.down_proj.bias",
}


def stack_block_params(model: GPTForCausalLM):
    """Collect per-block weights from a GPTForCausalLM into stacked arrays
    [L, ...] for the pipeline path."""
    blocks = list(model.gpt.h)

    def get(b, path):
        for part in path.split("."):
            b = getattr(b, part)
        return b

    return {k: jnp.stack([get(b, p)._data for b in blocks])
            for k, p in BLOCK_PARAM_PATHS.items()}
