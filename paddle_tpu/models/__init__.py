"""paddle_tpu.models — NLP model families (capability parity with the
reference's Fleet/PaddleNLP benchmark stack; see BASELINE.json configs)."""
from .gpt import (  # noqa: F401
    GPTConfig, GPT_CONFIGS, GPTModel, GPTForCausalLM, GPTBlock, gpt_loss_fn,
    gpt_block_fn, stack_block_params,
)
from .bert import (  # noqa: F401
    BertConfig, BERT_CONFIGS, BertModel, BertForPretraining,
    BertForSequenceClassification,
)
from .ernie import (  # noqa: F401
    ErnieConfig, ERNIE_CONFIGS, ErnieModel, ErnieForMaskedLM,
)
