"""ERNIE-style encoder (capability target: ERNIE-3.0 auto_parallel benchmark
config in BASELINE — a BERT-family encoder with task-specific heads; the
knowledge-masking objectives live in data prep, not the architecture)."""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn.layer_base import Layer
from .bert import BertConfig, BertModel


@dataclass
class ErnieConfig(BertConfig):
    vocab_size: int = 40000
    task_type_vocab_size: int = 3
    use_task_id: bool = True


ERNIE_CONFIGS = {
    "ernie-base": ErnieConfig(),
    "ernie-3.0-10B": ErnieConfig(hidden_size=4096, num_hidden_layers=48,
                                 num_attention_heads=64, intermediate_size=16384),
}


class ErnieModel(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.config = cfg
        self.encoder_model = BertModel(cfg)
        if cfg.use_task_id:
            self.task_embedding = nn.Embedding(cfg.task_type_vocab_size,
                                               cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, task_ids=None,
                attention_mask=None):
        # task-type embedding folds into the shared embedding sum
        if task_ids is not None and self.config.use_task_id:
            emb_layer = self.encoder_model.embeddings
            base = emb_layer(input_ids, token_type_ids)
            base = base + self.task_embedding(task_ids)
            seq = self.encoder_model.encoder(base, attention_mask)
            import paddle_tpu.nn.functional as F
            pooled = F.tanh(self.encoder_model.pooler(seq[:, 0]))
            return seq, pooled
        return self.encoder_model(input_ids, token_type_ids, attention_mask)


class ErnieForMaskedLM(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.head = nn.Linear(cfg.hidden_size, cfg.vocab_size)

    def forward(self, input_ids, token_type_ids=None, task_ids=None,
                attention_mask=None):
        seq, _ = self.ernie(input_ids, token_type_ids, task_ids, attention_mask)
        return self.head(seq)
