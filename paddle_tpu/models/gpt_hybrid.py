"""GPT hybrid-parallel training step — the flagship performance path.

Capability target: Fleet GPT-3 hybrid-parallel pretraining (TP×PP×DP×sharding,
ref: python/paddle/distributed/fleet/meta_parallel + meta_optimizers). One pure
XLA program per step:

    (params, opt_state, ids, key) -> (loss, new_params, new_opt_state)

Layer stack is STACKED ([L, ...] leaves) and driven by `lax.scan` (single-block
trace => fast compiles, weight-stationary loop) with `jax.checkpoint` remat per
block. Parallelism:
  * dp/sharding — batch sharded P('dp','sharding'? no: batch over 'dp'); ZeRO
    via optimizer-slot sharding over 'sharding';
  * mp (tensor) — qkv/up weights P(..., 'mp'), out/down P('mp', ...), vocab
    embedding and lm head vocab-sharded; XLA inserts the Megatron collectives;
  * pp — stacked blocks sharded P('pp') on the layer axis, executed by the
    scan+ppermute GPipe schedule (distributed/pipeline.py);
  * sp — optional ring attention over the sequence axis.
All params fp32 (or bf16) with fp32 adam moments; compute in bf16 on the MXU.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .gpt import GPTConfig, gpt_block_fn
from ..distributed.pipeline import run_pipeline


def init_gpt_params(config: GPTConfig, key, param_dtype=jnp.float32):
    H = config.hidden_size
    L = config.num_layers
    V = config.vocab_size
    I = config.ffn_mult * H
    k = iter(jax.random.split(key, 20))
    std = config.initializer_range

    def norm(key_, shape):
        return (jax.random.normal(key_, shape, jnp.float32) * std).astype(param_dtype)

    blocks = {
        "ln1_g": jnp.ones((L, H), param_dtype),
        "ln1_b": jnp.zeros((L, H), param_dtype),
        "qkv_w": norm(next(k), (L, H, 3 * H)),
        "qkv_b": jnp.zeros((L, 3 * H), param_dtype),
        "out_w": norm(next(k), (L, H, H)),
        "out_b": jnp.zeros((L, H), param_dtype),
        "ln2_g": jnp.ones((L, H), param_dtype),
        "ln2_b": jnp.zeros((L, H), param_dtype),
        "up_w": norm(next(k), (L, H, I)),
        "up_b": jnp.zeros((L, I), param_dtype),
        "down_w": norm(next(k), (L, I, H)),
        "down_b": jnp.zeros((L, H), param_dtype),
    }
    return {
        "wte": norm(next(k), (V, H)),
        "wpe": norm(next(k), (config.max_seq_len, H)),
        "lnf_g": jnp.ones((H,), param_dtype),
        "lnf_b": jnp.zeros((H,), param_dtype),
        "head_w": norm(next(k), (H, V)),
        "blocks": blocks,
    }


def gpt_params_fingerprint(params):
    """Device-independent uint32 digest of a GPT param tree (the same
    bit-exact fingerprint the sdc sentinel fuses into check steps — see
    distributed/integrity.py). Two trees agree iff their raw bits agree:
    the serving shadow audit's ``audit_ref`` copy, a peer-repaired
    training replica, and a checkpoint round-trip can all be compared
    with one host int instead of a leaf-by-leaf array diff."""
    from ..distributed.integrity import fingerprint_arrays
    return int(jax.device_get(fingerprint_arrays(params)))


def gpt_param_specs(config: GPTConfig, pp=1, zero_stage=1):
    """PartitionSpecs per param. Block leaves get a leading 'pp' axis when
    pipelining; matmul weights shard over 'mp' Megatron-style.

    zero_stage >= 3 (ref: fleet/meta_parallel/sharding/
    group_sharded_stage3.py capability): block matrices additionally shard
    their non-'mp' dim over ('dp','sharding') — FSDP-style. Inside the
    layer scan GSPMD inserts the per-layer all-gather on use (the
    reference's stage-3 prefetch) and turns the weight-grad psum into a
    reduce-scatter; persistent per-chip param bytes drop by dpxsharding."""
    lead = ("pp",) if pp > 1 else (None,)
    z3 = ("dp", "sharding") if zero_stage >= 3 else None
    blocks = {
        "ln1_g": P(*lead, None), "ln1_b": P(*lead, None),
        "qkv_w": P(*lead, z3, "mp"), "qkv_b": P(*lead, "mp"),
        "out_w": P(*lead, "mp", z3), "out_b": P(*lead, None),
        "ln2_g": P(*lead, None), "ln2_b": P(*lead, None),
        "up_w": P(*lead, z3, "mp"), "up_b": P(*lead, "mp"),
        "down_w": P(*lead, "mp", z3), "down_b": P(*lead, None),
    }
    return {
        # wte is NOT hidden-FSDP-sharded at stage 3: a z3 spec turns the
        # embedding lookup into a gather whose output GSPMD can only reshard
        # to the batch-sharded activation layout via full rematerialization
        # (an all-gather of [B,S,H] every step). Vocab-over-mp only: with
        # batch-sharded ids the gather output is born in the right sharding.
        "wte": P("mp", None),
        "wpe": P(),
        "lnf_g": P(), "lnf_b": P(),
        "head_w": P(z3, "mp"),
        "blocks": blocks,
    }


def _lm_loss(logits, ids):
    """Shifted next-token CE in fp32. logits [B,S,V], ids [B,S].

    Kept for the mp>1 path (vocab-sharded logits: per-chip memory is already
    V/mp) and as the numeric reference for the fused loss below."""
    lg = logits[:, :-1].astype(jnp.float32)
    lb = ids[:, 1:]
    logz = jax.scipy.special.logsumexp(lg, axis=-1)
    gold = jnp.take_along_axis(lg, lb[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def gpt_hidden(params, ids, config: GPTConfig, mesh=None, num_microbatches=1):
    """Pure forward to final-layernorm hidden states [B,S,H] (compute dtype).
    Under a mesh with pp>1 uses the pipeline. With FLAGS_sequence_parallel
    and mp>1 the layer scan runs the explicit shard_map schedule
    (distributed/tp_overlap.py): activations between blocks are seq-sharded
    at 1/mp size and each block's two all-reduces become RS+AG (ring-
    decomposed ppermute hops under FLAGS_mp_overlap)."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    B, S = ids.shape
    x = params["wte"].astype(compute)[ids] + \
        params["wpe"].astype(compute)[None, :S]
    from ..distributed import tp_overlap as _tp
    from ..distributed import comm_backend as _cb
    sp = _tp.resolve_gpt(config, mesh, batch=B, seq=S) \
        if mesh is not None else None
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    ppc = _cb.resolve_pp(config, mesh, batch=B,
                         num_microbatches=num_microbatches, sp=sp) \
        if pp > 1 else None
    if pp > 1 and ppc is None and sp is not None:
        # resolve_gpt admitted the pp axis on the explicit schedule's
        # behalf, but resolve_pp fell back — the GSPMD pipeline cannot run
        # the per-shard sp block, so both axes run GSPMD this step
        _cb._warn_once("pp-sp-gspmd",
                       "the explicit mp schedule composes with pp>1 only "
                       "through the explicit pp schedule, which just fell "
                       "back (see the pp warning above) — running GSPMD on "
                       "both axes")
        sp = None
    x_spec = None
    if mesh is not None:
        # seq-parallel entry: the vocab-sharded embedding's psum lands
        # directly in the seq-sharded layout (a reduce-scatter, GSPMD-emitted
        # from this constraint) instead of replicating [B,S,H]. Meshes
        # without a dp axis (the single-axis mp meshes interpret-mode fused
        # kernels need) replicate the batch dim.
        batch_axis = "dp" if "dp" in mesh.axis_names else None
        x_spec = _tp.sp_activation_spec(sp.batch_axis) if sp is not None \
            else P(batch_axis, None, None)
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, x_spec))
    block = gpt_block_fn(config)
    from ..distributed.recompute import POLICIES
    pol_name = getattr(config, "remat_policy", "full") or "full"
    if pol_name not in POLICIES:
        raise ValueError(f"unknown remat_policy {pol_name!r}; "
                         f"choose from {sorted(POLICIES)}")
    if pp > 1:
        if (ppc is None and jax.default_backend() == "cpu"
                and jnp.dtype(compute) == jnp.dtype(jnp.bfloat16)):
            # XLA's CPU backend hard-aborts ("Invalid binary instruction
            # opcode copy", hlo_instruction.cc:1585) PARTITIONING the
            # bf16 ppermute pipeline — fail with a catchable error instead
            # of killing the interpreter. TPU (the real target) is fine,
            # and so is the explicit full-manual schedule (nothing left
            # for the partitioner to partition): bf16 pipelines run on CPU
            # under FLAGS_comm_backend='pp=ring' (or 'pp=fused').
            raise ValueError(
                "pipeline parallelism with compute_dtype='bfloat16' "
                "crashes the XLA CPU backend under the GSPMD pp schedule; "
                "set FLAGS_comm_backend='pp=ring' (the explicit schedule "
                "wires bf16 fine) or compute_dtype='float32' for CPU runs")
        schedule = ppc.schedule if ppc is not None \
            else getattr(config, "pp_schedule", "1f1b")
        pol = POLICIES[pol_name]
        if pol is not None and schedule != "1f1b":
            import warnings
            warnings.warn(
                f"remat_policy={pol_name!r} needs the 1f1b schedule; the "
                "gpipe autodiff path derives recompute from the scan — "
                "falling back to full recompute")
            pol = None
        # 1f1b/VPP: the selective-save policy applies to the per-tick stage
        # vjp (stage-input checkpointing stays; the policy decides which
        # per-layer residuals the tick keeps — e.g. 'dots' pins MXU
        # outputs). The GPipe autodiff path keeps scan-derived recompute.
        # Under VPP the hybrid step stores blocks in vpp_storage_perm order
        # (see HybridTrainStep.__post_init__), so reshaping to chunks is
        # contiguous and needs no cross-device reshard.
        pk = {}
        if ppc is not None:
            # explicit (full-manual) schedule: hand run_pipeline the real
            # stacked-leaf specs and the activation spec so every input is
            # sharded INTO the region — no tensor is replicated-then-
            # repartitioned, so the partitioner never sees the stage
            # selects (the involuntary-remat warnings die structurally)
            blocks_specs = gpt_param_specs(config, pp=pp)["blocks"]
            blocks_specs = {
                k: P(*(a if (a is None or a in mesh.axis_names) else None
                       for a in tuple(s)))
                for k, s in blocks_specs.items()}
            boundary = None
            if ppc.backend == "fused":
                from .gpt import gpt_fused_boundary
                boundary = gpt_fused_boundary(config, ppc.kernel_meta(mesh),
                                              ppc.fused_rdma)
            if sp is not None:
                # per-shard sp block runs UNWRAPPED inside the pipeline's
                # full-manual region (make_sp_block's own shard_map would
                # nest); the pipeline in_specs deliver the mp-sharded
                # weights and seq-sharded activations it expects
                block = _tp.sp_block_fn(config, sp.n, axis=sp.axis,
                                        backend=sp.backend,
                                        meta=sp.kernel_meta(mesh))
            pk = dict(backend=ppc.backend, pp_param_specs=blocks_specs,
                      x_spec=x_spec, wire_dtype=ppc.wire_dtype,
                      boundary=boundary)
        x = run_pipeline(block, params["blocks"], x, num_microbatches, mesh=mesh,
                         schedule=schedule,
                         interleave=getattr(config, "pp_interleave", 1),
                         vpp_stage_major=getattr(config, "vpp_stage_major",
                                                 False),
                         remat_policy=pol, **pk)
    else:
        if sp is not None:
            block = _tp.make_sp_block(config, mesh, sp)
        ck_block = jax.checkpoint(block, policy=POLICIES[pol_name])

        def scan_body(h, layer_params):
            return ck_block(layer_params, h), None
        x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    # final layernorm is elementwise over H — it runs on the seq shard when
    # sequence parallelism is active (the head matmul's all-gather is the
    # first point the full sequence rematerializes)
    return final_ln_fp32(x, params["lnf_g"], params["lnf_b"],
                         config.layer_norm_epsilon).astype(compute)


def final_ln_fp32(x, g, b, eps):
    """Final layernorm in fp32 (shared by the hybrid and stage-3 steps);
    returns fp32 — callers cast back to their compute dtype."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    xn = (xf - mu) * jax.lax.rsqrt(var + eps)
    return xn * g.astype(jnp.float32) + b.astype(jnp.float32)


def gpt_forward(params, ids, config: GPTConfig, mesh=None, num_microbatches=1):
    """Pure forward to logits (inference / mp-sharded loss path)."""
    compute = jnp.dtype(config.compute_dtype or "float32")
    xn = gpt_hidden(params, ids, config, mesh, num_microbatches)
    return xn @ params["head_w"].astype(compute)


@dataclass
class HybridTrainStep:
    """Compiled hybrid-parallel GPT train step."""
    config: GPTConfig
    optimizer: object            # paddle_tpu Optimizer (functional API)
    mesh: object = None
    num_microbatches: int = 1
    param_dtype: object = jnp.float32
    seed: int = 0
    # ZeRO stage on the flagship path: 1 = optimizer slots sharded (via
    # optimizer._shard_opt_states_axis), 3 = + params FSDP-sharded over
    # ('dp','sharding') with per-layer all-gather in the scan
    zero_stage: int = 1
    # host offload of optimizer moments (ref: fleet group_sharded_stage3.py:84
    # cpu offload): slots live in pinned host memory between steps; on TPU the
    # compiled step streams them to HBM for the update and back. Moves the
    # 8-bytes/param fp32 adam moments off the 16G chip — the single-chip
    # enabler for 2.7B-class configs.
    offload: bool = False

    def __post_init__(self):
        key = jax.random.key(self.seed)
        self.params = init_gpt_params(self.config, key, self.param_dtype)
        pp = self.mesh.shape.get("pp", 1) if self.mesh is not None else 1
        V = getattr(self.config, "pp_interleave", 1)
        if pp > 1 and V > 1:
            # stage-major storage so VPP chunk placement == 'pp' sharding;
            # the config flag records the layout for gpt_hidden/run_pipeline
            from ..distributed.pipeline import vpp_storage_perm
            perm = jnp.asarray(
                vpp_storage_perm(self.config.num_layers, pp, V))
            self.params["blocks"] = jax.tree_util.tree_map(
                lambda a: a[perm], self.params["blocks"])
            self.config.vpp_stage_major = True
        mp = self.mesh.shape.get("mp", 1) if self.mesh is not None else 1
        from ..distributed import tp_overlap as _tp
        from ..distributed import comm_backend as _cb
        if self.zero_stage >= 3 and not getattr(self.config, "zero3_params",
                                                False):
            # record FSDP-sharded params on a private config copy so
            # trace-time resolvers (comm_backend.resolve_pp) can see it —
            # the explicit pp schedule cannot emit the per-layer stage-3
            # all-gather and must bail on such steps
            import copy
            self.config = copy.copy(self.config)
            self.config.zero3_params = True
        if (_tp.explicit_mp_requested() and mp > 1
                and (pp == 1 or _cb.pp_explicit_requested())
                and self.config.hidden_size % mp == 0
                and self.config.num_heads % mp == 0):
            # head-major qkv storage so a contiguous 1/mp column shard is
            # whole heads (see tp_overlap.qkv_head_major_perm); the config
            # flag records the layout and makes every block-fn consumer
            # interpret it consistently — even if resolve_gpt later falls
            # back to GSPMD at trace time. The layout flag must travel with
            # THIS instance's permuted params only, and callers often hand
            # in a shared config (GPT_CONFIGS) — mutate a private copy.
            import copy
            self.config = copy.copy(self.config)
            self.params["blocks"] = _tp.to_qkv_head_major(
                self.params["blocks"], self.config.hidden_size,
                self.config.num_heads)
            self.config.qkv_head_major = True
        flat, self._treedef = jax.tree_util.tree_flatten_with_path(self.params)
        self._names = ["/".join(str(p) for p in path) for path, _ in flat]
        self.opt_state = self.optimizer.init_state(self._flat(self.params))
        if getattr(self.optimizer, "_offload_opt_states", False):
            self.offload = True
        from ..framework import offload as _ol
        self._offload_in_jit = _ol.in_jit_transfers_supported()
        if self.mesh is not None:
            self._place()
        if self.offload:
            self.opt_state = self._move_opt(self.opt_state,
                                            self._opt_host_shardings())
        self._jitted = None
        self._step_count = 0
        # live step telemetry (FLAGS_step_telemetry): flops/tokens derive
        # from the config and batch shape at call time — live MFU uses the
        # SAME estimator as bench.py (observability/flops.py)
        from ..observability.step_telemetry import StepSampler
        self._tel = StepSampler("HybridTrainStep")

    # -- host offload helpers (mirror jit/train_step.py) ---------------------
    def _opt_dev_shardings(self):
        if self.mesh is not None:
            mesh = self.mesh
            # recompute the same placement _place() used
            flat_specs = self._flat(self._specs())
            zero_axis = getattr(self.optimizer, "_shard_opt_states_axis", None)

            def spec_of(name, arr):
                if jnp.ndim(arr) == 0:
                    return NamedSharding(mesh, P())
                base = flat_specs[name]
                replicated = all(a is None for a in tuple(base)) \
                    if len(tuple(base)) else True
                if (zero_axis and mesh.shape.get(zero_axis, 1) > 1
                        and replicated
                        and arr.shape[0] % mesh.shape[zero_axis] == 0):
                    return NamedSharding(
                        mesh, P(zero_axis, *([None] * (arr.ndim - 1))))
                return NamedSharding(mesh, base)
            return {"step": NamedSharding(mesh, P()),
                    "slots": {n: {k: spec_of(n, v) for k, v in s.items()}
                              for n, s in self.opt_state["slots"].items()}}
        from ..framework import offload as _ol
        dev = _ol.with_memory_kind(None, "device")
        return jax.tree_util.tree_map(lambda a: dev, self.opt_state)

    def _opt_host_shardings(self):
        from ..framework import offload as _ol
        return _ol.host_shardings(self.opt_state, self._opt_dev_shardings())

    @staticmethod
    def _move_opt(opt_state, shardings):
        from ..framework import offload as _ol
        return _ol.move_opt(opt_state, shardings)

    def _flat(self, tree):
        leaves = jax.tree_util.tree_leaves(tree)
        return dict(zip(self._names, leaves))

    def _unflat(self, d):
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self.params), [d[n] for n in self._names])

    def _specs(self):
        pp = self.mesh.shape.get("pp", 1) if self.mesh is not None else 1
        return gpt_param_specs(self.config, pp=pp, zero_stage=self.zero_stage)

    def _place(self):
        specs = self._specs()
        mesh = self.mesh
        self.params = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
            self.params, specs)
        # ZeRO: sharded slots follow params; scalars replicated — the single
        # source of slot placement is _opt_dev_shardings (shared with the
        # host-offload fetch/stash path)
        self.opt_state = self._move_opt(self.opt_state,
                                        self._opt_dev_shardings())

    def _build(self):
        config, mesh, M = self.config, self.mesh, self.num_microbatches
        optimizer = self.optimizer
        unflat = self._unflat
        flat = self._flat

        mp = mesh.shape.get("mp", 1) if mesh is not None else 1
        from ..framework import offload as _ol
        offload_in = self.offload and self._offload_in_jit
        # single-chip offload streams stacked block slots one layer at a
        # time (bulk fetch would put the whole moment set back in HBM —
        # the 2.7B OOM); on a multi-device mesh the slots are ZeRO- or
        # pp-sharded over the leading dim, which conflicts with layer
        # slicing, so bulk fetch/stash remains that path. A trivial
        # all-ones mesh shards nothing and streams like mesh=None.
        stream = offload_in and (
            mesh is None or all(s == 1 for s in mesh.shape.values()))
        fetch_opt, stash_opt = _ol.fetch_stash(
            offload_in and not stream,
            self._opt_dev_shardings() if offload_in else None,
            self._opt_host_shardings() if offload_in else None)
        # stream only the 3D matrix leaves: a [1, H, X] slice DMAs whole
        # sublane tiles, while [1, H] slices of the 2D bias/norm leaves trip
        # the TPU dynamic-index emitter's sublane-multiple check (observed
        # compiler crash) — and their moments are only ~5MB total anyway
        stacked = {n for n, a in self._flat(self.params).items()
                   if "blocks" in n and a.ndim >= 3}

        def step_fn(flat_params, opt_state, ids, lr):
            def loss_fn(fp):
                p = unflat(fp)
                if mp == 1:
                    # fused head+CE: never materializes fp32 [B,S,V] logits
                    from ..ops.fused_ce import fused_lm_loss
                    hidden = gpt_hidden(p, ids, config, mesh, M)
                    return fused_lm_loss(
                        hidden, p["head_w"].astype(hidden.dtype), ids)
                # mp>1: logits are vocab-sharded (V/mp per chip) — the plain
                # logsumexp stays within budget and XLA keeps it sharded
                logits = gpt_forward(p, ids, config, mesh, M)
                return _lm_loss(logits, ids)
            loss, grads = jax.value_and_grad(loss_fn)(flat_params)
            clip = getattr(optimizer, "_grad_clip", None)
            if clip is not None:
                names = list(grads)
                clipped = clip.apply_arrays([grads[n] for n in names])
                grads = dict(zip(names, clipped))
            # flat names are bracketed tree paths (e.g. "['blocks']/['up_b']")
            # — match on the unwrapped leaf name, not the raw string
            def _leaf(n):
                return n.rsplit("/", 1)[-1].strip("[]'\"")
            wd_mask = {n: not (_leaf(n).endswith("_b") or "ln" in _leaf(n)
                               or _leaf(n) == "wpe")
                       for n in flat_params}
            if stream:
                new_params, new_opt = _ol.streamed_apply_gradients(
                    optimizer, flat_params, grads, opt_state, lr, wd_mask,
                    stacked,
                    to_dev=lambda a: jax.device_put(
                        a, _ol.with_memory_kind(None, "device")),
                    to_host=lambda a: jax.device_put(
                        a, _ol.with_memory_kind(None, "pinned_host")))
                return loss, new_params, new_opt
            new_params, new_opt = optimizer.apply_gradients(
                flat_params, grads, fetch_opt(opt_state), lr, wd_mask=wd_mask)
            return loss, new_params, stash_opt(new_opt)

        jit_kwargs = dict(donate_argnums=(0, 1))
        if mesh is not None:
            batch_axis = "dp" if "dp" in mesh.axis_names else None
            data_sh = NamedSharding(mesh, P(batch_axis, None))
            rep = NamedSharding(mesh, P())
            jit_kwargs["in_shardings"] = (None, None, data_sh, rep)
        return jax.jit(step_fn, **jit_kwargs)

    def __call__(self, ids):
        ids = jnp.asarray(ids)
        if self._jitted is None:
            self._jitted = self._build()
        # static mp-axis comm ledger of the compiled schedule
        # (profiler.mp_comm_counters evidence), keyed per batch shape —
        # jax.jit retraces per shape and gpt_hidden re-resolves the
        # schedule at trace time, so the ledger must follow suit
        recs = getattr(self, "_mp_records", None)
        if recs is None:
            recs = self._mp_records = {}
        shape_key = tuple(ids.shape)
        if shape_key not in recs:
            from ..distributed import tp_overlap as _tp
            from ..distributed import comm_backend as _cb
            from ..distributed import pipeline as _pl
            B, S = ids.shape
            sp = _tp.resolve_gpt(self.config, self.mesh, batch=B, seq=S) \
                if self.mesh is not None else None
            pp = self.mesh.shape.get("pp", 1) if self.mesh is not None else 1
            ppc = _cb.resolve_pp(self.config, self.mesh, batch=B,
                                 num_microbatches=self.num_microbatches,
                                 sp=sp) if pp > 1 else None
            if pp > 1 and ppc is None:
                sp = None  # mirrors gpt_hidden's trace-time fallback
            sp_rec = _tp.gpt_step_record(self.config, sp, B, S) \
                if sp is not None else None
            pp_rec = _pl.gpt_pp_step_record(
                self.config, ppc, B, S, self.num_microbatches, S=pp,
                mp=sp.n if sp is not None else 1) if pp > 1 else None
            recs[shape_key] = (sp_rec, pp_rec)
        sp_rec, pp_rec = recs[shape_key]
        if sp_rec is not None:
            from ..distributed import tp_overlap as _tp
            _tp.record_step(sp_rec)
        if pp_rec is not None:
            from ..distributed import pipeline as _pl
            _pl.record_pp_step(pp_rec)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        flat_params = self._flat(self.params)
        offload_out = self.offload and not self._offload_in_jit
        if offload_out:  # backend without in-jit memory transfers (CPU)
            self.opt_state = self._move_opt(self.opt_state,
                                            self._opt_dev_shardings())
        t_tel = self._tel.begin(self._step_count)
        loss, flat_params, self.opt_state = self._jitted(
            flat_params, self.opt_state, ids, lr)
        if t_tel is not None:
            from ..observability.flops import train_step_flops
            B, S = ids.shape
            flops, _ = train_step_flops(self.config, B, S)
            wire = None
            if sp_rec is not None:
                wire = int(sp_rec.rs_bytes + sp_rec.ag_bytes)
            if pp_rec is not None and pp_rec.boundary_bytes:
                wire = (wire or 0) + int(pp_rec.boundary_bytes)
            self._tel.end(t_tel, self._step_count, loss, tokens=B * S,
                          flops=flops, wire_bytes=wire)
        if offload_out:
            self.opt_state = self._move_opt(self.opt_state,
                                            self._opt_host_shardings())
        self.params = self._unflat(flat_params)
        self._step_count += 1
        return loss

    def loss_only(self, ids):
        """Forward-only loss on the CURRENT params (no grads, no update) —
        the bench's step-time-breakdown probe. Shares the live param
        buffers; only activation workspace is added."""
        if not hasattr(self, "_fwd_jitted"):
            config, mesh, M = self.config, self.mesh, self.num_microbatches
            unflat = self._unflat
            mp = mesh.shape.get("mp", 1) if mesh is not None else 1

            def fwd(fp, ids):
                p = unflat(fp)
                if mp == 1:
                    from ..ops.fused_ce import fused_lm_loss
                    hidden = gpt_hidden(p, ids, config, mesh, M)
                    return fused_lm_loss(
                        hidden, p["head_w"].astype(hidden.dtype), ids)
                return _lm_loss(gpt_forward(p, ids, config, mesh, M), ids)

            self._fwd_jitted = jax.jit(fwd)
        return self._fwd_jitted(self._flat(self.params), jnp.asarray(ids))

    def num_params(self):
        return int(sum(np.prod(l.shape) for l in
                       jax.tree_util.tree_leaves(self.params)))
