"""paddle.incubate.distributed parity surface (ref:
python/paddle/incubate/distributed/)."""
from . import models  # noqa: F401
