"""Expert-parallel Mixture-of-Experts (ref:
python/paddle/incubate/distributed/models/moe/moe_layer.py + gate/*).

TPU-first redesign. The reference routes tokens with dynamic-shape
scatter/gather plus NCCL global_scatter/global_gather; XLA needs static
shapes, so routing uses the GShard dense-dispatch formulation instead:

  * gate -> top-k expert choice with a STATIC per-expert capacity C;
  * dispatch/combine tensors [T, E, C] built with one-hots + cumsum;
  * token exchange via ONE `lax.all_to_all` over the 'ep' mesh axis each
    way (split experts / concat capacity) — the collective rides ICI;
  * expert FFNs run batched as [E_local, ep*C, D] einsums on the MXU.

Capacity overflow drops tokens (their combine weight is 0 and the residual
path carries them), matching GShard semantics rather than the reference's
unbounded dynamic buffers — that is the TPU-correct trade.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..... import nn
from .....nn.layer_base import Layer
from .....tensor_impl import as_tensor_data, wrap
from .....dispatch import apply as _apply


# ---------------------------------------------------------------------------
# gates (ref gate/{base,naive,switch,gshard}_gate.py)
class BaseGate(Layer):
    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def forward(self, x):  # pragma: no cover - abstract
        raise NotImplementedError


class NaiveGate(BaseGate):
    """Linear gate + top-k (ref gate/naive_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp, return_all_scores=False):
        from .....tensor import search as S
        gate = self.gate(inp)
        val, idx = S.topk(gate, k=self.top_k, axis=-1)
        if return_all_scores:
            return val, idx, gate
        return val, idx


class SwitchGate(NaiveGate):
    """top-1 switch routing with logit jitter in training
    (ref gate/switch_gate.py: switch_eps multiplicative noise,
    capacity=(train, eval) factors)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4)):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity

    def capacity_factor(self):
        return self.capacity[0] if self.training else self.capacity[1]


class GShardGate(NaiveGate):
    """top-2 with capacity + random second-expert routing + aux
    load-balance loss (ref gate/gshard_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True):
        super().__init__(d_model, num_expert, world_size, topk=2)
        self.capacity = capacity
        self.random_routing = random_routing

    def capacity_factor(self):
        return self.capacity[0] if self.training else self.capacity[1]


# ---------------------------------------------------------------------------
# dense dispatch construction (pure jax; static shapes)
def make_dispatch_and_combine(gates, top_k, capacity, normalize=True,
                              random_routing_key=None):
    """gates [T, E] (softmax probs) -> dispatch [T,E,C] (0/1),
    combine [T,E,C] (gate-weighted), aux load-balance loss (GShard eq.).

    With `random_routing_key`, non-first choices are kept with probability
    min(1, top_k * gate_prob) — GShard's random routing of the 2nd expert."""
    T, E = gates.shape
    C = capacity
    f32 = jnp.float32
    remaining = gates
    loc_base = jnp.zeros((E,), jnp.int32)
    chosen = []  # (onehot [T,E] int, pos [T], keep [T], gateval [T])
    for i in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)
        gval = jnp.take_along_axis(gates, idx[:, None], 1)[:, 0]
        if i > 0 and random_routing_key is not None:
            u = jax.random.uniform(
                jax.random.fold_in(random_routing_key, i), (T,), f32)
            onehot = onehot * (u < top_k * gval).astype(jnp.int32)[:, None]
        pos = jnp.sum((jnp.cumsum(onehot, axis=0) - onehot +
                       loc_base[None]) * onehot, axis=1)
        keep = (pos < C) & (onehot.sum(-1) > 0)
        chosen.append((onehot, pos, keep, gval))
        loc_base = loc_base + jnp.sum(onehot * keep[:, None].astype(jnp.int32),
                                      axis=0)
        remaining = remaining * (1 - onehot.astype(gates.dtype))

    denom = sum(jnp.where(k, g, 0.0) for _, _, k, g in chosen) if normalize \
        else 1.0
    denom = jnp.maximum(denom, 1e-9) if normalize else 1.0
    dispatch = jnp.zeros((T, E, C), bool)
    combine = jnp.zeros((T, E, C), f32)
    for onehot, pos, keep, gval in chosen:
        oh_pos = jax.nn.one_hot(jnp.where(keep, pos, 0), C, dtype=f32)
        d = (onehot.astype(f32) * keep[:, None].astype(f32))[..., None] * \
            oh_pos[:, None, :]
        dispatch = dispatch | d.astype(bool)
        w = gval / denom if normalize else gval
        combine = combine + d * w[:, None, None]

    # aux loss (GShard): E * mean_e(fraction_of_tokens_e * mean_gate_e),
    # computed on the FIRST choice like the paper
    first = chosen[0][0].astype(f32)
    aux = E * jnp.mean(jnp.mean(first, axis=0) * jnp.mean(gates, axis=0)) \
        * top_k
    return dispatch, combine, aux


def expert_parallel_moe(x, gate_w, gate_b, w1, b1, w2, b2, *, mesh=None,
                        axis="ep", top_k=2, capacity_factor=1.25,
                        act="gelu", normalize=True, switch_jitter=0.0,
                        routing_key=None, random_routing=False):
    """Functional EP-MoE FFN. x [T, D] (token-sharded over `axis` under the
    mesh); expert weights w1 [E, D, H], w2 [E, H, D] (expert-sharded over
    `axis`). Returns (y [T, D], aux_loss scalar).

    switch_jitter: multiplicative logit noise in [1-eps, 1+eps] (SwitchGate
    training); random_routing: keep non-first experts with prob
    min(1, k*gate) (GShardGate). Both need `routing_key`."""
    act_fn = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[act]
    E = w1.shape[0]
    ep = mesh.shape.get(axis, 1) if mesh is not None else 1
    if ep > 1:
        assert E % ep == 0, (
            f"num_experts {E} must divide by ep degree {ep} for all_to_all")
        assert x.shape[0] % ep == 0, (
            f"token count {x.shape[0]} must divide by ep degree {ep}")
    T_local = x.shape[0] // max(ep, 1)
    C = max(1, math.ceil(top_k * T_local * capacity_factor / E))

    def local_fn(xs, gw, gb, w1s, b1s, w2s, b2s):
        xs = xs.reshape(xs.shape[-2:]) if xs.ndim == 3 else xs
        logits = (xs @ gw + gb).astype(jnp.float32)
        if switch_jitter and routing_key is not None:
            noise = jax.random.uniform(
                jax.random.fold_in(routing_key, 17), logits.shape,
                jnp.float32, 1.0 - switch_jitter, 1.0 + switch_jitter)
            logits = logits * noise
        gates = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, aux = make_dispatch_and_combine(
            gates, top_k, C, normalize,
            random_routing_key=(routing_key if random_routing else None))
        sent = jnp.einsum("tec,td->ecd", dispatch.astype(xs.dtype), xs)
        if ep > 1:
            # [E, C, D] -> peers get their experts -> [E/ep, ep*C, D]
            recv = lax.all_to_all(sent, axis, split_axis=0, concat_axis=1,
                                  tiled=True)
            aux = lax.pmean(aux, axis)
        else:
            recv = sent
        h = act_fn(jnp.einsum("ecd,edh->ech", recv, w1s) + b1s[:, None])
        out = jnp.einsum("ech,ehd->ecd", h, w2s) + b2s[:, None]
        if ep > 1:
            back = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                                  tiled=True)
        else:
            back = out
        y = jnp.einsum("tec,ecd->td", combine.astype(xs.dtype),
                       back.astype(xs.dtype))
        return y, aux

    if mesh is None or ep == 1:
        return local_fn(x, gate_w, gate_b, w1, b1, w2, b2)

    tok = P(axis, None)
    exp = P(axis, *([None] * (w1.ndim - 1)))
    from .....distributed import env as _dist_env
    mapped = _dist_env.shard_map_compat(
        local_fn, mesh=mesh,
        in_specs=(tok, P(), P(), exp, P(axis, None), exp, P(axis, None)),
        out_specs=(tok, P()),
        axis_names=frozenset({axis}))
    return mapped(x, gate_w, gate_b, w1, b1, w2, b2)


class MoELayer(Layer):
    """Expert-parallel MoE FFN layer (ref moe_layer.py MoELayer API shape;
    experts stored STACKED [E, ...] for batched MXU einsums instead of the
    reference's per-expert Layer list).

    `gate` may be a string ("gshard" | "switch" | "naive") or a gate
    instance (GShardGate/SwitchGate/NaiveGate); with an instance, its
    linear drives routing, its top_k/capacity/noise settings apply, and
    its `.loss` is set to the aux load-balance term after each forward
    (also mirrored on `self.l_aux`)."""

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, gate="gshard", act="gelu",
                 mesh=None, ep_axis="ep", seed=0):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.act = act
        self.mesh = mesh
        self.ep_axis = ep_axis
        self._gate_owns_capacity = isinstance(gate, BaseGate)
        if isinstance(gate, BaseGate):
            self.gate = gate
        elif gate == "switch":
            self.gate = SwitchGate(d_model, num_experts)
        elif gate == "naive":
            self.gate = NaiveGate(d_model, num_experts, topk=top_k)
        else:
            self.gate = GShardGate(d_model, num_experts, topk=top_k)
        self._default_capacity_factor = capacity_factor
        init = nn.initializer.Normal(0.0, (2.0 / d_model) ** 0.5)
        init2 = nn.initializer.Normal(0.0, (2.0 / d_hidden) ** 0.5)
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_hidden],
            attr=nn.ParamAttr(initializer=init))
        self.b1 = self.create_parameter([num_experts, d_hidden], is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model],
            attr=nn.ParamAttr(initializer=init2))
        self.b2 = self.create_parameter([num_experts, d_model], is_bias=True)
        self.l_aux = None

    def forward(self, x):
        shape = x.shape
        flat = as_tensor_data(x).reshape(-1, self.d_model)
        g = self.gate
        cf = (g.capacity_factor()
              if self._gate_owns_capacity and hasattr(g, "capacity_factor")
              else self._default_capacity_factor)
        jitter = getattr(g, "switch_eps", 0.0) if g.training else 0.0
        rand2 = getattr(g, "random_routing", False) and g.training
        key = None
        if jitter or rand2:
            from .....framework.random import next_key
            key = next_key()

        def f(xs, gw, gb, w1, b1, w2, b2):
            y, aux = expert_parallel_moe(
                xs, gw, gb, w1, b1, w2, b2, mesh=self.mesh,
                axis=self.ep_axis, top_k=g.top_k, capacity_factor=cf,
                act=self.act, switch_jitter=jitter, routing_key=key,
                random_routing=rand2)
            return y, aux

        y, aux = _apply(f, wrap(flat), g.gate.weight, g.gate.bias,
                        self.w1, self.b1, self.w2, self.b2,
                        op_name="moe")
        self.l_aux = aux
        g.loss = aux
        from .....tensor import manipulation as M
        return M.reshape(y, list(shape))
