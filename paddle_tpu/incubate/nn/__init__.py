"""Incubating nn APIs (ref: python/paddle/incubate/nn/__init__.py)."""
from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
    FusedEcMoe, FusedLinear, FusedDropoutAdd,
    FusedBiasDropoutResidualLayerNorm, FusedMultiTransformer,
)
