"""Fused transformer layers (ref: python/paddle/incubate/nn/layer/
fused_transformer.py, fused_ec_moe.py) — Layer wrappers over the
compiler-fused functional ops."""
from __future__ import annotations

import numpy as np

from ...nn import Layer
from ...nn.initializer import XavierUniform, Constant
from ...tensor_impl import Parameter
from . import functional as F


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None, normalize_before=False,
                 need_weights=False, qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            (3, num_heads, self.head_dim, embed_dim), default_initializer=XavierUniform())
        self.qkv_bias = self.create_parameter(
            (3, num_heads, self.head_dim), is_bias=True,
            default_initializer=Constant(0.0))
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), default_initializer=XavierUniform())
        self.linear_bias = self.create_parameter(
            (embed_dim,), is_bias=True, default_initializer=Constant(0.0))
        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter(
            (embed_dim,), is_bias=True, default_initializer=Constant(0.0))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.qkv_bias, self.linear_weight,
            self.linear_bias, pre_layer_norm=self.normalize_before,
            ln_scale=self.pre_ln_scale, ln_bias=self.pre_ln_bias,
            ln_epsilon=self.epsilon, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate, training=self.training)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None, ln2_bias_attr=None,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter(
            (dim_feedforward,), is_bias=True, default_initializer=Constant(0.0))
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter(
            (d_model,), is_bias=True, default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (d_model,), default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (d_model,), is_bias=True, default_initializer=Constant(0.0))

    def forward(self, src, cache=None):
        kw = (dict(ln1_scale=self.ln_scale, ln1_bias=self.ln_bias)
              if self.normalize_before
              else dict(ln2_scale=self.ln_scale, ln2_bias=self.ln_bias))
        return F.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight, self.linear1_bias,
            self.linear2_bias, activation=self.activation,
            pre_layer_norm=self.normalize_before, training=self.training,
            ln1_epsilon=self.epsilon, ln2_epsilon=self.epsilon, **kw)


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.self_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.self_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedEcMoe(Layer):
    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu",
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.act_type = act_type
        self.gate_weight = self.create_parameter(
            (hidden_size, num_experts), default_initializer=XavierUniform())
        self.bmm_weight0 = self.create_parameter(
            (num_experts, hidden_size, inter_size),
            default_initializer=XavierUniform())
        self.bmm_bias0 = self.create_parameter(
            (num_experts, inter_size), is_bias=True,
            default_initializer=Constant(0.0))
        self.bmm_weight1 = self.create_parameter(
            (num_experts, inter_size, hidden_size),
            default_initializer=XavierUniform())
        self.bmm_bias1 = self.create_parameter(
            (num_experts, hidden_size), is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, x, gate=None):
        return F.fused_ec_moe(x, self.gate_weight, self.bmm_weight0,
                              self.bmm_bias0, self.bmm_weight1, self.bmm_bias1,
                              act_type=self.act_type)


class FusedLinear(Layer):
    """ref incubate/nn/layer/fused_linear.py — Linear whose matmul+bias
    XLA emits as one fused op."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = [out_features, in_features] if transpose_weight else \
            [in_features, out_features]
        self.weight = self.create_parameter(shape=shape, attr=weight_attr)
        self.bias = self.create_parameter(shape=[out_features],
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias,
                              transpose_weight=self._transpose)


class FusedDropoutAdd(Layer):
    """ref incubate/nn/layer/fused_dropout_add.py: dropout(x) + y fused."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return F.fused_dropout_add(x, y, p=self.p, training=self.training,
                                   mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(Layer):
    """ref incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter(
            shape=[embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[embed_dim], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(shape=[embed_dim], attr=None,
                                             is_bias=True)

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self.dropout_rate, ln_epsilon=self.epsilon,
            training=self.training)


class FusedMultiTransformer(Layer):
    """ref incubate/nn/layer/fused_transformer.py FusedMultiTransformer —
    an L-layer pre-LN transformer stack executed as one fused dispatch."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=1, epsilon=1e-5, name=None, **kw):
        super().__init__()
        assert normalize_before, "post-LN fused stack not supported"
        self.num_heads = num_heads
        self.epsilon = epsilon
        self.activation = activation
        d = embed_dim // num_heads
        mk = self.create_parameter
        self.ln_scales = [mk([embed_dim], default_initializer=Constant(1.0))
                          for _ in range(num_layers)]
        self.ln_biases = [mk([embed_dim], is_bias=True)
                          for _ in range(num_layers)]
        self.qkv_weights = [mk([3, num_heads, d, embed_dim])
                            for _ in range(num_layers)]
        self.qkv_biases = [mk([3 * embed_dim], is_bias=True)
                           for _ in range(num_layers)]
        self.linear_weights = [mk([embed_dim, embed_dim])
                               for _ in range(num_layers)]
        self.linear_biases = [mk([embed_dim], is_bias=True)
                              for _ in range(num_layers)]
        self.ffn_ln_scales = [mk([embed_dim],
                                 default_initializer=Constant(1.0))
                              for _ in range(num_layers)]
        self.ffn_ln_biases = [mk([embed_dim], is_bias=True)
                              for _ in range(num_layers)]
        self.ffn1_weights = [mk([embed_dim, dim_feedforward])
                             for _ in range(num_layers)]
        self.ffn1_biases = [mk([dim_feedforward], is_bias=True)
                            for _ in range(num_layers)]
        self.ffn2_weights = [mk([dim_feedforward, embed_dim])
                             for _ in range(num_layers)]
        self.ffn2_biases = [mk([embed_dim], is_bias=True)
                            for _ in range(num_layers)]
        for i, group in enumerate((
                self.ln_scales, self.ln_biases, self.qkv_weights,
                self.qkv_biases, self.linear_weights, self.linear_biases,
                self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
                self.ffn1_biases, self.ffn2_weights, self.ffn2_biases)):
            for li, p in enumerate(group):
                self.add_parameter(f"p{i}_{li}", p)

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        return F.fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            epsilon=self.epsilon, attn_mask=attn_mask, cache_kvs=caches,
            time_step=time_step, activation=self.activation,
            training=self.training)
