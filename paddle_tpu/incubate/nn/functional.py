"""Fused op surface (ref: python/paddle/incubate/nn/functional/*).

On TPU these are *compiler-fused*: each function is written as one traced
composition so XLA emits a single fused region (elementwise chains folded
into the adjacent matmul/attention). The reference needed hand-written CUDA
fusions (fused_dropout_add, fused_matmul_bias, fused_transformer kernels);
here the API is kept for parity while fusion is delegated to XLA — except
attention, which routes to the pallas flash kernel on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...dispatch import apply
from ...framework import random as _rng
from ...tensor_impl import as_tensor_data

__all__ = [
    "fused_dropout_add", "fused_matmul_bias", "fused_linear",
    "fused_multi_head_attention", "fused_feedforward",
    "fused_rotary_position_embedding", "fused_rms_norm", "fused_layer_norm",
    "fused_ec_moe",
]


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y in one fused region (ref: fused_dropout_add.py)."""
    if not training or p == 0.0:
        return apply(lambda a, b: a + b, x, y)
    key = _rng.next_key()

    def f(a, b):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype) + b
        return jnp.where(keep, a, 0.0).astype(a.dtype) + b

    return apply(f, x, y)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias epilogue (ref: fused_matmul_bias.py) — XLA folds the
    add into the MXU matmul epilogue."""
    def f(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b)
        return out + rest[0] if rest else out

    args = (x, y) if bias is None else (x, y, bias)
    return apply(f, *args, op_name="matmul")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    name=None):
    """RoPE applied to q/k(/v) in one fused region (ref: the gpu
    fused_rotary_position_embedding kernel). Shapes [B, S, H, D]."""

    def rope_one(t, sin_t, cos_t):
        if use_neox_rotary_style:
            # rotate_half: [-x2; x1] over the two halves
            d = t.shape[-1] // 2
            x1, x2 = t[..., :d], t[..., d:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
        else:
            # interleaved pairs
            x1 = t[..., 0::2]
            x2 = t[..., 1::2]
            rot = jnp.stack([-x2, x1], axis=-1).reshape(t.shape)
        return t * cos_t + rot * sin_t

    first = next(t for t in (q, k, v) if t is not None)
    fa = as_tensor_data(first)
    B, S, H, D = fa.shape
    if sin is None or cos is None:
        pos = jnp.arange(S)[:, None].astype(jnp.float32)
        inv = 1.0 / (10000.0 ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        ang = pos * inv[None, :]                        # [S, D/2]
        if use_neox_rotary_style:
            ang_full = jnp.concatenate([ang, ang], axis=-1)
        else:
            ang_full = jnp.repeat(ang, 2, axis=-1)
        sin_a, cos_a = jnp.sin(ang_full), jnp.cos(ang_full)
    else:
        sin_a = jnp.asarray(as_tensor_data(sin)).reshape(S, D)
        cos_a = jnp.asarray(as_tensor_data(cos)).reshape(S, D)
    if position_ids is not None:
        pid = jnp.asarray(as_tensor_data(position_ids))    # [B, S]
        sin_b = jnp.take(sin_a, pid, axis=0)[:, :, None, :]
        cos_b = jnp.take(cos_a, pid, axis=0)[:, :, None, :]
    else:
        sin_b = sin_a[None, :, None, :]
        cos_b = cos_a[None, :, None, :]

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
        else:
            outs.append(apply(
                lambda a: rope_one(a, sin_b.astype(a.dtype),
                                   cos_b.astype(a.dtype)), t))
    return tuple(outs)


def fused_multi_head_attention(x, qkv_weight, qkv_bias, linear_weight,
                               linear_bias=None, pre_layer_norm=False,
                               ln_scale=None, ln_bias=None, ln_epsilon=1e-5,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, training=True,
                               name=None, **_):
    """Fused MHA block (ref: fused_transformer.py fused_multi_head_attention):
    pre-LN → qkv proj → attention → dropout → out proj → residual (+post-LN
    when pre_layer_norm=False, matching the reference's default path).
    qkv_weight: [3, H, D, hidden]; x: [B, S, hidden]."""
    from ...framework.random import next_key

    keys = []
    if training and attn_dropout_rate > 0:
        keys.append(next_key())
    if training and dropout_rate > 0:
        keys.append(next_key())

    def ln(h):
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.var(h, axis=-1, keepdims=True)
        out = (h - mu) * jax.lax.rsqrt(var + ln_epsilon)
        return out

    def f(xv, qkvw, qkvb, lw, *rest):
        idx = 0
        lns = lnb = None
        if ln_scale is not None:
            lns = rest[idx]; idx += 1
        if ln_bias is not None:
            lnb = rest[idx]; idx += 1
        h = xv
        if pre_layer_norm:
            h = ln(h)
            if lns is not None:
                h = h * lns
            if lnb is not None:
                h = h + lnb
        B, S, E = h.shape
        n_head, head_dim = qkvw.shape[1], qkvw.shape[2]
        qkv = jnp.einsum("bse,thde->tbshd", h, qkvw)
        if qkvb is not None:
            qkv = qkv + qkvb[:, None, None]
        qh, kh, vh = qkv[0], qkv[1], qkv[2]      # [B, S, H, D]
        scale = head_dim ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
        if attn_mask is not None:
            s = s + as_tensor_data(attn_mask).astype(s.dtype)
        p = jax.nn.softmax(s, axis=-1)
        ki = 0
        if training and attn_dropout_rate > 0:
            keep = jax.random.bernoulli(keys[ki], 1 - attn_dropout_rate, p.shape)
            p = jnp.where(keep, p / (1 - attn_dropout_rate), 0.0)
            ki += 1
        ctx = jnp.einsum("bhqk,bkhd->bqhd", p, vh).reshape(B, S, n_head * head_dim)
        out = ctx @ lw
        if linear_bias is not None:
            out = out + rest[-1]
        if training and dropout_rate > 0:
            keep = jax.random.bernoulli(keys[ki], 1 - dropout_rate, out.shape)
            out = jnp.where(keep, out / (1 - dropout_rate), 0.0)
        res = xv + out
        if not pre_layer_norm:
            res = ln(res)
            if lns is not None:
                res = res * lns
            if lnb is not None:
                res = res + lnb
        return res

    args = [x, qkv_weight, qkv_bias, linear_weight]
    if ln_scale is not None:
        args.append(ln_scale)
    if ln_bias is not None:
        args.append(ln_bias)
    if linear_bias is not None:
        args.append(linear_bias)
    return apply(f, *args, op_name="attention")


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, name=None, **_):
    """Fused FFN block: [pre-LN] → linear → act → dropout → linear → dropout
    → residual [+post-LN] (ref: fused_transformer.py fused_feedforward)."""
    from ...framework.random import next_key
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]
    keys = []
    if training and dropout1_rate > 0:
        keys.append(next_key())
    if training and dropout2_rate > 0:
        keys.append(next_key())

    def ln(h, scale, bias, eps):
        mu = jnp.mean(h, axis=-1, keepdims=True)
        var = jnp.var(h, axis=-1, keepdims=True)
        h = (h - mu) * jax.lax.rsqrt(var + eps)
        if scale is not None:
            h = h * scale
        if bias is not None:
            h = h + bias
        return h

    extras = [t for t in (linear1_bias, linear2_bias, ln1_scale, ln1_bias,
                          ln2_scale, ln2_bias) if t is not None]
    flags = [linear1_bias is not None, linear2_bias is not None,
             ln1_scale is not None, ln1_bias is not None,
             ln2_scale is not None, ln2_bias is not None]

    def f(xv, w1, w2, *rest):
        it = iter(rest)
        b1, b2, s1, sb1, s2, sb2 = (next(it) if flag else None
                                    for flag in flags)
        h = ln(xv, s1, sb1, ln1_epsilon) if pre_layer_norm else xv
        h = h @ w1
        if b1 is not None:
            h = h + b1
        h = act(h)
        ki = 0
        if training and dropout1_rate > 0:
            keep = jax.random.bernoulli(keys[ki], 1 - dropout1_rate, h.shape)
            h = jnp.where(keep, h / (1 - dropout1_rate), 0.0)
            ki += 1
        h = h @ w2
        if b2 is not None:
            h = h + b2
        if training and dropout2_rate > 0:
            keep = jax.random.bernoulli(keys[ki], 1 - dropout2_rate, h.shape)
            h = jnp.where(keep, h / (1 - dropout2_rate), 0.0)
        out = xv + h
        if not pre_layer_norm:
            out = ln(out, s2, sb2, ln2_epsilon)
        return out

    return apply(f, x, linear1_weight, linear2_weight, *extras,
                 op_name="linear")


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, name=None):
    """RMSNorm in one fused region (ref: the gpu fused_rms_norm kernel)."""
    def f(a, *rest):
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        i = 0
        if norm_weight is not None:
            out = out * rest[i]; i += 1
        if norm_bias is not None:
            out = out + rest[i]
        return out

    args = [x] + [t for t in (norm_weight, norm_bias) if t is not None]
    return apply(f, *args, op_name="rms_norm")


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, name=None):
    def f(a, *rest):
        af = a.astype(jnp.float32)
        mu = jnp.mean(af, axis=-1, keepdims=True)
        var = jnp.var(af, axis=-1, keepdims=True)
        out = ((af - mu) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        i = 0
        if norm_weight is not None:
            out = out * rest[i]; i += 1
        if norm_bias is not None:
            out = out + rest[i]
        return out

    args = [x] + [t for t in (norm_weight, norm_bias) if t is not None]
    return apply(f, *args, op_name="layer_norm")


def fused_ec_moe(x, gate_weight, expert_w1, expert_b1, expert_w2, expert_b2,
                 act_type="gelu", name=None):
    """Expert-choice MoE FFN (ref: fused_ec_moe.py): softmax gate over
    experts, all experts computed batched on the MXU (dense einsum — the TPU
    way for moderate expert counts), gate-weighted sum."""
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[act_type]

    def f(xv, gw, w1, b1, w2, b2):
        gate = jax.nn.softmax(xv @ gw, axis=-1)            # [B, S, E]
        h = jnp.einsum("bsd,ndh->bsnh", xv, w1) + b1[None, None]
        h = act(h)
        out = jnp.einsum("bsnh,nhd->bsnd", h, w2) + b2[None, None]
        return jnp.einsum("bsnd,bsn->bsd", out, gate)

    return apply(f, x, gate_weight, expert_w1, expert_b1, expert_w2,
                 expert_b2, op_name="linear")


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """layer_norm(residual + dropout(x + bias)) in one program
    (ref incubate/nn/functional/fused_transformer.py). XLA fuses the chain;
    the API exists so reference code ports unchanged."""
    from ...dispatch import apply
    from ...framework.random import next_key
    import jax
    import jax.numpy as jnp
    keep = 1.0 - dropout_rate
    key = next_key() if (training and dropout_rate > 0.0) else None

    def f(xv, res, *rest):
        i = 0
        if bias is not None:
            xv = xv + rest[i]; i += 1
        if training and dropout_rate > 0.0:
            mask = jax.random.bernoulli(key, keep, xv.shape)
            xv = jnp.where(mask, xv / keep, 0.0) if mode == "upscale_in_train" \
                else jnp.where(mask, xv, 0.0)
        elif mode == "downscale_in_infer":
            xv = xv * keep
        h = res + xv
        mu = jnp.mean(h, -1, keepdims=True)
        var = jnp.var(h, -1, keepdims=True)
        out = (h - mu) * jax.lax.rsqrt(var + ln_epsilon)
        if ln_scale is not None:
            out = out * rest[i]; i += 1
        if ln_bias is not None:
            out = out + rest[i]; i += 1
        return out

    extra = [a for a in (bias, ln_scale, ln_bias) if a is not None]
    return apply(f, x, residual, *extra,
                 op_name="fused_bias_dropout_residual_layer_norm")


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, cache_kvs=None, pre_caches=None, rotary_embs=None,
        time_step=None, attn_mask=None, dropout_rate=0.0, activation="gelu",
        training=False, mode="upscale_in_train", trans_qkvw=True,
        ring_id=-1, name=None):
    """Whole-transformer-stack fusion (ref incubate/nn/functional/
    fused_transformer.py fused_multi_transformer — the CUDA inference
    megakernel). TPU-native: run the L layers under one dispatch; XLA
    fuses/pipelines. Supports the common pre-LN path with optional
    additive attn_mask; cache/rotary args of the CUDA decoder are not
    implemented (use models/generation.py for decode)."""
    if cache_kvs is not None or pre_caches is not None or \
            rotary_embs is not None or time_step is not None:
        raise NotImplementedError(
            "fused_multi_transformer cache/rotary decode args: use "
            "GPTForCausalLM.generate (models/generation.py) for decoding")
    from ...dispatch import apply
    import jax
    import jax.numpy as jnp
    L = len(qkv_weights)
    act = {"gelu": lambda v: jax.nn.gelu(v, approximate=True),
           "relu": jax.nn.relu}[activation]

    def ln(h, g, b):
        mu = jnp.mean(h, -1, keepdims=True)
        var = jnp.var(h, -1, keepdims=True)
        out = (h - mu) * jax.lax.rsqrt(var + epsilon)
        return out * g + b

    def f(xv, *flat):
        it = iter(flat)
        take = lambda: next(it)  # noqa: E731
        h = xv
        B, S, H = h.shape
        mask = None
        params = [[take() for _ in range(12)] for _ in range(L)]
        if attn_mask is not None:
            mask = take()
        for (lng, lnb, qkvw, qkvb, lw, lb, flng, flnb, w1, b1, w2, b2) \
                in params:
            inp = ln(h, lng, lnb) if pre_layer_norm else h
            # qkv weight layout [3, nh, d, H] when trans_qkvw (ref layout)
            if trans_qkvw:
                three, nh, d, _ = qkvw.shape
                qkv = jnp.einsum("bsh,endh->bsend", inp, qkvw) + \
                    qkvb.reshape(3, nh, d)
                q, k, v = (qkv[:, :, i] for i in range(3))
            else:
                nh_d = qkvw.shape[-1] // 3
                qkv = inp @ qkvw + qkvb
                q, k, v = jnp.split(qkv, 3, axis=-1)
                nh = 1  # flat heads
                q = q.reshape(B, S, -1, qkvw.shape[-1] // 3 // 1)
            scores = jnp.einsum("bqnd,bknd->bnqk", q, k) / (q.shape[-1] ** 0.5)
            if mask is not None:
                scores = scores + mask
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bnqk,bknd->bqnd", probs, v).reshape(B, S, H)
            h = h + ctx @ lw + lb
            inp2 = ln(h, flng, flnb) if pre_layer_norm else h
            h = h + act(inp2 @ w1 + b1) @ w2 + b2
        return h

    flat = []
    for i in range(L):
        flat += [ln_scales[i], ln_biases[i], qkv_weights[i], qkv_biases[i],
                 linear_weights[i], linear_biases[i], ffn_ln_scales[i],
                 ffn_ln_biases[i], ffn1_weights[i], ffn1_biases[i],
                 ffn2_weights[i], ffn2_biases[i]]
    if attn_mask is not None:
        flat.append(attn_mask)
    return apply(f, x, *flat, op_name="fused_multi_transformer")
