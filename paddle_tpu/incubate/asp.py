"""Automatic Structured Pruning — n:m sparsity (ref: python/paddle/incubate/
asp/asp.py, asp/utils.py).

n:m pattern = at least n ZEROS in every 1xm (or mxm) block, pruned by
magnitude. The reference maintains masks so cuSPARSELt can use the A100
2:4 sparse tensor cores; the TPU MXU has no structured-sparse datapath, so
here the value is model compression + training-under-mask parity: masks
are computed with vectorized jnp (grouped top-k, no python-per-row loops),
weights stay dense-with-zeros, and `decorate(optimizer)` re-applies masks
after every update so sparsity survives training.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..tensor_impl import Tensor

# param name -> mask (jnp array); and excluded param-name set
_MASKS = {}
_EXCLUDED = set()


def _rank_in_group(mat_abs):
    """rank (0 = smallest) of each element within its last-axis group."""
    order = jnp.argsort(mat_abs, axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    return ranks


def get_mask_1d(mat, n, m):
    """n:m zeros per 1xm block along rows, smallest-|.| pruned
    (ref asp/utils.py get_mask_1d — numpy row loop there; grouped
    argsort-of-argsort here, one fused XLA program)."""
    arr = jnp.asarray(mat._data if isinstance(mat, Tensor) else mat)
    rows, cols = arr.shape[-2], arr.shape[-1]
    pad = (-cols) % m
    padded = jnp.pad(arr, [(0, 0)] * (arr.ndim - 1) + [(0, pad)])
    grouped = padded.reshape(padded.shape[:-1] + ((cols + pad) // m, m))
    ranks = _rank_in_group(jnp.abs(grouped))
    mask = (ranks >= n).astype(arr.dtype)
    mask = mask.reshape(padded.shape)[..., :cols]
    return mask


def check_mask_1d(mat, n, m):
    arr = np.asarray(mat._data if isinstance(mat, Tensor) else mat)
    cols = arr.shape[-1]
    pad = (-cols) % m
    padded = np.pad(arr.reshape(-1, cols), [(0, 0), (0, pad)],
                    constant_values=0)
    grouped = padded.reshape(padded.shape[0], -1, m)
    zeros = (grouped == 0).sum(axis=-1)
    # padding counts as zeros, matching the reference's padded check
    return bool((zeros >= min(n, m)).all())


def get_mask_2d_greedy(mat, n, m):
    """mxm blocks with >= n zeros per row AND column, greedy by magnitude
    (ref asp/utils.py get_mask_2d_greedy)."""
    arr = np.asarray(
        (mat._data if isinstance(mat, Tensor) else mat), dtype=np.float32)
    rows, cols = arr.shape
    pr, pc = (-rows) % m, (-cols) % m
    padded = np.pad(np.abs(arr), [(0, pr), (0, pc)])
    mask = np.zeros_like(padded)
    keep = m - n  # values kept per row/col of each block
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = padded[bi:bi + m, bj:bj + m]
            order = np.argsort(block, axis=None)[::-1]
            row_budget = np.full(m, keep)
            col_budget = np.full(m, keep)
            bm = mask[bi:bi + m, bj:bj + m]
            for flat in order:
                r, c = divmod(int(flat), m)
                if row_budget[r] > 0 and col_budget[c] > 0:
                    bm[r, c] = 1
                    row_budget[r] -= 1
                    col_budget[c] -= 1
    return jnp.asarray(mask[:rows, :cols], dtype=jnp.asarray(arr).dtype)


def get_mask_2d_best(mat, n, m):
    """Exhaustive per-block search over valid per-row keep-patterns
    (the reference's precomputed valid-pattern bank, ref asp/utils.py
    get_mask_2d_best): maximizes kept magnitude under the 2D n:m
    constraint. Falls back to greedy for m > 4 (pattern count explodes)."""
    arr = np.asarray(
        (mat._data if isinstance(mat, Tensor) else mat), dtype=np.float32)
    if m > 4:
        return get_mask_2d_greedy(arr, n, m)
    import itertools
    keep = m - n
    row_patterns = []
    for kept_cols in itertools.combinations(range(m), keep):
        pat = np.zeros(m, np.float32)
        pat[list(kept_cols)] = 1
        row_patterns.append(pat)
    row_patterns = np.stack(row_patterns)           # [P, m]
    combos = list(itertools.product(range(len(row_patterns)), repeat=m))
    combo_masks = np.stack([row_patterns[list(c)] for c in combos])  # [C,m,m]
    valid = (combo_masks.sum(axis=1) <= keep).all(axis=1)  # col budget
    combo_masks = combo_masks[valid]
    rows, cols = arr.shape
    pr, pc = (-rows) % m, (-cols) % m
    padded = np.pad(np.abs(arr), [(0, pr), (0, pc)])
    mask = np.zeros_like(padded)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = padded[bi:bi + m, bj:bj + m]
            scores = (combo_masks * block[None]).sum(axis=(1, 2))
            mask[bi:bi + m, bj:bj + m] = combo_masks[int(np.argmax(scores))]
    return jnp.asarray(mask[:rows, :cols], dtype=jnp.asarray(arr).dtype)


def check_mask_2d(mat, n, m):
    arr = np.asarray(mat._data if isinstance(mat, Tensor) else mat)
    rows, cols = arr.shape
    pr, pc = (-rows) % m, (-cols) % m
    padded = np.pad(arr, [(0, pr), (0, pc)])
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = padded[bi:bi + m, bj:bj + m]
            if ((block != 0).sum(axis=0) > m - n).any() or \
                    ((block != 0).sum(axis=1) > m - n).any():
                return False
    return True


MaskAlgo = {"mask_1d": get_mask_1d, "mask_2d_greedy": get_mask_2d_greedy,
            "mask_2d_best": get_mask_2d_best}
CheckMethod = {"mask_1d": check_mask_1d, "mask_2d_greedy": check_mask_2d,
               "mask_2d_best": check_mask_2d}


_EXTRA_SUPPORTED = {}  # layer type/name -> optional custom pruning_func


def add_supported_layer(layer, pruning_func=None):
    """Register an extra layer type/name as prunable, optionally with a
    custom mask function (mat, n, m) -> mask
    (ref asp/supported_layer_list.py add_supported_layer). prune_model
    consults this registry for params whose dotted path contains the
    registered name."""
    if isinstance(layer, str):
        name = layer
    elif isinstance(layer, type):
        name = layer.__name__
    else:  # instance: register its class
        name = type(layer).__name__
    _EXTRA_SUPPORTED[name] = pruning_func


def calculate_density(x):
    """Fraction of non-zeros (ref asp/utils.py calculate_density)."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float((arr != 0).mean()) if arr.size else 1.0


def set_excluded_layers(param_names, main_program=None):
    """Exclude params (by name prefix) from pruning (ref asp.py:40)."""
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def _extra_match(name, type_names=None):
    """The registered extra-layer entry matching either a dotted-path
    component (string registrations) or the owning layer's class name
    (class registrations; prune_model passes the param's layer type)."""
    parts = name.split(".")
    for extra in _EXTRA_SUPPORTED:
        if extra in parts or extra.lower() in (s.lower() for s in parts):
            return extra
        if type_names and extra in type_names:
            return extra
    return None


def _prunable(name, p, type_names=None):
    # prefix (dotted-path component) or exact match — substring matching
    # would over-exclude ('fc1' must not exclude 'fc10.weight')
    if any(name == e or name.startswith(e + ".") or p.name == e
           for e in _EXCLUDED):
        return False
    if p.ndim < 2:
        return False
    return "weight" in name or name.endswith("_w") or \
        _extra_match(name, type_names) is not None


def _as_2d(arr):
    return arr.reshape(arr.shape[0], -1)


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune supported weights in place to the n:m pattern; register masks
    for maintenance under training (ref asp.py prune_model)."""
    algo = MaskAlgo[mask_algo]
    pruned = {}
    sub_types = {prefix: type(layer).__name__
                 for prefix, layer in model.named_sublayers()}
    for name, p in model.named_parameters():
        owner = name.rsplit(".", 1)[0] if "." in name else ""
        tnames = {sub_types[owner]} if owner in sub_types else set()
        if not _prunable(name, p, tnames):
            continue
        extra = _extra_match(name, tnames)
        fn = _EXTRA_SUPPORTED.get(extra) if extra else None
        w2 = _as_2d(p._data)
        mask = jnp.asarray((fn or algo)(w2, n, m), dtype=p._data.dtype)
        p._data = (w2 * mask).reshape(p._data.shape)
        if with_mask:
            # keyed by both the dotted path and the Parameter's own name
            # (Tensor has __slots__, so the mask cannot live on the object)
            _MASKS[name] = mask.reshape(p._data.shape)
            if p.name:
                _MASKS[p.name] = _MASKS[name]
        pruned[name] = float((np.asarray(mask) == 0).mean())
    return pruned


def decorate(optimizer):
    """Wrap an optimizer so masks are re-applied after every step
    (ref asp.py decorate -> OptimizerWithSparsityGuarantee)."""

    class OptimizerWithSparsityGuarantee:
        # NOT slice-equivariant even when the inner optimizer is: the mask
        # re-application keys on whole-tensor names/shapes, so the streamed
        # host-offload path (which updates [L, ...] leaves one layer slice
        # at a time) would silently skip every mask. Forcing the bulk path
        # keeps the sparsity guarantee.
        _elementwise_update = False

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, item):
            return getattr(self._inner, item)

        def step(self):
            self._inner.step()
            for p in (self._inner._parameter_list or []):
                mask = _MASKS.get(p.name)
                if mask is not None and mask.shape == p._data.shape:
                    p._data = p._data * mask

        def apply_gradients(self, params, grads, state, lr=None, **kw):
            new_params, new_state = self._inner.apply_gradients(
                params, grads, state, lr, **kw)
            for name, mask in _MASKS.items():
                # shape guard: _MASKS is process-global, and a same-named
                # param of a DIFFERENT (un-pruned) model must not be masked
                if name in new_params and \
                        new_params[name].shape == mask.shape:
                    new_params[name] = new_params[name] * mask
            return new_params, new_state

    return OptimizerWithSparsityGuarantee(optimizer)
