"""Incubating optimizers (ref: python/paddle/incubate/optimizer/lookahead.py,
modelaverage.py): wrappers that keep slow/averaged copies of the fast
optimizer's parameters."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..optimizer.optimizer import Optimizer
from ..tensor_impl import Tensor

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k fast steps, then slow weights interpolate toward fast weights:
    slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        super().__init__(learning_rate=inner_optimizer._learning_rate,
                         parameters=inner_optimizer._parameter_list)
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._slow = {}

    def step(self):
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k != 0:
            return
        for p in self._parameter_list or []:
            key = id(p)
            slow = self._slow.get(key)
            if slow is None:
                slow = p._data
            slow = slow + self.alpha * (p._data - slow)
            self._slow[key] = slow
            # distinct buffer for the live weights: the inner optimizer's
            # fused update DONATES p._data, which must not invalidate the
            # retained slow copy
            p._data = jnp.copy(slow)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_count
        return sd


class ModelAverage(Optimizer):
    """Maintains a running average of parameters; `apply()` swaps it in for
    evaluation, `restore()` swaps the live weights back."""

    def __init__(self, average_window_rate, parameters=None, min_average_window=10000,
                 max_average_window=10000, name=None):
        super().__init__(parameters=list(parameters) if parameters else [])
        self.avg_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._sum = {id(p): jnp.zeros_like(p._data) for p in self._parameter_list}
        self._cnt = 0
        self._old_sum = {}
        self._old_cnt = 0
        self._backup = None

    def step(self):
        # sliding-window approximation matching the reference's accumulator
        # swap: when the live window fills, it becomes the "old" block and a
        # fresh accumulator starts; apply() averages over both blocks.
        if self._cnt >= self.max_average_window:
            self._old_sum = dict(self._sum)
            self._old_cnt = self._cnt
            self._sum = {id(p): jnp.zeros_like(p._data)
                         for p in self._parameter_list}
            self._cnt = 0
        for p in self._parameter_list:
            self._sum[id(p)] = self._sum[id(p)] + p._data
        self._cnt += 1

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._parameter_list}
        total = self._cnt + self._old_cnt
        for p in self._parameter_list:
            if total:
                acc = self._sum[id(p)] + self._old_sum.get(id(p), 0)
                p._data = (acc / total).astype(p._data.dtype)
        if not need_restore:
            self._backup = None

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._parameter_list:
            p._data = self._backup[id(p)]
        self._backup = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
