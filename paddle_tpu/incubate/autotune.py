"""Auto-tuning config (ref: python/paddle/incubate/autotune.py set_config).

The reference toggles exhaustive cuDNN kernel search, NCHW/NHWC layout
rewriting, and DataLoader num_workers search. The TPU/XLA analogs:

- kernel: XLA's own autotuner picks MXU tilings during compilation; what a
  user controls is the persistent compilation cache that makes those
  choices pay off across processes. kernel.enable wires it.
- layout: XLA performs layout assignment in-graph (there is no user-visible
  NCHW/NHWC rewrite to make); the setting is recorded and surfaced via
  get_config() so callers can branch on it.
- dataloader: enable lets paddle_tpu.io.DataLoader pick a prefetch worker
  count instead of the user-provided one.
"""
from __future__ import annotations

import json
import warnings

_CONFIG = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}


def set_config(config=None):
    """Accepts a dict, a json-file path, or None (enable everything) —
    ref incubate/autotune.py:24."""
    if config is None:
        for section in _CONFIG.values():
            section["enable"] = True
        _apply()
        return
    if isinstance(config, str):
        try:
            with open(config) as f:
                config = json.load(f)
        except Exception as e:  # noqa: BLE001 — parity: warn, keep defaults
            warnings.warn(f"Load config error: {e}; "
                          "use default configuration for auto-tuning.")
            config = {}
    for key, val in (config or {}).items():
        if key not in _CONFIG:
            warnings.warn(f"Unknown autotune section {key!r}")
            continue
        if not isinstance(val, dict):
            warnings.warn(f"autotune section {key!r} must be a dict")
            continue
        _CONFIG[key].update(val)
    _apply()


def get_config():
    return {k: dict(v) for k, v in _CONFIG.items()}


def _apply():
    if _CONFIG["kernel"]["enable"]:
        import os
        import jax
        cache = os.path.join(os.getcwd(), ".jax_cache")
        try:
            jax.config.update("jax_compilation_cache_dir", cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              1.0)
        except Exception:  # noqa: BLE001 — already configured is fine
            pass


def dataloader_num_workers(requested):
    """Called by io.DataLoader: returns the tuned worker count when
    dataloader autotune is on, else the requested one."""
    if not _CONFIG["dataloader"]["enable"]:
        return requested
    import os
    return max(requested, min(4, max(1, (os.cpu_count() or 2) // 2)))
