"""paddle_tpu.incubate — experimental subsystems (ref: python/paddle/incubate).

Currently: step-tagged async checkpointing (``incubate.checkpoint``).
"""
from . import checkpoint  # noqa: F401
