"""paddle_tpu.incubate — experimental subsystems (ref: python/paddle/incubate).

- ``checkpoint``: step-tagged async checkpointing
- ``nn``: fused transformer/MoE blocks + ``nn.functional`` fused op surface
  (XLA compiler fusion; pallas flash attention on TPU)
- ``autograd``: functional jvp/vjp/Jacobian/Hessian (jax transforms)
- ``optimizer``: LookAhead, ModelAverage wrappers
- ``asp``: n:m automatic structured pruning + mask maintenance
- ``autotune``: kernel/layout/dataloader auto-tuning config
"""
from . import checkpoint  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
