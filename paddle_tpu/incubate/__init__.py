"""paddle_tpu.incubate — experimental subsystems (ref: python/paddle/incubate).

- ``checkpoint``: step-tagged async checkpointing
- ``nn``: fused transformer/MoE blocks + ``nn.functional`` fused op surface
  (XLA compiler fusion; pallas flash attention on TPU)
- ``autograd``: functional jvp/vjp/Jacobian/Hessian (jax transforms)
- ``optimizer``: LookAhead, ModelAverage wrappers
- ``asp``: n:m automatic structured pruning + mask maintenance
- ``autotune``: kernel/layout/dataloader auto-tuning config
"""
from . import checkpoint  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

# ---- functional surface (ref python/paddle/incubate/__init__.py __all__) ----
import jax as _jax
import jax.numpy as _jnp

from ..tensor_impl import Tensor as _T, as_tensor_data as _d
from ..dispatch import apply as _apply


def _segment(reduce_fn, fill=0.0):
    def op(data, segment_ids, name=None):
        ids = _jnp.asarray(_d(segment_ids), _jnp.int32)
        n = int(_jax.device_get(ids.max())) + 1 if ids.size else 0

        def f(x):
            return reduce_fn(x, ids, n)
        return _apply(f, data, op_name="segment_op")
    return op


segment_sum = _segment(
    lambda x, ids, n: _jax.ops.segment_sum(x, ids, num_segments=n))
segment_max = _segment(
    lambda x, ids, n: _jax.ops.segment_max(x, ids, num_segments=n))
segment_min = _segment(
    lambda x, ids, n: _jax.ops.segment_min(x, ids, num_segments=n))


def segment_mean(data, segment_ids, name=None):
    ids = _jnp.asarray(_d(segment_ids), _jnp.int32)
    n = int(_jax.device_get(ids.max())) + 1 if ids.size else 0

    def f(x):
        s = _jax.ops.segment_sum(x, ids, num_segments=n)
        c = _jax.ops.segment_sum(_jnp.ones_like(ids, x.dtype), ids,
                                 num_segments=n)
        return s / _jnp.maximum(c, 1).reshape((n,) + (1,) * (x.ndim - 1))
    return _apply(f, data, op_name="segment_mean")


def identity_loss(x, reduction="none"):
    """ref incubate/nn/loss.py identity_loss (IPU-era reduction wrapper)."""
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)

    def f(v):
        if red == "sum":
            return v.sum()
        if red == "mean":
            return v.mean()
        return v
    return _apply(f, x, op_name="identity_loss")


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused program (ref incubate/operators/
    softmax_mask_fuse.py — a CUDA fusion; XLA fuses this composition)."""
    def f(xv, mv):
        return _jax.nn.softmax(xv.astype(_jnp.float32) +
                               mv.astype(_jnp.float32),
                               axis=-1).astype(xv.dtype)
    return _apply(f, x, mask, op_name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax (ref softmax_mask_fuse_upper_triangle: mask is
    the upper triangle, queries attend to <= their position)."""
    def f(xv):
        S1, S2 = xv.shape[-2], xv.shape[-1]
        mask = _jnp.tril(_jnp.ones((S1, S2), bool))
        logits = _jnp.where(mask, xv.astype(_jnp.float32), -_jnp.inf)
        return _jax.nn.softmax(logits, axis=-1).astype(xv.dtype)
    return _apply(f, x, op_name="softmax_mask_fuse_upper_triangle")


# graph ops: the geometric namespace owns the TPU-native implementations
# (ref incubate graph_* were promoted to paddle.geometric upstream)
from ..geometric import (  # noqa: E402
    send_u_recv as graph_send_recv,
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling: chained sample_neighbors over hops
    (ref incubate/operators/graph_khop_sampler.py)."""
    import numpy as _np
    from ..geometric import sample_neighbors as _sn
    nodes = _np.asarray(_jax.device_get(_d(input_nodes))).reshape(-1)
    all_rows, all_cols = [], []
    seen = set(int(v) for v in nodes)
    frontier = nodes  # hop k samples ONLY newly discovered nodes
    for k in sample_sizes:
        if frontier.size == 0:
            break
        out_neighbors, out_count = _sn(row, colptr, frontier, sample_size=k)
        nb = _np.asarray(_jax.device_get(_d(out_neighbors)))
        cnt = _np.asarray(_jax.device_get(_d(out_count)))
        dst = _np.repeat(frontier[:len(cnt)], cnt)
        all_rows.append(nb)
        all_cols.append(dst)
        fresh = [int(v) for v in _np.unique(nb) if int(v) not in seen]
        seen.update(fresh)
        frontier = _np.asarray(fresh, nodes.dtype)
    edge_src = _np.concatenate(all_rows) if all_rows else _np.zeros(0, _np.int64)
    edge_dst = _np.concatenate(all_cols) if all_cols else _np.zeros(0, _np.int64)
    # seeds first, then neighbors in first-seen order (the reindex_graph
    # contract: input nodes map to [0, len(input_nodes)))
    remap = {}
    for v in nodes:
        remap.setdefault(int(v), len(remap))
    for v in edge_src:
        remap.setdefault(int(v), len(remap))
    sample_index = _np.asarray(list(remap), _np.int64)
    reindex_src = _np.asarray([remap[int(v)] for v in edge_src], _np.int64)
    reindex_dst = _np.asarray([remap[int(v)] for v in edge_dst], _np.int64)
    out = (_T(_jnp.asarray(edge_src)), _T(_jnp.asarray(edge_dst)),
           _T(_jnp.asarray(sample_index)),
           (_T(_jnp.asarray(reindex_src)), _T(_jnp.asarray(reindex_dst))))
    return out
