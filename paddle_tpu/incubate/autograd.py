"""Functional autodiff (ref: python/paddle/incubate/autograd/__init__.py).

jvp/vjp/Jacobian/Hessian map directly onto jax transforms — forward-mode is
native here (the reference needed a primitive-rewrite pass, enable_prim).
"""
from __future__ import annotations

import jax

from ..autograd import jvp, vjp, jacobian as _jacobian, hessian as _hessian
from ..tensor_impl import Tensor, as_tensor_data

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim", "disable_prim",
           "forward_grad", "grad"]


class Jacobian:
    """Lazy Jacobian J[func](xs) with [i, j] indexing (ref: functional.py)."""

    def __init__(self, func, xs, is_batched=False):
        self._mat = _jacobian(func, xs)
        self.is_batched = is_batched

    def __getitem__(self, idx):
        return self._mat[idx]

    @property
    def shape(self):
        return self._mat.shape

    def numpy(self):
        return self._mat.numpy() if isinstance(self._mat, Tensor) else self._mat


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        self._mat = _hessian(func, xs)
        self.is_batched = is_batched

    def __getitem__(self, idx):
        return self._mat[idx]

    @property
    def shape(self):
        return self._mat.shape


def forward_grad(func, xs, v=None):
    """Forward-mode gradient: jax.jvp is the primitive here."""
    return jvp(func, xs, v)


def grad(func, xs, v=None):
    """Reverse-mode gradient; `v` seeds the cotangent (ones when omitted)."""
    _, grads = vjp(func, xs, v)
    return grads


def enable_prim():
    """The reference lowers to primitive ops for higher-order AD; jax traces
    primitives natively, so this is a no-op kept for API parity."""


def disable_prim():
    """No-op (see enable_prim)."""
