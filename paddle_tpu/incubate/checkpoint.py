"""Step-tagged checkpoint manager with async save and exact resume
(ref: python/paddle/incubate/checkpoint/auto_checkpoint.py, framework/io.py).

TPU-first design notes:
  * the device→host snapshot happens synchronously (device buffers may be
    donated by the very next jitted step), but the disk write runs on a
    background thread so training overlaps with IO — the reference gets the
    same overlap from its C++ checkpoint workers
  * a checkpoint directory is made visible atomically (write to ``.tmp``,
    ``os.rename``) so a crash mid-write can never produce a half checkpoint
    that ``latest_step`` would pick up
  * retention: ``keep_last_n`` prunes old steps after each successful save
"""
from __future__ import annotations

import os
import shutil
import threading

import jax
import numpy as np

from ..framework import io as fio
from ..tensor_impl import Tensor

_STEP_PREFIX = "step_"


class CheckpointManager:
    def __init__(self, directory, keep_last_n=3, async_save=True):
        self.directory = os.fspath(directory)
        self.keep_last_n = int(keep_last_n)
        self.async_save = bool(async_save)
        os.makedirs(self.directory, exist_ok=True)
        self._thread = None
        self._error = None
        self._lock = threading.Lock()

    # -- querying ----------------------------------------------------------
    def all_steps(self):
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX) and not name.endswith(".tmp"):
                try:
                    steps.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _step_dir(self, step):
        return os.path.join(self.directory, f"{_STEP_PREFIX}{step}")

    # -- saving ------------------------------------------------------------
    def save(self, step, state, blocking=None):
        """Checkpoint ``state`` (a pytree of Tensors/arrays/scalars) at ``step``.

        Snapshots to host immediately; writes to disk on a background thread
        unless ``blocking`` (or the manager was created with
        ``async_save=False``).
        """
        self.wait()  # one in-flight save at a time; surfaces prior IO errors

        def _snap(a):
            if hasattr(a, "_data"):  # Tensor: host copy, keep wrapper type
                t = Tensor(np.asarray(jax.device_get(a._data)),
                           stop_gradient=a.stop_gradient)
                t.name = a.name
                return t
            if isinstance(a, jax.Array):
                return np.asarray(jax.device_get(a))
            return a

        snap = jax.tree_util.tree_map(_snap, state)
        if blocking is None:
            blocking = not self.async_save
        if blocking:
            self._write(int(step), snap)
        else:
            self._thread = threading.Thread(
                target=self._write_guarded, args=(int(step), snap), daemon=True)
            self._thread.start()

    def _write_guarded(self, step, snap):
        try:
            self._write(step, snap)
        except BaseException as e:  # surfaced on next save()/wait()
            with self._lock:
                self._error = e

    def _write(self, step, snap):
        final = self._step_dir(step)
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        fio.save(snap, os.path.join(tmp, "state.pdckpt"))
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)  # atomic publish
        self._prune()

    def _prune(self):
        steps = self.all_steps()
        for s in steps[: max(0, len(steps) - self.keep_last_n)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def wait(self):
        """Block until any in-flight async save has finished; re-raise IO errors."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        with self._lock:
            if self._error is not None:
                e, self._error = self._error, None
                raise e

    # -- restoring ---------------------------------------------------------
    def restore(self, step=None):
        """Load the checkpoint at ``step`` (default: latest). None if empty."""
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        path = os.path.join(self._step_dir(step), "state.pdckpt")
        return fio.load(path)
